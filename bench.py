"""Driver benchmark gate: ALL FIVE BASELINE.json configs, headline first.

Config #4 (100k-variable scale-free graph coloring, MaxSum, one TPU chip) is
the headline: north star (BASELINE.md) is solving in < 10 s wall at
CPU-matching solution quality — the reference (pyDCOP, pure python threads +
dict arithmetic) cannot run this size at all; its per-cycle cost is python
enumeration of joint assignments per factor (reference maxsum.py:382-447).
The other four configs (DSA coloring-50, 1k MaxSum, 10k Ising MGM-2, DPOP
meeting scheduling) ride in the same watchdog child so every end-of-round TPU
window captures the full BASELINE table (round-2 verdict item 2).

Prints one JSON line PER CONFIG — {"metric", "value", "unit", ...} — with the
config-4 line FIRST for driver compatibility.

Robustness (round-1 verdict item 2): the axon TPU backend can hang
INDEFINITELY at init (down relay) or even mid-run, so all benchmarks execute
in a watchdog subprocess with a hard timeout.  Lines are flushed per config:
a mid-run hang costs only the remaining configs.  Any config missing from the
TPU child's output is retried on a pinned-CPU subprocess, so five parsable
JSON lines (with ``device`` and, on fallback, ``error`` fields) are emitted
no matter what state the relay is in.
"""

import json
import os
import subprocess
import sys

# run order: headline config first, then the rest of the BASELINE table
CONFIG_ORDER = ["4", "1", "2", "3", "5"]


def _metric_names():
    # bench_all owns the metric names; import is deferred so the parent
    # process never imports jax-adjacent modules
    import bench_all

    return bench_all.METRIC_NAMES

# TPU attempt: backend init (~30s when healthy) + one jit compile per config
# (~20-40s each) + the solves themselves.  CPU fallback: no init cost but
# slower solves.  Env-overridable for driver/test tuning.
TPU_BUDGET_S = float(os.environ.get("BENCH_TPU_BUDGET_S", 540.0))
CPU_BUDGET_S = float(os.environ.get("BENCH_CPU_BUDGET_S", 420.0))


def _child(config_keys, pin_cpu_first: bool) -> None:
    from pydcop_tpu.utils.platform import enable_compilation_cache, pin_cpu

    if pin_cpu_first:
        pin_cpu()
    else:
        # persistent XLA executable cache (accelerator path only): a fresh
        # compile of a fused solve program costs minutes through the TPU
        # relay (remote compile), so the five configs only fit the
        # watchdog budget when warm
        enable_compilation_cache()
    import bench_all

    for key in config_keys:
        print(json.dumps(bench_all.run_config(key)))
        sys.stdout.flush()


def _run_child(flag, budget_s: float, configs):
    """Run this script in child mode; return ({config: record}, error)."""
    argv = [sys.executable, __file__, flag] + list(configs)
    try:
        out = subprocess.run(
            argv, capture_output=True, text=True, timeout=budget_s
        )
        stdout, stderr, rc = out.stdout, out.stderr, out.returncode
        error = None
    except subprocess.TimeoutExpired as te:
        def _s(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else (b or "")
        stdout, stderr, rc = _s(te.stdout), _s(te.stderr), None
        error = f"benchmark timed out after {budget_s:.0f}s ({flag})"
    records = {}
    for line in stdout.strip().splitlines():
        try:
            record = json.loads(line)
        except ValueError:
            continue
        if isinstance(record, dict) and "metric" in record:
            records[record.get("config")] = record
    if error is None and not records:
        tail = (stderr or "").strip().splitlines()
        error = tail[-1][:300] if tail else f"child rc={rc}"
    return records, error


def main() -> None:
    records, error = _run_child("--child", TPU_BUDGET_S, CONFIG_ORDER)
    missing = [
        k for k in CONFIG_ORDER
        if k not in records or records[k].get("value") is None
    ]
    if missing:
        fallback, fb_error = _run_child("--child-cpu", CPU_BUDGET_S, missing)
        for k in missing:
            record = fallback.get(k)
            if record is not None and record.get("value") is not None:
                if error:
                    record["error"] = error
                records[k] = record
            elif k not in records:
                records[k] = {
                    "metric": _metric_names()[k],
                    "value": None,
                    "unit": "s",
                    "vs_baseline": None,
                    "device": None,
                    "config": k,
                    "error": f"{error}; cpu fallback: {fb_error}",
                }
    # headline extras: vs_baseline = speedup vs the 10 s north-star budget
    head = records.get("4")
    if head and head.get("value"):
        head["vs_baseline"] = round(10.0 / head["value"], 2)
        head.setdefault("n_vars", 100_000)
    for k in CONFIG_ORDER:
        print(json.dumps(records[k]))
    sys.stdout.flush()


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child(sys.argv[sys.argv.index("--child") + 1 :], pin_cpu_first=False)
    elif "--child-cpu" in sys.argv:
        _child(
            sys.argv[sys.argv.index("--child-cpu") + 1 :], pin_cpu_first=True
        )
    else:
        main()
