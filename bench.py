"""Headline benchmark: BASELINE.json config #4.

100k-variable scale-free graph coloring, MaxSum, on one TPU chip.  North
star (BASELINE.md): solve in < 10 s wall at CPU-matching solution quality —
the reference (pyDCOP, pure python threads + dict arithmetic) cannot run this
size at all; its per-cycle cost is dominated by python enumeration of joint
assignments per factor (reference maxsum.py:382-447).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup vs the 10 s north-star budget.
"""

import json
import time

N_VARS = 100_000
N_COLORS = 3
M_EDGE = 2
N_CYCLES = 30
SEED = 7
# 0.7 beats the 0.5 default on this loopy instance (18.8k vs 19.8k final
# cost at identical wall time; measured in BASELINE.md round-1 runs)
DAMPING = 0.7


def main() -> None:
    import jax

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    compiled = generate_coloring_arrays(
        N_VARS, N_COLORS, graph="scalefree", m_edge=M_EDGE, seed=SEED
    )
    dev = to_device(compiled)

    params = {"damping": DAMPING}
    # warm-up: trace + compile (n_cycles is a static scan length, so the
    # warm-up must use the same value for the executable to be reused)
    maxsum.solve(compiled, params, n_cycles=N_CYCLES, seed=SEED, dev=dev)

    t0 = time.perf_counter()
    # solve() returns host floats, so it is already synchronized
    result = maxsum.solve(compiled, params, n_cycles=N_CYCLES, seed=SEED, dev=dev)
    wall = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "metric": "maxsum_100k_scalefree_wall",
                "value": round(wall, 4),
                "unit": "s",
                "vs_baseline": round(10.0 / wall, 2),
                "cost": result.cost,
                "violations": result.violations,
                "cycles": N_CYCLES,
                "n_vars": N_VARS,
                "device": str(jax.devices()[0].platform),
            }
        )
    )


if __name__ == "__main__":
    main()
