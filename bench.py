"""Headline benchmark: BASELINE.json config #4.

100k-variable scale-free graph coloring, MaxSum, on one TPU chip.  North
star (BASELINE.md): solve in < 10 s wall at CPU-matching solution quality —
the reference (pyDCOP, pure python threads + dict arithmetic) cannot run this
size at all; its per-cycle cost is dominated by python enumeration of joint
assignments per factor (reference maxsum.py:382-447).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup vs the 10 s north-star budget.

Robustness (VERDICT.md round-1 item 2): the axon TPU backend can hang
INDEFINITELY at init (down relay) or even mid-run, so the whole benchmark —
not just a probe — executes in a watchdog subprocess with a hard timeout.
On failure/timeout the parent retries on a pinned-CPU subprocess, so a
parsable JSON line (with ``device`` and, on fallback, ``error`` fields) is
emitted no matter what state the relay is in.
"""

import json
import subprocess
import sys
import time

N_VARS = 100_000
N_COLORS = 3
M_EDGE = 2
N_CYCLES = 30
SEED = 7
# 0.7 beats the 0.5 default on this loopy instance (18.8k vs 19.8k final
# cost at identical wall time; measured in BASELINE.md round-1 runs)
DAMPING = 0.7

# TPU attempt: backend init (~30s when healthy) + first jit compile
# (~20-40s) + two 30-cycle solves.  CPU fallback measured at ~120s total.
TPU_BUDGET_S = 360.0
CPU_BUDGET_S = 300.0


def run_benchmark() -> dict:
    import jax

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    compiled = generate_coloring_arrays(
        N_VARS, N_COLORS, graph="scalefree", m_edge=M_EDGE, seed=SEED
    )
    dev = to_device(compiled)

    params = {"damping": DAMPING}
    # warm-up: trace + compile (n_cycles is a static scan length, so the
    # warm-up must use the same value for the executable to be reused)
    maxsum.solve(compiled, params, n_cycles=N_CYCLES, seed=SEED, dev=dev)

    t0 = time.perf_counter()
    # solve() returns host floats, so it is already synchronized
    result = maxsum.solve(compiled, params, n_cycles=N_CYCLES, seed=SEED, dev=dev)
    wall = time.perf_counter() - t0

    return {
        "metric": "maxsum_100k_scalefree_wall",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(10.0 / wall, 2),
        "cost": result.cost,
        "violations": result.violations,
        "cycles": N_CYCLES,
        "n_vars": N_VARS,
        "device": str(jax.devices()[0].platform),
    }


def _child(pin_cpu_first: bool) -> None:
    if pin_cpu_first:
        from pydcop_tpu.utils.platform import pin_cpu

        pin_cpu()
    print(json.dumps(run_benchmark()))
    sys.stdout.flush()


def _run_child(flag: str, budget_s: float):
    """Run this script in child mode; return (record, error)."""
    try:
        out = subprocess.run(
            [sys.executable, __file__, flag],
            capture_output=True,
            text=True,
            timeout=budget_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"benchmark timed out after {budget_s:.0f}s ({flag})"
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            record = json.loads(line)
        except ValueError:
            continue
        if isinstance(record, dict) and "metric" in record:
            return record, None
    tail = (out.stderr or "").strip().splitlines()
    return None, (tail[-1][:300] if tail else f"child rc={out.returncode}")


def main() -> None:
    record, error = _run_child("--child", TPU_BUDGET_S)
    if record is None:
        fallback, fb_error = _run_child("--child-cpu", CPU_BUDGET_S)
        if fallback is not None:
            fallback["error"] = error
            record = fallback
        else:
            record = {
                "metric": "maxsum_100k_scalefree_wall",
                "value": None,
                "unit": "s",
                "vs_baseline": None,
                "cycles": N_CYCLES,
                "n_vars": N_VARS,
                "device": None,
                "error": f"{error}; cpu fallback: {fb_error}",
            }
    print(json.dumps(record))
    sys.stdout.flush()


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child(pin_cpu_first=False)
    elif "--child-cpu" in sys.argv:
        _child(pin_cpu_first=True)
    else:
        main()
