"""Driver benchmark gate: ALL FIVE BASELINE.json configs, headline first.

Config #4 (100k-variable scale-free graph coloring, MaxSum, one TPU chip) is
the headline: north star (BASELINE.md) is solving in < 10 s wall at
CPU-matching solution quality — the reference (pyDCOP, pure python threads +
dict arithmetic) cannot run this size at all; its per-cycle cost is python
enumeration of joint assignments per factor (reference maxsum.py:382-447).
The other four configs (DSA coloring-50, 1k MaxSum, 10k Ising MGM-2, DPOP
meeting scheduling) ride in the same watchdog child so every end-of-round TPU
window captures the full BASELINE table (round-2 verdict item 2).

Prints one JSON line PER CONFIG — {"metric", "value", "unit", ...} — with the
config-4 line FIRST for driver compatibility.

Robustness (round-1 verdict item 2): the axon TPU backend can hang
INDEFINITELY at init (down relay) or even mid-run, so all benchmarks execute
in a watchdog subprocess with a hard timeout.  Lines are flushed per config:
a mid-run hang costs only the remaining configs.  Any config missing from the
TPU child's output is retried on a pinned-CPU subprocess, so five parsable
JSON lines (with ``device`` and, on fallback, ``error`` fields) are emitted
no matter what state the relay is in.

Probe economics (graftprof round): a fully-failed probe window is cached
on disk for ``BENCH_PROBE_CACHE_TTL_S`` (default 3600 s), so repeated
bench.py invocations in one driver run pay the dead-relay window once,
not per metric; ``PYDCOP_TPU_SKIP_PROBE=1`` skips the probe entirely and
trusts the watchdog budget (see ``_persistent_probe``).
"""

import json
import os
import subprocess
import sys

# run order: headline config first, then the rest of the BASELINE table,
# then the graftserve throughput config (ROADMAP item 3) and the
# graftpart partition-quality config (ROADMAP item 2) — config 9 must be
# in the driver order so the BENCH trajectory accumulates baselines for
# bench_gate to regress partition quality against
CONFIG_ORDER = ["4", "1", "2", "3", "5", "8", "9"]


def _metric_names():
    # bench_all owns the metric names; import is deferred so the parent
    # process never imports jax-adjacent modules
    import bench_all

    return bench_all.METRIC_NAMES

# TPU attempt: backend init (~30s when healthy) + one jit compile per
# config (~5s warm via the persistent .jax_cache, up to minutes each when
# the relay's remote-compile path is cold — hence the generous budget;
# records stream out per config, so even a budget overrun or the driver
# killing this process keeps every config finished so far).  CPU fallback:
# no init cost but slower solves.  Env-overridable for driver/test tuning.
TPU_BUDGET_S = float(os.environ.get("BENCH_TPU_BUDGET_S", 900.0))
CPU_BUDGET_S = float(os.environ.get("BENCH_CPU_BUDGET_S", 420.0))


def _child(config_keys, pin_cpu_first: bool) -> None:
    from pydcop_tpu.utils.platform import enable_compilation_cache, pin_cpu

    if pin_cpu_first:
        pin_cpu()
    else:
        # persistent XLA executable cache (accelerator path only): a fresh
        # compile of a fused solve program costs minutes through the TPU
        # relay (remote compile), so the five configs only fit the
        # watchdog budget when warm
        enable_compilation_cache()
    import bench_all

    # graftcap tee: with PYDCOP_TPU_CAPTURE_DIR set, every record ALSO
    # lands in a capture bundle as it streams (manifest re-written per
    # config, so a watchdog kill leaves a valid partial bundle).  The
    # one-command front door is `pydcop_tpu capture`; this hook is for
    # driver windows that still run bench.py.
    capture_dir = os.environ.get("PYDCOP_TPU_CAPTURE_DIR")
    manifest = None
    if capture_dir:
        from pydcop_tpu.telemetry import perfdiff

        manifest = _load_or_new_manifest(perfdiff, capture_dir)

    for key in config_keys:
        record = bench_all.run_config(key)
        if manifest is not None:
            from pydcop_tpu.telemetry import perfdiff

            perfdiff.append_record(capture_dir, record, manifest)
        print(json.dumps(record))
        sys.stdout.flush()


def _load_or_new_manifest(perfdiff, capture_dir: str):
    """Resume the bundle manifest if one exists, else start one with
    this child's provenance."""
    path = os.path.join(capture_dir, "manifest.json")
    if os.path.exists(path):
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            pass
    import time as _time

    import jax

    return perfdiff.new_manifest(
        environment=perfdiff.capture_environment(extra={
            "device": str(jax.devices()[0].platform),
            "jax": jax.__version__,
            "source": "bench.py child (PYDCOP_TPU_CAPTURE_DIR tee)",
        }),
        created=_time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    )


def _run_child(flag, budget_s: float, configs, emit):
    """Run this script in child mode, STREAMING records as they arrive.

    Each completed config's JSON line is passed to ``emit`` the moment the
    child flushes it — if the driver (or an operator) kills this parent
    mid-run, every finished config is already on stdout.  Returns
    ({config: record}, error)."""
    import threading
    import time as _time

    argv = [sys.executable, __file__, flag] + list(configs)
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    records = {}
    stderr_buf = []

    def _drain_stderr():
        for line in proc.stderr:
            stderr_buf.append(line)

    t_err = threading.Thread(target=_drain_stderr, daemon=True)
    t_err.start()

    lines = []

    def _drain_stdout():
        for line in proc.stdout:
            lines.append(line)

    t_out = threading.Thread(target=_drain_stdout, daemon=True)
    t_out.start()

    seen = [0]

    def _drain():
        # publish any newly-arrived complete records
        while seen[0] < len(lines):
            line = lines[seen[0]]
            seen[0] += 1
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if isinstance(record, dict) and "metric" in record:
                records[record.get("config")] = record
                emit(record)

    deadline = _time.monotonic() + budget_s
    error = None
    while True:
        _drain()
        if proc.poll() is not None and not t_out.is_alive():
            _drain()  # records written between the drain and the checks
            break
        if _time.monotonic() >= deadline:
            proc.kill()
            error = f"benchmark timed out after {budget_s:.0f}s ({flag})"
            t_out.join(timeout=5)
            _drain()
            break
        _time.sleep(0.2)
    if error is None and not records:
        t_err.join(timeout=5)
        tail = "".join(stderr_buf).strip().splitlines()
        error = (
            tail[-1][:300] if tail else f"child rc={proc.returncode}"
        )
    return records, error


def _load_probe_module():
    """Load the platform helpers standalone: importing the pydcop_tpu
    package here would pull jax into this watchdog parent, whose whole job
    is to never touch a backend that might hang."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_bench_platform_probe",
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "pydcop_tpu", "utils", "platform.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _probe_cache_path() -> str:
    """On-disk cache for a FAILED probe verdict, shared by every bench.py
    invocation of one driver run (the driver re-invokes bench.py per
    metric: BENCH_r05.json shows the identical 13-attempt / 1530 s dead
    window re-paid per invocation, dominating bench wall time)."""
    import tempfile

    override = os.environ.get("PYDCOP_TPU_PROBE_CACHE")
    if override:
        return override
    return os.path.join(
        tempfile.gettempdir(),
        f"pydcop_tpu_bench_probe_{os.getuid()}.json",
    )


def _read_cached_probe_failure():
    """The cached failed verdict when still fresh, else None.  Only
    failures are cached: a healthy probe answers in seconds, and trusting
    a stale healthy verdict would commit the accelerator child against a
    relay that may have died since."""
    import json as _json
    import time as _time

    ttl_s = float(os.environ.get("BENCH_PROBE_CACHE_TTL_S", 3600.0))
    try:
        with open(_probe_cache_path()) as f:
            rec = _json.load(f)
        age = _time.time() - float(rec.get("ts", 0))
        if rec.get("platform") is None and 0 <= age < ttl_s:
            rec["age_s"] = age
            return rec
    except (OSError, ValueError, TypeError):
        pass
    return None


def _write_probe_cache(platform, error, attempts, window_s) -> None:
    """Persist a failed verdict; clear the cache on a healthy answer."""
    import json as _json
    import time as _time

    path = _probe_cache_path()
    try:
        if platform is not None:
            if os.path.exists(path):
                os.remove(path)
            return
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(_json.dumps({
                "ts": _time.time(),
                "platform": None,
                "error": error,
                "attempts": len(attempts),
                "window_s": round(window_s, 1),
            }))
        os.replace(tmp, path)
    except OSError:
        pass  # cache is an optimization; never fail the bench over it


def _persistent_probe(probe_module):
    """Poll the accelerator probe until it answers or the window closes.

    A single 90 s probe sample against a relay whose outages flip between
    healthy, fast-error and indefinite-hang states decided three rounds of
    perf narrative (round-4 verdict item 1).  This keeps sampling — one
    attempt roughly every ``BENCH_PROBE_RETRY_S`` across a
    ``BENCH_PROBE_TOTAL_S`` window — before surrendering the headline slot
    to the CPU fallback, and returns the full attempt log so the emitted
    JSON proves how hard the gate fought (``probe_attempts`` /
    ``probe_window_s`` fields).  A healthy first answer (including a
    CPU-only machine's host backend) exits immediately, so the window cost
    is only ever paid against a dead relay — and only ONCE per run: a
    fully-failed window is cached on disk (``BENCH_PROBE_CACHE_TTL_S``,
    default 3600 s) so the driver's next bench.py invocation skips
    straight to the CPU fallback instead of re-burning the window.

    ``PYDCOP_TPU_SKIP_PROBE=1`` skips the probe entirely and commits the
    accelerator child directly (its hard watchdog budget still bounds a
    hung relay) — for operators who already know the backend is healthy.
    """
    import time as _time

    if os.environ.get("PYDCOP_TPU_SKIP_PROBE") == "1":
        print(
            "[bench] probe skipped (PYDCOP_TPU_SKIP_PROBE=1); running "
            "the accelerator child under its watchdog budget",
            file=sys.stderr,
        )
        # the operator is asserting the backend is healthy: a stale
        # cached failure must not keep short-circuiting later plain
        # invocations to the CPU fallback for the rest of its TTL
        _write_probe_cache("skipped", None, [], 0.0)
        return "skipped", None, [], 0.0
    cached = _read_cached_probe_failure()
    if cached is not None:
        error = (
            f"{cached.get('error')} [cached verdict, "
            f"{cached['age_s']:.0f}s old — probe window not re-run; "
            f"set PYDCOP_TPU_SKIP_PROBE=1 or delete "
            f"{_probe_cache_path()} to override]"
        )
        print(f"[bench] {error}", file=sys.stderr)
        return None, error, [], 0.0
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 90.0))
    total_s = float(os.environ.get("BENCH_PROBE_TOTAL_S", 1500.0))
    retry_s = float(os.environ.get("BENCH_PROBE_RETRY_S", 120.0))
    attempts = []
    start = _time.monotonic()
    platform, error = None, None
    while True:
        t0 = _time.monotonic()
        platform, _, error = probe_module.probe_backend(
            timeout_s=timeout_s, retries=0
        )
        attempts.append({
            "t_s": round(t0 - start, 1),
            "platform": platform,
            "error": error,
        })
        print(
            f"[bench] probe attempt {len(attempts)}"
            f" (t={t0 - start:.0f}s): platform={platform} error={error}",
            file=sys.stderr,
        )
        sys.stderr.flush()
        if platform is not None:
            break
        elapsed = _time.monotonic() - start
        if elapsed >= total_s:
            break
        # keep the attempt cadence near retry_s whether the probe failed
        # fast or burned its whole timeout hanging
        attempt_cost = _time.monotonic() - t0
        _time.sleep(min(max(retry_s - attempt_cost, 0.0),
                        max(total_s - elapsed, 0.0)))
    window_s = _time.monotonic() - start
    if platform is None and error is not None and len(attempts) > 1:
        error = (
            f"{error} ({len(attempts)} attempts over {window_s:.0f}s)"
        )
    _write_probe_cache(platform, error, attempts, window_s)
    return platform, error, attempts, window_s


def main(_probe_module=None) -> None:
    emitted = set()
    held = []  # successful records waiting for the headline line
    probe_log = []  # filled by the persistent probe before any emit
    probe_window = [0.0]

    def _print(record):
        emitted.add(record.get("config"))
        # the attempt log rides the JSON so a CPU-only BENCH file proves
        # whether the relay was down for the whole window or just sampled
        # at a bad moment
        record["probe_attempts"] = len(probe_log)
        record["probe_window_s"] = round(probe_window[0], 1)
        if record.get("config") == "4":
            record["probe_log"] = probe_log
        print(json.dumps(record))
        sys.stdout.flush()

    def emit(record):
        # one line per config, streamed on completion — but the headline
        # (config 4) line must lead the output for the driver, so when it
        # errors on the accelerator child, later configs are held until
        # its CPU-fallback line resolves
        key = record.get("config")
        if key in emitted or record.get("value") is None:
            return
        if key == "4":
            _print(record)
            for r in held:
                _print(r)
            held.clear()
        elif "4" in emitted:
            _print(record)
        elif key not in {r.get("config") for r in held}:
            held.append(record)

    def _fallback_emit(record):
        # keep a failed record's own error; annotate successes with the
        # accelerator-side reason they were re-run
        if error and record.get("value") is not None:
            record["error"] = error
        emit(record)

    # a hung accelerator runtime would burn the whole TPU budget before the
    # CPU fallback even starts — probe first (subprocess, hard timeout),
    # PERSISTENTLY (the relay's outages are intermittent; see
    # _persistent_probe), and skip the accelerator child only when the
    # whole probe window fails
    platform, probe_err, attempts, window_s = _persistent_probe(
        _probe_module or _load_probe_module()
    )
    probe_log.extend(attempts)
    probe_window[0] = window_s
    if platform is not None:
        # healthy backend — accelerator or a CPU-only machine's host
        # backend; the child records report the device either way
        records, error = _run_child(
            "--child", TPU_BUDGET_S, CONFIG_ORDER, emit
        )
    else:
        records = {}
        error = f"accelerator probe failed: {probe_err}"
    done = emitted | {r.get("config") for r in held}
    missing = [k for k in CONFIG_ORDER if k not in done]
    if missing:
        fallback, fb_error = _run_child(
            "--child-cpu", CPU_BUDGET_S, missing, _fallback_emit,
        )
    else:
        fallback, fb_error = {}, None
    held_keys = {r.get("config") for r in held}
    for k in CONFIG_ORDER:
        if k in emitted or k in held_keys:
            continue
        # both children failed this config: preserve each side's reason
        tpu_err = records.get(k, {}).get("error") or error or "no record"
        cpu_err = fallback.get(k, {}).get("error") or fb_error or "no record"
        rec = {
            "metric": _metric_names()[k],
            "value": None,
            "unit": "s",
            "vs_baseline": None,
            "device": None,
            "config": k,
            "error": f"accelerator: {tpu_err}; cpu fallback: {cpu_err}",
        }
        if k == "4":
            # even a failed headline leads the output
            _print(rec)
            for r in held:
                _print(r)
            held.clear()
        else:
            held.append(rec)
    # a failed headline never resolved: release anything still held
    for r in held:
        _print(r)


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child(sys.argv[sys.argv.index("--child") + 1 :], pin_cpu_first=False)
    elif "--child-cpu" in sys.argv:
        _child(
            sys.argv[sys.argv.index("--child-cpu") + 1 :], pin_cpu_first=True
        )
    else:
        main()
