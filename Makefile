# Test / QA entry points (role parity with the reference's Makefile:3-22).

all: test

test:
	python -m pytest tests/ -q

test_fast:
	python -m pytest tests/ -q -m "not slow"

test_cli:
	python -m pytest tests/test_cli.py -q

doctest:
	python -m pytest --doctest-modules pydcop_tpu/ -q

mypy:
	mypy --ignore-missing-imports pydcop_tpu

# graftlint static analysis against the checked-in baseline: any NEW
# finding (lock discipline, JAX tracing hazard, protocol mismatch,
# graftflow array shape/dtype/batch-axis flow, graftproto conversation
# verification — reply gaps, stale-epoch guards, blocking handlers,
# unsent messages — graftperf performance discipline: host syncs /
# per-iteration dispatches / recompile hazards / donation misses /
# eager hot kernels) fails the build; pre-existing findings are tracked
# in the baseline (currently EMPTY — keep it that way).  Warm reruns
# hit the content-hash finding cache in $PYDCOP_TPU_STATE_DIR
# (default .bench_state/); pass --no-cache to bypass it.
# tests/test_analysis.py re-runs this same check inside the tier-1
# pytest flow, so `make test_fast` fails on new findings too.
lint:
	python -m pydcop_tpu.analysis --baseline tools/graftlint_baseline.json --quiet pydcop_tpu/

# same ratchet, machine-readable: SARIF 2.1.0 (rule metadata from the
# --explain docs) for CI annotation / editor ingestion; written into
# the state dir so the artifact never lands in the tree
lint-sarif:
	@mkdir -p $${PYDCOP_TPU_STATE_DIR:-.bench_state}
	python -m pydcop_tpu.analysis --baseline tools/graftlint_baseline.json --format sarif pydcop_tpu/ > $${PYDCOP_TPU_STATE_DIR:-.bench_state}/graftlint.sarif
	@echo "wrote $${PYDCOP_TPU_STATE_DIR:-.bench_state}/graftlint.sarif"

# re-ratchet after intentionally accepting or fixing findings
lint-baseline:
	python -m pydcop_tpu.analysis --baseline tools/graftlint_baseline.json --write-baseline pydcop_tpu/

# telemetry smoke: a tiny CPU solve with tracing + metrics on, then schema
# validation of the emitted Chrome trace (fails on format drift)
trace-smoke:
	JAX_PLATFORMS=cpu python -m pydcop_tpu --output /tmp/pydcop_smoke_result.json \
		solve -a dsa -n 5 \
		--trace-out /tmp/pydcop_smoke_trace.json \
		--metrics-out /tmp/pydcop_smoke_metrics.json \
		tests/instances/graph_coloring.yaml
	python -m pydcop_tpu telemetry --validate /tmp/pydcop_smoke_trace.json

# graftwatch smoke: a thread-mode run with tracing + the live /metrics
# surface on — fails unless >= 95% of message send flows pair with a
# delivery flow event AND at least one /metrics scrape lands mid-run
# (docs/observability.md)
watch-smoke:
	JAX_PLATFORMS=cpu python tools/watch_smoke.py

# chaos smoke: a tiny seeded kill-and-repair scenario through the real
# runtime — fails unless the run finishes, converges to the fault-free
# assignment and dead-letters nothing (docs/chaos.md)
chaos-smoke:
	JAX_PLATFORMS=cpu python -m pydcop_tpu --output /tmp/pydcop_chaos_smoke.json \
		chaos -a dsa -n 10 --seed 0 -k 1 \
		--fault-schedule tests/instances/chaos_kill_repair.yaml \
		--max-dead-letters 0 --check-convergence \
		tests/instances/graph_coloring.yaml

# graftucs resilience smoke: distributed replication negotiation under
# fire — k=2 negotiated quietly, then a re-replication round with a
# seeded kill of a replica host MID-negotiation; fails unless the repair
# converges onto a negotiated replica, the solve matches the fault-free
# assignment and nothing dead-letters (docs/resilience.md)
resilience-smoke:
	JAX_PLATFORMS=cpu python tools/resilience_smoke.py

# graftpulse smoke: seeded solver-health gate — a DSA run forced to
# stall (frustrated clique, zero noise) and one that converges must be
# diagnosed stalled-plateau / converged, and a chaos-killed run must
# leave a postmortem.json the postmortem verb renders
# (docs/observability.md, graftpulse)
pulse-smoke:
	JAX_PLATFORMS=cpu python tools/pulse_smoke.py

# pallas kernel smoke: interpret-mode bit-agreement of the Pallas ELL
# min-plus kernel against the pure-jnp ELL step (kernel-level AND full
# solve), plus the per-op roofline attribution bar (>= 90% of the fused
# step attributed) and the jnp-vs-pallas micro-benchmark record
# (docs/observability.md, graftkern)
kernel-smoke:
	JAX_PLATFORMS=cpu python tools/kernel_smoke.py

# graftdur durability smoke: the kill-and-resume soak — a chaos
# kill_process schedule kills a checkpointing 1500-var MaxSum solve
# mid-run (abrupt os._exit, direct mode) and a thread-runtime run too;
# both must RESUME from the checkpoints the corpse left to the
# bit-identical fault-free assignment, with zero dead letters
# (docs/durability.md)
durability-smoke:
	JAX_PLATFORMS=cpu python tools/durability_smoke.py

# graftserve smoke: a real `pydcop_tpu serve` process, >= 8 concurrent
# tenants over HTTP across 2 shape buckets — fails unless every tenant's
# cost is EXACTLY its sequential-solve cost (the batch bit-identity
# contract end-to-end), /status carries per-tenant pulse rows, and
# shutdown drains with zero dead letters (docs/serving.md)
serve-smoke:
	JAX_PLATFORMS=cpu python tools/serve_smoke.py

# graftslo smoke: SLOs + burn-rate alerting over the serving layer — a
# quiet serve run must trip zero alerts with the full request-trace /
# exemplar surface live, and a seeded chaos-delay schedule must trip the
# fast-burn alert with the IDENTICAL transition sequence across two
# runs, leaving a renderable postmortem naming the violated objective
# (docs/observability.md, graftslo)
slo-smoke:
	JAX_PLATFORMS=cpu python tools/slo_smoke.py

# graftfleet smoke: 3 real serve workers federated by a `pydcop_tpu
# fleet` process, traffic at every worker, one worker SIGKILLed mid-run
# — federated counters must stay monotone across every scrape,
# fleet.worker_up must flip for exactly the victim (its series dropped
# past --stale-after, meta-series kept), the fleet SLO must keep
# burning over the survivors with the alert naming a worst worker, and
# `watch --fleet` must render the worker table
# (docs/observability.md, graftfleet)
fleet-smoke:
	JAX_PLATFORMS=cpu python tools/fleet_smoke.py

# graftha soak: HA serve fleet under chaos — a placement A/B (affinity
# vs round-robin, 300 serially-driven tenants each) must show affinity
# beating round-robin on measured queue p99, then a 3-worker affinity
# fleet takes a chaos SIGKILL of the bucket-owning worker mid-solve and
# a same-port restart: zero lost tenants (every survivor bit-identical
# to an in-process solve), the router's fast-burn alert must trip (low
# shed with Retry-After, normal deferred) AND resolve, federated
# counters stay monotone through the kill, the census returns to 3/3,
# and the router drains clean with failover/from-scratch accounting
# (docs/serving.md "HA fleet", graftha)
fleet-soak:
	JAX_PLATFORMS=cpu python tools/fleet_soak.py

# graftpart smoke: the multilevel partitioning subsystem end to end —
# a 10k scale-free instance must drop cross_shard_incidence >= 35%
# below the BFS baseline, an 8-virtual-device sharded MaxSum solve of
# the partitioned layout must cost EXACTLY the single-device solve, the
# analytic ICI model must match the measured mesh.ell_cross_frac gauge,
# and the 100k config-4 graph's BFS-vs-multilevel incidence is printed
# side by side (docs/partitioning.md)
partition-smoke:
	JAX_PLATFORMS=cpu python tools/partition_smoke.py

# graftprof smoke: one thread-mode solve through the CLI with the full
# profiling surface on (--profile-out/--dump-hlo/--trace-out/--metrics-out)
# — fails unless compile.* metrics are present, >= 90% of device window
# time is attributed to named algorithm phases, and HLO text was dumped
# (docs/observability.md, graftprof)
prof-smoke:
	JAX_PLATFORMS=cpu python tools/prof_smoke.py

# graftperf smoke: the six-pass lint cold AND warm (the warm run must
# serve the identical clean verdict from the finding cache), plus the
# perf budget ratchet — analysis/budget.py re-derives the per-engine-
# path dispatch/readback site census and diffs it against the pins in
# tools/perf_budget.json; an engine edit that adds a dispatch or
# readback site fails here until the manifest is consciously re-pinned
# (docs/graftlint.md, graftperf; runtime half in tests/test_analysis_perf.py)
perf-lint-smoke:
	python tools/perf_lint_smoke.py

# graftcap smoke: a small CPU capture bundle (configs 2+5, everything
# forced on minus the profiler trace), whose self-diff must report zero
# significant deltas and whose diff against a perturbed copy must rank
# the inflated op first (tools/capture_smoke.py; docs/observability.md)
capture-smoke:
	JAX_PLATFORMS=cpu python tools/capture_smoke.py

# graftmem smoke: the device-memory observability gate — the analytic
# model must land within ±20% of XLA's own memory_analysis() peak on a
# real CPU solve, an explicit 1 KiB limit must turn a solve into a loud
# MemoryBudgetExceeded naming the breach (never an XLA crash), the live
# plane must COUNT its degradation on stats-less backends while still
# publishing the limit gauge, and the memplan verb must render the
# capacity answers through the real CLI (docs/observability.md, graftmem)
mem-smoke:
	JAX_PLATFORMS=cpu python tools/mem_smoke.py

bench:
	python bench.py

# perf regression gate: fresh bench_all records (CPU-pinned, so the gate
# runs whatever state the TPU relay is in) vs the BENCH_*.json trajectory
# with per-metric noise tolerances — exits non-zero with a table on
# regression (tools/bench_gate.py; docs/observability.md)
bench-gate:
	@f=$$(mktemp -t pydcop_bench_fresh.XXXXXX); \
	JAX_PLATFORMS=cpu python bench_all.py --cpu > $$f || { rm -f $$f; exit 1; }; \
	python tools/bench_gate.py --fresh $$f; rc=$$?; rm -f $$f; exit $$rc

coverage:
	coverage run --source=pydcop_tpu -m pytest tests/ -q
	coverage report
