# Test / QA entry points (role parity with the reference's Makefile:3-22).

all: test

test:
	python -m pytest tests/ -q

test_fast:
	python -m pytest tests/ -q -m "not slow"

test_cli:
	python -m pytest tests/test_cli.py -q

doctest:
	python -m pytest --doctest-modules pydcop_tpu/ -q

mypy:
	mypy --ignore-missing-imports pydcop_tpu

bench:
	python bench.py

coverage:
	coverage run --source=pydcop_tpu -m pytest tests/ -q
	coverage report
