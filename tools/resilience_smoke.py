"""graftucs resilience smoke (``make resilience-smoke``): the negotiation
protocol under fire, end to end through the real thread-mode runtime.

Scenario: a 5-agent ring replicates at k=2 via the distributed
visit/accept/refuse negotiation (quiet phase — every computation ends up
with two NEGOTIATED replica hosts), then a re-replication round runs under
chaos: ucs message delays stretch the negotiation while a seeded kill
takes out ``a1`` — a replica host for most computations — mid-round.

Pass criteria (exit 0):
  * the replication barrier completes on the survivors (no hang),
  * the victim's computation is repaired onto one of ITS phase-1
    negotiated replica hosts (repair converges onto a negotiated replica),
  * every surviving computation still has >= 1 replica on a survivor,
  * the solve finishes and matches the fault-free assignment bit-for-bit,
  * zero dead letters.

Wired next to chaos-smoke in the Makefile (docs/resilience.md).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.api import solve_result
from pydcop_tpu.chaos import ChaosController, FaultSchedule, KillEvent, MessageRule
from pydcop_tpu.dcop import DCOP, AgentDef, Domain, Variable, constraint_from_str
from pydcop_tpu.infrastructure.run import run_local_thread_dcop

N_AGENTS = 5
VICTIM = "a1"
SEED = 0


def build_dcop():
    d = Domain("colors", "", ["R", "G", "B"])
    vs = [Variable(f"v{i}", d) for i in range(N_AGENTS)]
    dcop = DCOP("resilience_smoke")
    for i in range(N_AGENTS):
        a, b = vs[i], vs[(i + 1) % N_AGENTS]
        dcop += constraint_from_str(
            f"c{i}", f"10 if {a.name} == {b.name} else 0", [a, b]
        )
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=100) for i in range(N_AGENTS)]
    )
    return dcop, vs


def main() -> int:
    dcop, vs = build_dcop()
    algo = AlgorithmDef.build_with_default_param("dsa", mode=dcop.objective)
    baseline = solve_result(dcop, algo, n_cycles=30, seed=SEED)["assignment"]

    schedule = FaultSchedule(
        seed=7,
        events=[
            KillEvent(VICTIM, at=0.3),
            # stretch the negotiation so the kill lands mid-round
            MessageRule(
                action="delay", pattern="ucs_visit", p=0.6, seconds=0.08
            ),
            MessageRule(
                action="delay", pattern="ucs_accept", p=0.3, seconds=0.05
            ),
        ],
    )
    controller = ChaosController(schedule)
    orchestrator = run_local_thread_dcop(
        "dsa", dcop, "oneagent", n_cycles=30, seed=SEED, chaos=controller
    )
    failures = []
    report = {}
    try:
        for agent in orchestrator._local_agents.values():
            agent.replication.visit_timeout = 1.0
        orchestrator.deploy_computations()

        # phase 1 — quiet negotiation: k=2 replicas everywhere
        levels = orchestrator.start_replication(k=2, timeout=30)
        negotiated = {
            c: list(h) for c, h in orchestrator.mgt.replica_hosts.items()
        }
        report["phase1_levels"] = levels
        if any(n < 2 for n in levels.values()):
            failures.append(f"phase-1 replication below k=2: {levels}")
        victim_comps = list(
            orchestrator.distribution.computations_hosted(VICTIM)
        )
        report["victim_comps"] = victim_comps

        # phase 2 — re-replication under chaos; the timeline is started
        # NOW so the seeded kill fires mid-negotiation
        controller.start(orchestrator.kill_agent)
        orchestrator.start_replication(k=2, timeout=40)
        controller.wait_timeline(timeout=30)

        # the victim's computations repaired onto phase-1 NEGOTIATED hosts
        for comp in victim_comps:
            new_host = orchestrator.distribution.agent_for(comp)
            report.setdefault("repaired", {})[comp] = new_host
            if new_host == VICTIM:
                failures.append(f"{comp} still hosted on the corpse")
            elif new_host not in negotiated.get(comp, []):
                failures.append(
                    f"{comp} repaired onto {new_host}, not one of its "
                    f"negotiated replicas {negotiated.get(comp)}"
                )

        orchestrator.run(timeout=60)
        report["status"] = orchestrator.status
        if orchestrator.status != "FINISHED":
            failures.append(f"run status {orchestrator.status}")

        assignment, _ = orchestrator.current_solution()
        report["converged"] = assignment == baseline
        if assignment != baseline:
            failures.append("assignment differs from the fault-free solve")

        # every surviving computation keeps >= 1 replica on a survivor
        survivors = set(orchestrator.mgt.registered_agents)
        for comp, hosts in orchestrator.mgt.replica_hosts.items():
            live = [h for h in hosts if h in survivors]
            if comp not in victim_comps and not live:
                failures.append(f"{comp} lost all replicas: {hosts}")
        report["final_levels"] = dict(orchestrator.mgt.replication_levels)

        dead = orchestrator.dead_letter_total()
        report["dead_letters"] = dead
        if dead:
            failures.append(f"{dead} dead letters")
    finally:
        orchestrator.stop_agents(timeout=5)
        orchestrator.stop()

    report["failures"] = failures
    print(json.dumps(report, indent=2, sort_keys=True))
    if failures:
        print(f"resilience-smoke: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print("resilience-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
