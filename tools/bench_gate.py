"""bench_gate: perf regression gate over the BENCH_*.json trajectory.

``make bench-gate`` compares a FRESH set of bench records (one JSON line
per config, as emitted by ``bench_all.py`` / ``bench.py``) against the
repo's historical ``BENCH_*.json`` driver records, per metric and per
device, with noise tolerances — and exits non-zero with a readable table
when a metric regressed.  This is the judge every later perf PR is
measured with: "the headline got slower" becomes a CI failure instead of
a narrative.

Comparison rules (see ``compare``):

- the baseline of a metric is the MEDIAN of its historical values on the
  SAME device (a CPU-fallback record never gates a TPU run or vice
  versa); metrics with no same-device history are reported as
  ``no-baseline`` and never fail the gate.
- machine-drift normalization (default on): the BENCH trajectory may
  have been recorded on different hardware than the gate runs on, so
  every comparison is scaled by the MEDIAN fresh/baseline ratio across
  metrics ON THE SAME DEVICE (a mixed TPU + CPU-fallback fresh set gets
  one scale per device — one device's drift never excuses the other's
  regression) — a uniform 8x container slowdown cancels out, while one
  metric regressing beyond its device's fleet still fails.  Blind spot,
  by construction: a change that slows EVERY config by the same factor
  is normalized away; on fixed hardware pass ``--no-normalize`` to
  close it.  Normalization needs >= 3 comparable metrics per device
  (the median of two is a mean a single regression can drag), else that
  device's scale is 1.
- wall regression: fresh > baseline * scale * (1 + tol) AND the excess
  exceeds the absolute slack (microbenchmark configs finish in
  milliseconds, where relative noise is meaningless).  Improvements
  always pass.
- quality regression: the solve's reported cost worsened past the cost
  tolerance relative to the same-device median cost (bit-stability
  changes are expected to update the trajectory deliberately, not slip
  through a wall-time-only gate).
- a fresh record with ``value: null`` (config errored) is reported and,
  by default, only warned about — environments legitimately differ in
  which configs can run (e.g. a missing reference instance file);
  ``--strict`` turns those into failures.  A record that instead
  declares itself ``skipped`` (config 1 emits one when the
  ``/root/reference`` checkout is absent) is reported as SKIPPED and
  never fails the gate, strict or not — the gate can go green on
  containers without the reference checkout.
- known-drift waivers (``tools/bench_known_drift.json``, or
  ``--known-drift FILE``): a per-metric allowlist for DOCUMENTED
  container drift that single-metric normalization cannot absorb
  (config 3 mgm2's pair-phase kernel on this container, CHANGES
  PR-12/13).  A waived metric that would have regressed is printed as
  ``WAIVED`` with the waiver's reason and does not fail the gate; a
  waived metric that passes on its own is reported ``ok`` as usual.
  Waived metrics are also excluded from the drift-scale ratio pool, so
  a waived outlier cannot inflate the expectation every other metric is
  judged against.

History files may be either the driver wrapper shape
(``{"tail": "<stdout lines>", ...}`` — possibly head-truncated, so
unparsable lines are skipped) or raw bench output (one JSON object per
line).  Stdlib-only: the gate must run on a machine that cannot import
jax at all.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "load_records",
    "load_history",
    "load_waivers",
    "compare",
    "format_table",
    "attribution_blocks",
    "format_attribution_blocks",
    "main",
]

DEFAULT_TOL = 0.35  # relative wall-time tolerance (bench noise band)
DEFAULT_COST_TOL = 0.10  # relative solution-quality tolerance
DEFAULT_ABS_SLACK_S = 0.10  # absolute wall slack for millisecond configs


def _parse_lines(text: str) -> List[Dict[str, Any]]:
    """Bench records out of a blob of output lines: JSON objects with a
    ``metric`` field; anything else (stderr noise, truncated head lines
    of a driver ``tail``) is skipped."""
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            records.append(rec)
    return records


def load_records(path: str) -> List[Dict[str, Any]]:
    """Records from one file: driver wrapper (``tail`` field) or raw
    JSON-lines bench output."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            payload = json.loads(text)
        except ValueError:
            payload = None
        if isinstance(payload, dict) and "tail" in payload:
            return _parse_lines(str(payload.get("tail") or ""))
    return _parse_lines(text)


def load_history(paths: List[str]) -> Dict[str, List[Dict[str, Any]]]:
    """metric name -> historical records (each stamped with its source
    file under ``_file``), in the given path order."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for path in paths:
        try:
            records = load_records(path)
        except OSError:
            continue
        for rec in records:
            rec["_file"] = os.path.basename(path)
            out.setdefault(rec["metric"], []).append(rec)
    return out


def load_waivers(path: Optional[str]) -> Dict[str, str]:
    """metric name -> reason from a known-drift waiver file
    (``{"version": 1, "waivers": [{"metric": ..., "reason": ...}]}``).
    A missing or unreadable file is an empty waiver set — the gate must
    stay runnable on checkouts without one."""
    if not path:
        return {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return {}
    out: Dict[str, str] = {}
    for w in payload.get("waivers", []) if isinstance(payload, dict) else []:
        if isinstance(w, dict) and w.get("metric"):
            out[str(w["metric"])] = str(w.get("reason", "known drift"))
    return out


def _same_device(
    records: List[Dict[str, Any]], device: Optional[str]
) -> List[Dict[str, Any]]:
    return [
        r for r in records
        if r.get("value") is not None and r.get("device") == device
    ]


def compare(
    fresh: List[Dict[str, Any]],
    history: Dict[str, List[Dict[str, Any]]],
    tol: float = DEFAULT_TOL,
    cost_tol: float = DEFAULT_COST_TOL,
    abs_slack_s: float = DEFAULT_ABS_SLACK_S,
    metric_tols: Optional[Dict[str, float]] = None,
    strict: bool = False,
    normalize: bool = True,
    waivers: Optional[Dict[str, str]] = None,
) -> Tuple[List[Dict[str, Any]], int, Dict[Any, float]]:
    """(rows, n_regressions, scales) for a fresh record set vs the
    trajectory; ``scales`` maps device -> the machine-drift factor
    applied (absent when normalization is off or under-determined for
    that device, in which case 1.0 was used).  ``waivers`` maps metric
    names to documented known-drift reasons: a would-be regression on a
    waived metric becomes status ``WAIVED`` instead of failing."""
    metric_tols = metric_tols or {}
    waivers = waivers or {}
    # pass 1: same-device baselines per fresh record, and PER-DEVICE
    # drift scales — bench.py legitimately emits mixed-device sets (TPU
    # records + CPU-fallback records), and one blended median would let
    # a real single-device regression hide behind the other device's
    # drift
    hists: Dict[int, List[Dict[str, Any]]] = {}
    baselines: Dict[int, Optional[float]] = {}
    ratios_by_device: Dict[Any, List[float]] = {}
    for i, rec in enumerate(fresh):
        hist = _same_device(
            history.get(rec.get("metric"), []), rec.get("device")
        )
        hists[i] = hist
        base = (
            statistics.median(r["value"] for r in hist) if hist else None
        )
        baselines[i] = base
        # a waived metric's ratio is the very drift being waived —
        # letting it into the pool would inflate every other metric's
        # drift-corrected expectation on this device
        if base and rec.get("value") and rec.get("metric") not in waivers:
            ratios_by_device.setdefault(rec.get("device"), []).append(
                rec["value"] / base
            )
    # >= 3 ratios per device: the median of two is their mean, which a
    # single regressed metric drags far enough to absorb half its own
    # regression — with three or more, the median stays on the healthy
    # metrics' drift
    scales: Dict[Any, float] = {
        device: statistics.median(ratios)
        for device, ratios in ratios_by_device.items()
        if normalize and len(ratios) >= 3
    }
    rows: List[Dict[str, Any]] = []
    regressions = 0
    for i, rec in enumerate(fresh):
        metric = rec.get("metric")
        device = rec.get("device")
        m_tol = metric_tols.get(metric, tol)
        hist = hists[i]
        row = {
            "metric": metric,
            "device": device,
            "n_hist": len(hist),
            "baseline_s": None,
            "fresh_s": rec.get("value"),
            "delta_pct": None,
            "tol_pct": round(100.0 * m_tol, 1),
            "status": "ok",
            "note": "",
        }
        if rec.get("value") is None:
            if rec.get("skipped"):
                # the config declared itself inapplicable in this
                # environment (e.g. config 1 without the /root/reference
                # checkout) — a SKIP, never a failure, strict or not
                row["status"] = "SKIPPED"
                row["note"] = str(rec["skipped"])[:80]
                rows.append(row)
                continue
            # strict only bites when the SAME device has history — the
            # rule every other comparison uses (a config that succeeded
            # here would have been no-baseline and could never fail)
            if strict and hist:
                if metric in waivers:
                    row["status"] = "WAIVED"
                    row["note"] = f"known drift: {waivers[metric]}"
                else:
                    row["status"] = "REGRESSION"
                    row["note"] = f"no fresh value: {rec.get('error', '?')}"
                    regressions += 1
            else:
                row["status"] = "skipped"
                row["note"] = (
                    f"config errored: {str(rec.get('error', '?'))[:80]}"
                )
            rows.append(row)
            continue
        base = baselines[i]
        if base is None:
            row["status"] = "no-baseline"
            row["note"] = f"no prior {device} records for this metric"
            rows.append(row)
            continue
        row["baseline_s"] = round(base, 4)
        # drift-corrected expectation: what this metric "should" cost on
        # THIS machine, given how this device's whole fleet shifted
        scale = scales.get(device, 1.0)
        expected = base * scale
        delta = rec["value"] - expected
        row["delta_pct"] = (
            round(100.0 * delta / expected, 1) if expected else None
        )
        if delta > expected * m_tol and delta > abs_slack_s:
            detail = (
                f"wall {rec['value']:.4g}s vs median {base:.4g}s"
                f" x drift {scale:.2f} = {expected:.4g}s expected "
                f"(+{100.0 * delta / expected:.0f}% > "
                f"{100.0 * m_tol:.0f}% and +{delta:.3g}s > "
                f"{abs_slack_s:g}s slack)"
            )
            if metric in waivers:
                row["status"] = "WAIVED"
                row["note"] = f"known drift: {waivers[metric]}"
            else:
                row["status"] = "REGRESSION"
                row["note"] = detail
                regressions += 1
            rows.append(row)
            continue
        # solution-quality gate: same-device median cost, tolerance band
        # scaled by |cost| (costs may be negative for max problems);
        # deliberately NOT drift-normalized — quality does not depend on
        # machine speed
        costs = [
            r["cost"] for r in hist
            if isinstance(r.get("cost"), (int, float))
        ]
        if costs and isinstance(rec.get("cost"), (int, float)):
            cbase = statistics.median(costs)
            worse = rec["cost"] - cbase  # minimization form in records
            band = cost_tol * max(abs(cbase), 1e-9)
            if worse > band:
                if metric in waivers:
                    row["status"] = "WAIVED"
                    row["note"] = f"known drift: {waivers[metric]}"
                else:
                    row["status"] = "REGRESSION"
                    row["note"] = (
                        f"cost {rec['cost']:.6g} vs median {cbase:.6g} "
                        f"(worse by {worse:.4g} > {band:.4g} band)"
                    )
                    regressions += 1
        rows.append(row)
    return rows, regressions, scales


def format_table(rows: List[Dict[str, Any]]) -> str:
    header = (
        f"{'metric':<30} {'device':<7} {'n':>2} {'baseline':>10} "
        f"{'fresh':>10} {'Δ%':>7} {'tol%':>6} {'status':<12} note"
    )
    lines = [header, "-" * len(header)]
    for r in rows:
        base = f"{r['baseline_s']:.4f}" if r["baseline_s"] is not None else "-"
        fresh = f"{r['fresh_s']:.4f}" if r["fresh_s"] is not None else "-"
        delta = (
            f"{r['delta_pct']:+.1f}" if r["delta_pct"] is not None else "-"
        )
        lines.append(
            f"{str(r['metric']):<30} {str(r['device']):<7} "
            f"{r['n_hist']:>2} {base:>10} {fresh:>10} {delta:>7} "
            f"{r['tol_pct']:>6} {r['status']:<12} {r['note']}"
        )
    return "\n".join(lines)


_PERFDIFF_CACHE: List[Any] = []


def _load_perfdiff():
    """telemetry/perfdiff.py loaded standalone (importlib, not the
    package import chain): perfdiff is stdlib-only by contract, and this
    gate must keep running on hosts that cannot import jax — or even
    pydcop_tpu.  Returns None when the module is absent/broken."""
    if _PERFDIFF_CACHE:
        return _PERFDIFF_CACHE[0]
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "pydcop_tpu", "telemetry", "perfdiff.py",
    )
    try:
        spec = importlib.util.spec_from_file_location(
            "_bench_gate_perfdiff", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
    except Exception:  # noqa: BLE001 - attribution is best-effort
        module = None
    _PERFDIFF_CACHE.append(module)
    return module


def attribution_blocks(
    rows: List[Dict[str, Any]],
    fresh: List[Dict[str, Any]],
    history: Dict[str, List[Dict[str, Any]]],
) -> Dict[str, Dict[str, Any]]:
    """graftcap per-op attribution for every REGRESSION/WAIVED row:
    diff the fresh record against the same-device median-value history
    record, so "what regressed" and "why" (which op/phase moved, did it
    recompile, did GB/s fall) arrive in the same gate output.  metric ->
    perfdiff per-metric diff dict; empty when nothing is flagged or
    perfdiff is unavailable."""
    flagged = [
        r["metric"] for r in rows
        if r["status"] in ("REGRESSION", "WAIVED")
    ]
    if not flagged:
        return {}
    perfdiff = _load_perfdiff()
    if perfdiff is None:
        return {}
    by_metric: Dict[str, Dict[str, Any]] = {}
    for rec in fresh:
        if rec.get("metric"):
            by_metric.setdefault(rec["metric"], rec)
    out: Dict[str, Dict[str, Any]] = {}
    for metric in flagged:
        rec = by_metric.get(metric)
        if rec is None or rec.get("value") is None:
            continue
        hist = _same_device(history.get(metric, []), rec.get("device"))
        if not hist:
            continue
        base = sorted(hist, key=lambda r: r["value"])[len(hist) // 2]
        out[metric] = perfdiff.diff_records(base, rec)
    return out


def format_attribution_blocks(
    attribution: Dict[str, Dict[str, Any]]
) -> str:
    perfdiff = _load_perfdiff()
    if perfdiff is None or not attribution:
        return ""
    lines = ["", "per-op attribution (graftcap, vs same-device median):"]
    for metric, md in attribution.items():
        lines.append("")
        lines.append(perfdiff.format_attribution(md))
    return "\n".join(lines)


def _parse_metric_tols(pairs: List[str]) -> Dict[str, float]:
    out = {}
    for p in pairs:
        if "=" not in p:
            raise ValueError(
                f"bad --metric-tolerance {p!r}: expected name=fraction"
            )
        name, frac = p.split("=", 1)
        out[name.strip()] = float(frac)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--fresh", required=True, metavar="FILE",
        help="fresh bench output (JSON lines from bench_all.py/bench.py, "
        "or a driver wrapper record)",
    )
    ap.add_argument(
        "--history", default=None, metavar="GLOB",
        help="history file glob (default: BENCH_*.json next to this "
        "repo's root)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOL,
        help=f"relative wall-time tolerance (default {DEFAULT_TOL})",
    )
    ap.add_argument(
        "--cost-tolerance", type=float, default=DEFAULT_COST_TOL,
        help="relative solution-quality tolerance "
        f"(default {DEFAULT_COST_TOL})",
    )
    ap.add_argument(
        "--abs-slack", type=float, default=DEFAULT_ABS_SLACK_S,
        help="absolute wall slack in seconds — deltas below this never "
        f"regress, whatever the percentage (default {DEFAULT_ABS_SLACK_S})",
    )
    ap.add_argument(
        "--metric-tolerance", action="append", default=[],
        metavar="NAME=FRAC",
        help="per-metric wall tolerance override (repeatable)",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="a fresh config with no value (errored) fails the gate when "
        "the metric has any history",
    )
    ap.add_argument(
        "--known-drift", default=None, metavar="FILE",
        help="known-drift waiver file (default: tools/"
        "bench_known_drift.json next to this repo's root; waived "
        "metrics print WAIVED instead of failing)",
    )
    ap.add_argument(
        "--no-waivers", action="store_true",
        help="ignore the known-drift waiver file (every regression "
        "fails, documented or not)",
    )
    ap.add_argument(
        "--no-normalize", action="store_true",
        help="disable machine-drift normalization (compare raw seconds; "
        "use on hardware identical to the trajectory's)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the comparison rows as JSON instead of a table",
    )
    args = ap.parse_args(argv)

    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    pattern = args.history or os.path.join(repo_root, "BENCH_*.json")
    paths = sorted(glob.glob(pattern))
    try:
        fresh = load_records(args.fresh)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not fresh:
        print(
            f"error: no bench records in {args.fresh}", file=sys.stderr
        )
        return 2
    history = load_history(paths)
    try:
        metric_tols = _parse_metric_tols(args.metric_tolerance)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    waivers = (
        {} if args.no_waivers
        else load_waivers(
            args.known_drift
            or os.path.join(repo_root, "tools", "bench_known_drift.json")
        )
    )
    rows, regressions, scales = compare(
        fresh, history,
        tol=args.tolerance,
        cost_tol=args.cost_tolerance,
        abs_slack_s=args.abs_slack,
        metric_tols=metric_tols,
        strict=args.strict,
        normalize=not args.no_normalize,
        waivers=waivers,
    )
    waived = sum(1 for r in rows if r["status"] == "WAIVED")
    # any regression (and any printed waiver) auto-runs the graftcap
    # diff against the same-device median baseline record: the failure
    # output carries WHICH op/phase moved, not just that the wall did
    attribution = attribution_blocks(rows, fresh, history)
    if args.json:
        print(json.dumps(
            {"rows": rows, "regressions": regressions,
             "scales": {str(k): v for k, v in scales.items()},
             "history_files": [os.path.basename(p) for p in paths],
             "attribution": attribution},
            indent=2,
        ))
    else:
        drift = ", ".join(
            f"{device}: {s:.2f}x" for device, s in sorted(
                scales.items(), key=lambda kv: str(kv[0])
            )
        )
        print(
            f"bench-gate: {len(fresh)} fresh records vs "
            f"{len(paths)} history files"
            + (f" (machine-drift scale {drift})" if drift else "")
        )
        print(format_table(rows))
        table = format_attribution_blocks(attribution)
        if table:
            print(table)
        print(
            f"\n{'FAIL' if regressions else 'PASS'}: "
            f"{regressions} regression(s)"
            + (f", {waived} known-drift waiver(s)" if waived else "")
        )
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
