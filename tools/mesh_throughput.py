#!/usr/bin/env python
"""Virtual-mesh throughput datum (round-4 verdict item 4).

Times N-cycle SHARDED solves at 1 vs 8 virtual CPU devices on

- a config-4-shaped problem (scale-free graph coloring under the fused
  MaxSum solve, same generator/params as ``bench_all.py`` config 4 — size
  overridable, default 100k variables), and
- a 5k-node DPOP tree with the UTIL-wave joints mesh-partitioned
  (``algorithms/dpop.py`` ``_group_contract`` sharding),

recording per-cycle wall time and the cross-shard row counts of the
layout (``parallel/placement.py:cross_shard_edges``).  Virtual CPU
devices measure the SPMD *mechanics* — collective insertion, partitioned
memory, per-device work — not TPU silicon speed: the value of the datum
is that the sharded program compiles, runs, matches the single-device
result, and scales its per-device row count, while the absolute wall
clock on one CPU host generally gets WORSE with more virtual devices
(they time-share the same cores and add collective overhead).

Usage:  python tools/mesh_throughput.py [n_vars_maxsum] [n_dpop]
Prints one JSON line per measurement; results are recorded in
BASELINE.md's round-5 table.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEVICES = 8


def main(n_vars: int = 100_000, n_dpop: int = 5_000) -> None:
    from pydcop_tpu.utils.platform import pin_cpu

    pin_cpu(N_DEVICES)

    import numpy as np

    from pydcop_tpu.algorithms import dpop, maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.direct import compile_from_edges
    from pydcop_tpu.compile.kernels import to_device
    from pydcop_tpu.parallel.mesh import (
        make_mesh,
        pad_device_dcop,
        shard_device_dcop,
    )
    from pydcop_tpu.parallel.placement import (
        cross_shard_edges,
        cross_shard_incidence,
    )

    # --- MaxSum, config-4-shaped ------------------------------------
    # layout="auto" resolves to the shard-major ELL layout on the sharded
    # mesh (round 6); the record carries the cross-shard incidence of the
    # pair-permutation gather — the ONE cross-shard op of the ELL cycle
    # and the analytic ICI-traffic predictor for real multi-chip runs
    n_cycles = 30
    compiled = generate_coloring_arrays(
        n_vars, 3, graph="scalefree", m_edge=2, seed=7
    )
    # ordering pinned OFF here: this pair of rows measures the RAW
    # contiguous layout (the printed incidence describes the solve); the
    # graftpart variant below measures the partitioned one explicitly
    params = {"damping": 0.7, "stop_cycle": n_cycles, "ordering": "none"}
    base_dev = to_device(compiled)
    results = {}
    for n_dev in (1, N_DEVICES):
        mesh = make_mesh(n_dev)
        dev = shard_device_dcop(
            pad_device_dcop(base_dev, mesh.size), mesh
        )
        maxsum.solve(compiled, dict(params), n_cycles=n_cycles, dev=dev)
        t0 = time.perf_counter()
        r = maxsum.solve(compiled, dict(params), n_cycles=n_cycles, dev=dev)
        wall = time.perf_counter() - t0
        results[n_dev] = (wall, r)
        print(json.dumps({
            "metric": f"maxsum_{n_vars}_sharded_wall",
            "devices": n_dev,
            "value": round(wall, 4),
            "unit": "s",
            "per_cycle_ms": round(1000 * wall / n_cycles, 3),
            "cost": r.cost,
            "layout": "ell",
            "cross_shard_rows": cross_shard_edges(compiled, n_dev),
            "total_edge_rows": int(compiled.n_edges),
            "cross_shard_incidence_frac": round(
                cross_shard_incidence(compiled, n_dev), 4
            ),
        }))
        sys.stdout.flush()
    assert results[1][1].cost == results[N_DEVICES][1].cost, (
        "sharded MaxSum diverged from single-device"
    )

    # --- graftpart: the same solve on the multilevel-partitioned layout
    # (parallel/placement.py partition_compiled) — the incidence column
    # is the ICI-traffic predictor the partition drives down vs the raw
    # ordering above
    from pydcop_tpu.parallel.placement import partition_compiled

    t0 = time.perf_counter()
    placed = partition_compiled(
        compiled, strategy="multilevel", n_shards=N_DEVICES
    )
    order_wall = time.perf_counter() - t0
    mesh = make_mesh(N_DEVICES)
    dev_p = shard_device_dcop(
        pad_device_dcop(to_device(placed), mesh.size), mesh
    )
    params = dict(params, ordering="auto")  # resolves to the pre-partition
    single_p = maxsum.solve(
        placed, dict(params), n_cycles=n_cycles
    )
    maxsum.solve(placed, dict(params), n_cycles=n_cycles, dev=dev_p)
    t0 = time.perf_counter()
    r = maxsum.solve(placed, dict(params), n_cycles=n_cycles, dev=dev_p)
    wall = time.perf_counter() - t0
    assert r.cost == single_p.cost, (
        "partitioned sharded MaxSum diverged from single-device"
    )
    print(json.dumps({
        "metric": f"maxsum_{n_vars}_sharded_partitioned_wall",
        "devices": N_DEVICES,
        "value": round(wall, 4),
        "unit": "s",
        "per_cycle_ms": round(1000 * wall / n_cycles, 3),
        "cost": r.cost,
        "layout": "ell",
        "ordering": "multilevel",
        "order_wall_s": round(order_wall, 2),
        "cross_shard_incidence_frac": round(
            cross_shard_incidence(placed, N_DEVICES), 4
        ),
        "cross_shard_incidence_frac_unordered": round(
            cross_shard_incidence(compiled, N_DEVICES), 4
        ),
    }))
    sys.stdout.flush()

    # --- DPOP, 5k-node tree -----------------------------------------
    rng = np.random.default_rng(0)
    parents = np.array(
        [rng.integers(max(0, i - 4), i) for i in range(1, n_dpop)]
    )
    edges = np.stack([parents, np.arange(1, n_dpop)], axis=1)
    tables = rng.uniform(0, 10, size=(len(edges), 3, 3)).astype(np.float32)
    tree_problem = compile_from_edges(n_dpop, 3, edges, tables)
    costs = {}
    for n_dev in (1, N_DEVICES):
        mesh = make_mesh(n_dev)
        dpop.solve(tree_problem, {}, mesh=mesh)
        t0 = time.perf_counter()
        r = dpop.solve(tree_problem, {}, mesh=mesh)
        wall = time.perf_counter() - t0
        costs[n_dev] = r.cost
        print(json.dumps({
            "metric": f"dpop_{n_dpop}_tree_sharded_wall",
            "devices": n_dev,
            "value": round(wall, 4),
            "unit": "s",
            "cost": r.cost,
            "cross_shard_rows": cross_shard_edges(tree_problem, n_dev),
            "total_edge_rows": int(tree_problem.n_edges),
        }))
        sys.stdout.flush()
    assert costs[1] == costs[N_DEVICES], (
        "sharded DPOP diverged from single-device"
    )


if __name__ == "__main__":
    main(
        int(sys.argv[1]) if len(sys.argv) > 1 else 100_000,
        int(sys.argv[2]) if len(sys.argv) > 2 else 5_000,
    )
