"""Per-op timing of the MaxSum cycle at the bench-4 scale (100k vars).

Times each kernel piece as its own jitted 30-iteration scan so per-op cost is
amortized over dispatch; prints a ms/cycle table.  Run on TPU (default) or
``--cpu``.
"""

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jaxcache")

OP_FILTER = []

# measured once: wall of an empty dispatch + 1-element readback.  On the
# tunneled relay this is a full network round trip (~65 ms) that would
# otherwise be misread as kernel time; on local backends it is ~0.
_SYNC_FLOOR_MS = [0.0]


def _sync(out):
    """Force completion of every device array in ``out`` via one readback.

    ``jax.block_until_ready`` returns without waiting on the tunneled relay
    backend (measured: a 12 ms/cycle scan 'completes' in 0.1 ms, then the
    first readback blocks for the full execution) — every timing in this
    tool must sync through an actual readback or it reports fiction.
    Stacking one element of each leaf into a single probe makes the
    readback depend on ALL leaves while paying one round trip, not one
    per leaf."""
    import jax
    import jax.numpy as jnp

    leaves = [
        leaf for leaf in jax.tree_util.tree_leaves(out)
        if isinstance(leaf, jax.Array)
    ]
    if not leaves:
        return out
    if len(leaves) == 1:
        np.asarray(leaves[0].ravel()[:1])
    else:
        # one JITTED probe over the whole list: a single dispatch + a
        # single readback regardless of leaf count (eager per-leaf ops
        # would each pay the relay round trip inside the timed region)
        np.asarray(_probe_stack(leaves))
    return out


def _probe_stack(leaves):
    import jax

    global _PROBE_JIT
    if _PROBE_JIT is None:
        import jax.numpy as jnp

        _PROBE_JIT = jax.jit(
            lambda ls: jnp.stack(
                [l.ravel()[0].astype(jnp.float32) for l in ls]
            )
        )
    return _PROBE_JIT(leaves)


_PROBE_JIT = None


def measure_sync_floor():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    _sync(f(jnp.zeros((), jnp.float32)))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(f(jnp.zeros((), jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    _SYNC_FLOOR_MS[0] = best * 1000
    print(f"sync floor (dispatch + 1-elem readback): {best*1000:.1f} ms")


def bench_op(name, fn, *args, n=30, traffic_bytes=None):
    """Time fn as a jitted n-iteration scan; with ``traffic_bytes`` (the
    analytic minimum HBM traffic of ONE iteration) also print achieved
    bytes/s — the utilization evidence for BASELINE.md."""
    if OP_FILTER and not any(f in name for f in OP_FILTER):
        return None
    import jax

    scanned = jax.jit(
        lambda *a: jax.lax.scan(
            lambda c, _: (fn(*a[:-1], c), 0.0), a[-1], None, length=n
        )[0]
    )
    out = _sync(scanned(*args))
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        out = _sync(scanned(*args))
        best = min(best, time.perf_counter() - t0)
    dt = max(0.0, best * 1000 - _SYNC_FLOOR_MS[0]) / n
    note = ""
    if traffic_bytes is not None and dt > 0:
        gbps = traffic_bytes / (dt / 1000) / 1e9
        note = f"  ~{gbps:7.1f} GB/s achieved (analytic min traffic)"
    print(f"{name:40s} {dt:8.3f} ms/cycle{note}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--n-vars", type=int, default=100_000)
    ap.add_argument("--ops", nargs="*", default=[])
    ap.add_argument(
        "--trace", default=None, metavar="DIR",
        help="also capture a jax.profiler device trace of the full-step "
        "benchmarks into DIR (open with tensorboard / xprof)",
    )
    args = ap.parse_args()
    OP_FILTER.extend(args.ops)
    if args.cpu:
        from pydcop_tpu.utils.platform import pin_cpu

        pin_cpu()

    import jax
    import jax.numpy as jnp

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile import kernels
    from pydcop_tpu.compile.kernels import (
        factor_step,
        select_values,
        to_device,
        variable_step,
    )

    print("device:", jax.devices()[0])
    measure_sync_floor()
    compiled = generate_coloring_arrays(
        args.n_vars, 3, graph="scalefree", m_edge=2, seed=7
    )
    dev = to_device(compiled)
    d = dev.max_domain
    print(
        f"n_vars={dev.n_vars} n_edges={dev.n_edges} "
        f"n_constraints={dev.n_constraints} D={d} "
        f"buckets={[ (b.arity, b.tables_flat.shape) for b in dev.buckets ]}"
    )

    v2f = jnp.zeros((dev.n_edges, d), dtype=dev.unary.dtype)

    # --- full current step --------------------------------------------------
    from pydcop_tpu.compile.kernels import lanes_aux, masked_argmin

    step = maxsum._make_step(0.7, True, True, True)
    act_v, act_f = maxsum.activation_cycles(compiled, "leafs", dev.n_edges)
    state0 = maxsum.MaxSumState(
        v2f=v2f, f2v=v2f,
        values=masked_argmin(dev.unary, dev.valid_mask),
        cycle=jnp.zeros((), dtype=jnp.int32),
        act_v=jnp.asarray(act_v), act_f=jnp.asarray(act_f),
        aux=None,
    )
    key = jax.random.PRNGKey(0)
    # analytic minimum HBM traffic of one cycle: the two message planes are
    # each read ~3x and written ~1x (factor marginalization, damping blend,
    # fan-in, selection), the joint tables are read once, plus the int32
    # edge index arrays
    itemsize = dev.unary.dtype.itemsize
    table_elems = sum(
        b.tables_flat.size for b in dev.buckets
    )
    plane = dev.n_edges * d
    traffic = itemsize * (8 * plane + table_elems) + 4 * 3 * dev.n_edges

    import contextlib

    with contextlib.ExitStack() as stack:  # covers ALL full-step variants
        if args.trace:
            stack.enter_context(jax.profiler.trace(args.trace))
        bench_op(
            "full step (wavefront)",
            lambda dv, s: step(dv, s, key), dev, state0,
            traffic_bytes=traffic,
        )
        # lane-major full step for comparison
        step_lanes = maxsum._make_step(0.7, True, True, True, lanes=True)
        v2f_t = jnp.zeros((d, dev.n_edges), dtype=dev.unary.dtype)
        state0_t = state0._replace(v2f=v2f_t, f2v=v2f_t, aux=lanes_aux(dev))
        bench_op(
            "full step LANES (wavefront)",
            lambda dv, s: step_lanes(dv, s, key), dev, state0_t,
            traffic_bytes=traffic,
        )
        if jax.devices()[0].platform == "tpu":
            # real-hardware only: the interpreter is far too slow at this size
            step_pl = maxsum._make_step(0.7, True, True, True, lanes=True,
                                        pallas=True)
            bench_op(
                "full step PALLAS (wavefront)",
                lambda dv, s: step_pl(dv, s, key), dev, state0_t,
                traffic_bytes=traffic,
            )
        step_nw = maxsum._make_step(0.7, True, True, False)
        bench_op(
            "full step (no wavefront)",
            lambda dv, s: step_nw(dv, s, key), dev, state0,
            traffic_bytes=traffic,
        )
        # ELL layout (round 5): dense fan-in/fan-out + one partner gather
        from pydcop_tpu.algorithms.maxsum import (
            EllCarry,
            _ell_activation,
            _ell_dev_arrays,
        )
        from pydcop_tpu.compile.kernels import build_ell

        ell = build_ell(compiled)
        arrays = _ell_dev_arrays(compiled, ell, dev)
        act_ve, act_fe = _ell_activation(compiled, ell, "leafs")
        step_ell = maxsum._make_step(
            0.7, True, True, True, ell_spans=ell.spans
        )
        v2f_e = jnp.zeros((d, ell.n_pad), dtype=dev.unary.dtype)
        state0_e = state0._replace(
            v2f=v2f_e, f2v=v2f_e,
            act_v=act_ve, act_f=act_fe,
            aux=EllCarry(unary_t=dev.unary[jnp.asarray(ell.var_perm)].T),
        )
        bench_op(
            "full step ELL (wavefront)",
            lambda dv, s: step_ell(dv, s, key, act_ve, act_fe, *arrays),
            dev, state0_e,
            traffic_bytes=traffic,
        )


    # --- pieces -------------------------------------------------------------
    bench_op("factor_step", factor_step, dev, v2f)
    bench_op("variable_step", lambda dv, m: variable_step(dv, m, 0.7, m), dev, v2f)
    bench_op(
        "select+evaluate",
        lambda dv, m: kernels.evaluate(dv, select_values(dv, m)) + m,
        dev, v2f,
    )
    vals = jnp.zeros(dev.n_vars, dtype=jnp.int32)
    bench_op(
        "evaluate only",
        lambda dv, v: kernels.evaluate(dv, v).astype(jnp.int32) + v, dev, vals,
    )
    bench_op(
        "select_values only",
        # fold the [n_vars] result back into the [n_edges, D] carry via
        # the edge_var gather (a direct broadcast has mismatched shapes)
        lambda dv, m: (
            select_values(dv, m)[dv.edge_var][:, None].astype(m.dtype) + m
        ),
        dev, v2f,
    )

    # factor_step decomposition: gather-in vs compute vs scatter-out
    b = dev.buckets[0]
    n_c = b.tables_flat.shape[0]
    a = b.arity

    def fs_gather(dv, m):
        gathered = m[b.edge_ids].sum(axis=1)  # [n_c, d]
        # fold back without a zeros plane: the scatter row below is the
        # one meant to measure scatter-side cost
        return m.at[:n_c].add(gathered)

    bench_op("  factor: gather v2f[edge_ids]", fs_gather, dev, v2f)

    def fs_compute(dv, m):
        joint = b.tables_flat.reshape((n_c,) + (d,) * a)
        in_msgs = m[: n_c * a].reshape(n_c, a, d)
        total = joint
        for s in range(a):
            shape = [n_c] + [1] * a
            shape[1 + s] = d
            total = total + in_msgs[:, s].reshape(shape)
        outs = []
        for s in range(a):
            shape = [n_c] + [1] * a
            shape[1 + s] = d
            marg = total - in_msgs[:, s].reshape(shape)
            axes = tuple(1 + t for t in range(a) if t != s)
            outs.append(jnp.min(marg, axis=axes))
        stacked = jnp.concatenate(outs, axis=0)  # [n_c*a, d]
        return jnp.zeros_like(m).at[: n_c * a].set(stacked) + m

    bench_op("  factor: compute (no gather/scatter)", fs_compute, dev, v2f)

    def fs_scatter(dv, m):
        out = m[: n_c * a].reshape(n_c, a, d)
        f2v = jnp.zeros_like(m)
        for s in range(a):
            f2v = f2v.at[b.edge_ids[:, s]].set(out[:, s])
        return f2v + m

    bench_op("  factor: scatter .at[].set", fs_scatter, dev, v2f)

    # permutation-gather alternative to the scatter: f2v = stacked[perm]
    edge_ids = np.asarray(b.edge_ids)
    perm = np.zeros(dev.n_edges, dtype=np.int32)
    for s in range(a):
        perm[edge_ids[:, s]] = s * n_c + np.arange(n_c)
    perm_j = jnp.asarray(perm)

    def fs_permgather(dv, m):
        stacked = jnp.concatenate(
            [m[: n_c * a].reshape(n_c, a, d)[:, s] for s in range(a)], axis=0
        )
        return stacked[perm_j] + m

    bench_op("  factor: perm-gather out", fs_permgather, dev, v2f)

    # 1-D flat permutation gather (row gather as element gather)
    flat_idx = (perm[:, None] * d + np.arange(d)[None, :]).reshape(-1)
    flat_idx_j = jnp.asarray(flat_idx)

    def fs_flatgather(dv, m):
        stacked = jnp.concatenate(
            [m[: n_c * a].reshape(n_c, a, d)[:, s] for s in range(a)], axis=0
        )
        return stacked.reshape(-1)[flat_idx_j].reshape(dev.n_edges, d) + m

    bench_op("  factor: flat 1-D gather out", fs_flatgather, dev, v2f)

    # segment_sum fan-in alone
    def fan_in(dv, m):
        s = jax.ops.segment_sum(
            m, dv.edge_var, num_segments=dv.n_vars, indices_are_sorted=True
        )
        return s[dv.edge_var] + m

    bench_op("  var: segment_sum + gather back", fan_in, dev, v2f)

    # transposed [D, n_edges] layout experiment
    v2f_t = jnp.zeros((d, dev.n_edges), dtype=dev.unary.dtype)

    def fan_in_t(dv, m):
        s = jax.vmap(
            lambda row: jax.ops.segment_sum(
                row, dv.edge_var, num_segments=dv.n_vars,
                indices_are_sorted=True,
            )
        )(m)
        return s[:, dv.edge_var] + m

    bench_op("  var: transposed segsum+gather", fan_in_t, dev, v2f_t)

    # elementwise on [n_edges, D] vs [D, n_edges]
    bench_op("  ew: [n_edges,D] mul-add x4",
             lambda dv, m: ((m * 1.1 + 1.0) * 0.9 - 0.5) * 1.01, dev, v2f)
    bench_op("  ew: [D,n_edges] mul-add x4",
             lambda dv, m: ((m * 1.1 + 1.0) * 0.9 - 0.5) * 1.01, dev, v2f_t)

    # one-hot matmul fan-in: [n_vars, D] = onehot[n_vars, n_edges] @ m — too
    # big dense; instead time the take_along_axis pattern in evaluate
    def eval_gather(dv, v):
        flat = dv.buckets[0].tables_flat
        vals = v[dv.buckets[0].var_slots]
        strides = jnp.asarray([d, 1], dtype=vals.dtype)
        fi = (vals * strides).sum(axis=1)
        c = jnp.take_along_axis(flat, fi[:, None], axis=1)[:, 0]
        return v + c.sum().astype(jnp.int32)

    bench_op("  eval: table take_along_axis", eval_gather, dev, vals)

    # --- MGM-2 full cycle at the bench-3 scale (10k Ising) ------------------
    # the captured TPU wall implies ~13 ms/cycle for MGM-2's 5-phase step;
    # this row exists so the next hardware window decomposes it instead of
    # guessing (the maxsum lesson: profile first, the bottleneck was not
    # where three rounds of intuition put it)
    if not OP_FILTER or any(f in "mgm2 cycle" for f in OP_FILTER):
        from pydcop_tpu.algorithms import mgm2 as _mgm2
        from pydcop_tpu.commands.generators.ising import (
            generate_ising_arrays,
        )

        ising = generate_ising_arrays(100, 100, seed=3)
        idev = to_device(ising)
        # warm with the SAME cycle bucket or the timed run pays the compile
        _mgm2.solve(ising, {"stop_cycle": 30}, n_cycles=30, seed=3,
                    dev=idev)
        t0 = time.perf_counter()
        r = _mgm2.solve(ising, {"stop_cycle": 30}, n_cycles=30, seed=3,
                        dev=idev)
        wall = time.perf_counter() - t0
        print(
            f"{'mgm2 full solve (10k ising, 30cy)':40s} "
            f"{wall:8.3f} s total = {1000*wall/30:6.2f} ms/cycle "
            f"(incl dispatch+readback; cost {r.cost:.1f})"
        )

    # --- transfers per solve (round-4 verdict item 3) -----------------------
    # a warm fused solve must be ZERO host->device uploads and exactly two
    # packed readbacks; on the tunneled TPU each transfer is a ~50 ms round
    # trip, so the census is part of the perf record, not just a test
    if OP_FILTER and not any(f in "census" for f in OP_FILTER):
        return  # --ops runs stay cheap: the census costs two full solves
    from pydcop_tpu.algorithms import base as algo_base

    params = {"damping": 0.7, "stop_cycle": 30}
    maxsum.solve(compiled, dict(params), n_cycles=30, seed=7, dev=dev)  # warm
    readbacks = []
    orig_to_host = algo_base.to_host
    algo_base.to_host = lambda x: (readbacks.append(1), orig_to_host(x))[1]
    try:
        with jax.transfer_guard_host_to_device("disallow_explicit"):
            maxsum.solve(compiled, dict(params), n_cycles=30, seed=7, dev=dev)
        uploads = "0 (guard-verified)"
    except Exception as e:  # noqa: BLE001 - report, don't crash the profile
        uploads = f"VIOLATION: {str(e)[:120]}"
    finally:
        algo_base.to_host = orig_to_host
    print(
        f"transfer census (warm fused solve): uploads={uploads} "
        f"readbacks={len(readbacks)}"
    )


if __name__ == "__main__":
    main()
