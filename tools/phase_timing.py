"""Phase-level timing of one config-4 solve on the current backend.

Times each host-side phase of maxsum.solve separately to locate where the
wall goes when kernels only account for ~0.5 ms of a >1 s solve.
"""

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


from profile_maxsum import _sync  # noqa: E402 - shared readback sync


def t(label, fn):
    t0 = time.perf_counter()
    # force completion through a real readback: block_until_ready returns
    # early on the tunneled relay backend and under-reports by orders of
    # magnitude (see tools/profile_maxsum.py::_sync)
    out = _sync(fn())
    dt = time.perf_counter() - t0
    print(f"{label:36s} {dt*1000:9.1f} ms")
    return out


def main():
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.algorithms.base import run_cycles
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    print("device:", jax.devices()[0])
    compiled = t(
        "generate arrays",
        lambda: generate_coloring_arrays(
            100_000, 3, graph="scalefree", m_edge=2, seed=7
        ),
    )
    dev = t("to_device", lambda: to_device(compiled))
    params = {"damping": 0.7, "layout": "lanes"}

    # warm-up full solve (compiles)
    t("solve #1 (compile)", lambda: maxsum.solve(
        compiled, params, n_cycles=30, seed=7, dev=dev))
    # timed full solve
    t("solve #2 (steady)", lambda: maxsum.solve(
        compiled, params, n_cycles=30, seed=7, dev=dev))

    # now phase by phase, mirroring solve()'s internals
    # bypass the per-compiled cache: measure the actual BFS cost
    t("activation_cycles (BFS, uncached)", lambda: (
        maxsum._activation_cycles_impl(compiled, "leafs", dev.n_edges)
    ))
    from pydcop_tpu.algorithms import prepare_algo_params
    p = prepare_algo_params(params, maxsum.algo_params)
    print("params:", {k: p[k] for k in (
        "damping", "start_messages", "noise", "stop_cycle", "stability",
        "layout")})

    t("solve #3 (steady)", lambda: maxsum.solve(
        compiled, params, n_cycles=30, seed=7, dev=dev))
    t("host finalize (repeat)", lambda: compiled.host_cost(
        np.zeros(compiled.n_vars, dtype=np.int32), 10000))


if __name__ == "__main__":
    main()
