"""prof-smoke: graftprof end-to-end gate (``make prof-smoke``).

One thread-mode solve through the real CLI with the full graftprof
surface on (``--profile-out``, ``--dump-hlo``, ``--trace-out``,
``--metrics-out``), asserting the ISSUE-5 acceptance bars:

1. **compile observability** — the metrics snapshot carries ``compile.*``
   series: at least one fresh XLA compile counted, and either
   cost-analysis totals or the explicit ``compile.analysis_unavailable``
   marker (graceful-degradation path);
2. **device attribution** — >= 90% of the trace's device/chunk window
   time (``solve.window`` spans) is attributed to a named algorithm
   phase, and the host-clock fallback (``device.chunk_ms``) recorded at
   least one window;
3. **HLO dumps** — ``--dump-hlo`` wrote at least one HLO text file.

The jax.profiler session itself is best-effort by design (backends
without the profiler fall back to the host clock), so an empty profile
dir is a warning, not a failure.

Exits non-zero with a diagnosis on any miss, like trace-smoke.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ATTRIBUTION_PCT = 90.0
INSTANCE = "tests/instances/graph_coloring.yaml"


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="pydcop_prof_smoke_")
    result_f = os.path.join(workdir, "result.json")
    trace_f = os.path.join(workdir, "trace.json")
    metrics_f = os.path.join(workdir, "metrics.json")
    profile_d = os.path.join(workdir, "profile")
    hlo_d = os.path.join(workdir, "hlo")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, "-m", "pydcop_tpu", "--output", result_f,
        "solve", "-a", "dsa", "-m", "thread", "-n", "10",
        "--trace-out", trace_f, "--metrics-out", metrics_f,
        "--profile-out", profile_d, "--dump-hlo", hlo_d,
        INSTANCE,
    ]
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=600
    )
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        print(f"FAIL: solve exited {proc.returncode}")
        return 1

    failures = []

    # -- 1. compile observability ---------------------------------------
    with open(metrics_f, "r", encoding="utf-8") as f:
        metrics = json.load(f).get("metrics", {})

    def total(name: str) -> float:
        out = 0.0
        for entry in metrics.get(name, {}).get("values", []):
            v = entry.get("value")
            if isinstance(v, dict):
                v = v.get("count", 0)
            out += float(v or 0.0)
        return out

    compiles = total("compile.jit_compiles")
    if compiles < 1:
        failures.append("no compile.jit_compiles recorded")
    analyses = total("compile.flops_total") + total(
        "compile.bytes_accessed_total"
    )
    if analyses <= 0 and total("compile.analysis_unavailable") <= 0:
        failures.append(
            "neither cost-analysis totals nor the analysis_unavailable "
            "fallback marker present"
        )
    if total("device.chunk_ms") < 1:
        failures.append("no device.chunk_ms windows (host-clock fallback)")

    # -- 2. phase attribution over the trace ----------------------------
    with open(trace_f, "r", encoding="utf-8") as f:
        events = json.load(f).get("traceEvents", [])
    windows = [
        e for e in events
        if e.get("name") == "solve.window" and e.get("ph") == "X"
    ]
    if not windows:
        failures.append("trace has no solve.window spans")
        pct = 0.0
    else:
        total_dur = sum(float(e.get("dur", 0.0)) for e in windows)
        attributed = sum(
            float(e.get("dur", 0.0)) for e in windows
            if e.get("args", {}).get("phase")
        )
        pct = 100.0 * attributed / total_dur if total_dur else 0.0
        if pct < ATTRIBUTION_PCT:
            failures.append(
                f"only {pct:.1f}% of device window time attributed to "
                f"named phases (need >= {ATTRIBUTION_PCT:.0f}%)"
            )

    # -- 3. HLO dumps ---------------------------------------------------
    hlo_files = (
        sorted(os.listdir(hlo_d)) if os.path.isdir(hlo_d) else []
    )
    if not hlo_files:
        failures.append("--dump-hlo wrote no HLO files")

    profiler_files = sum(
        len(files) for _, _, files in os.walk(profile_d)
    ) if os.path.isdir(profile_d) else 0

    print(
        f"prof-smoke: {int(compiles)} compile(s), "
        f"{len(windows)} device window(s), {pct:.1f}% phase-attributed, "
        f"{len(hlo_files)} HLO dump(s), "
        f"{profiler_files} profiler file(s)"
    )
    if profiler_files == 0:
        print(
            "note: jax.profiler produced no files on this backend — "
            "host-clock fallback (device.chunk_ms) is the timeline"
        )
    if failures:
        for f_ in failures:
            print(f"FAIL: {f_}")
        return 1
    print("PASS")
    shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
