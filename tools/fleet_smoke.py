"""graftfleet smoke gate (``make fleet-smoke``, docs/observability.md).

Three real ``pydcop_tpu serve`` worker processes (each with SLOs on),
one ``pydcop_tpu fleet`` federation process scraping them, HTTP traffic
driven at every worker, and a chaos SIGKILL of one worker mid-run.
Fails unless:

- every federated counter series stays MONOTONE across every scrape of
  the fleet surface, through the kill (the reset/staleness machinery
  never lets a fleet total jump backwards),
- ``fleet.worker_up`` flips 1 -> 0 for EXACTLY the killed worker while
  the survivors stay up, and past ``--stale-after`` the victim's own
  series are dropped from ``/metrics.json`` while its meta-series stay,
- the fleet SLO keeps evaluating over the survivors: the impossible
  latency objective burns (fleet alert fires, naming a worst worker)
  while availability stays clean, and fleet good-counts keep growing
  from post-kill traffic,
- ``watch --fleet --once`` renders the worker table (survivors UP, the
  victim DOWN),
- the fleet process drains on SIGTERM with a final report agreeing with
  the last scrape.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CYCLES = 20
N_WORKERS = 3
VICTIM = "w1"
SLO_SPECS = ["lat=p99<1ms", "avail=availability>=99%"]


def _fail(msg: str) -> int:
    print(f"FLEET-SMOKE FAIL: {msg}")
    return 1


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def make_problems(n):
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_graph_coloring,
    )
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    return [
        dcop_yaml(generate_graph_coloring(
            9, 3, graph="grid", seed=300 + i, extensive=True
        ))
        for i in range(n)
    ]


def start_worker(name, env):
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "serve", "--port", "0",
            "--window-ms", "30", "--max-batch", "8",
        ]
        + [a for s in SLO_SPECS for a in ("--slo", s)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env, cwd=REPO,
    )
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVE_PORT="):
            return proc, int(line.strip().split("=", 1)[1])
    raise AssertionError(f"worker {name} never announced its port")


def drive(base, yaml_docs, tag):
    """Submit one tenant per doc and wait until every one is terminal."""
    tenants = []
    for i, doc in enumerate(yaml_docs):
        body = json.dumps({
            "dcop_yaml": doc, "algo": "dsa", "n_cycles": CYCLES,
            "seed": i, "tenant": f"{tag}{i}",
        }).encode()
        req = urllib.request.Request(
            base + "/solve", data=body, method="POST"
        )
        tenants.append(
            json.loads(urllib.request.urlopen(req, timeout=60).read())
            ["tenant"]
        )
    deadline = time.time() + 300
    for tenant in tenants:
        while time.time() < deadline:
            doc = _get(f"{base}/result/{tenant}", timeout=30)
            if doc["status"] in ("done", "failed", "killed"):
                assert doc["status"] == "done", f"{tenant}: {doc}"
                break
            time.sleep(0.1)
    return tenants


class MonotoneWatch:
    """Scrapes the fleet /metrics.json in a loop and records any counter
    series that goes backwards between consecutive snapshots."""

    def __init__(self, base):
        self.base = base
        self.violations = []
        self.scrapes = 0
        self._prev = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def check_once(self):
        snap = _get(self.base + "/metrics.json")
        cur = {}
        for name, m in snap["metrics"].items():
            if m.get("kind") != "counter":
                continue
            for e in m.get("values", []):
                key = (name, tuple(sorted(e["labels"].items())))
                cur[key] = float(e["value"])
        for key, v in cur.items():
            prev = self._prev.get(key)
            if prev is not None and v < prev:
                self.violations.append(f"{key}: {prev} -> {v}")
        self._prev = cur
        self.scrapes += 1
        return snap

    def _run(self):
        while not self._stop.is_set():
            try:
                self.check_once()
            except OSError:
                pass  # fleet surface busy/starting: not a gate failure
            self._stop.wait(0.2)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


def worker_up(snap):
    ups = {}
    for e in snap["metrics"]["fleet.worker_up"]["values"]:
        ups[e["labels"]["worker"]] = e["value"]
    return ups


def main() -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYDCOP_TPU_STATE_DIR"] = "/tmp/pydcop_fleet_smoke_state"
    problems = make_problems(2)

    workers = {}
    fleet_proc = None
    fleet_out = "/tmp/pydcop_fleet_smoke.json"
    try:
        for i in range(N_WORKERS):
            name = f"w{i}"
            workers[name] = start_worker(name, env)
        targets = [
            f"{name}=http://127.0.0.1:{port}"
            for name, (_proc, port) in sorted(workers.items())
        ]
        fleet_proc = subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu",
                "--output", fleet_out, "fleet",
            ]
            + targets
            + [
                "--port", "0", "--interval", "0.25",
                "--stale-after", "2",
            ]
            + [a for s in SLO_SPECS for a in ("--slo", s)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=REPO,
        )
        fport = None
        deadline = time.time() + 60
        while time.time() < deadline:
            line = fleet_proc.stdout.readline()
            if line.startswith("FLEET_PORT="):
                fport = int(line.strip().split("=", 1)[1])
                break
        if not fport:
            return _fail("fleet verb never announced its port")
        fleet_base = f"http://127.0.0.1:{fport}"

        watch = MonotoneWatch(fleet_base)
        watch.start()

        # ---- wave 1: traffic at every worker, whole fleet up ----------
        for name, (_proc, port) in sorted(workers.items()):
            drive(f"http://127.0.0.1:{port}", problems, f"{name}-a")
        time.sleep(1.0)  # a few scrape intervals
        snap = _get(fleet_base + "/metrics.json")
        ups = worker_up(snap)
        if ups != {f"w{i}": 1.0 for i in range(N_WORKERS)}:
            return _fail(f"fleet never saw all workers up: {ups}")
        st = _get(fleet_base + "/fleet/status")
        if st["fleet"]["solves"] != N_WORKERS * len(problems):
            return _fail(
                f"fleet solves {st['fleet']['solves']} != "
                f"{N_WORKERS * len(problems)}"
            )
        slo_before = _get(fleet_base + "/fleet/slo")
        good_before = slo_before["fleet"]["objectives"]["avail"]["good"]
        if good_before <= 0:
            return _fail(f"fleet SLO saw no events: {slo_before['fleet']}")

        # ---- chaos: SIGKILL one worker mid-run ------------------------
        victim_proc, victim_port = workers[VICTIM]
        victim_proc.kill()
        victim_proc.wait(timeout=30)
        survivors = [n for n in sorted(workers) if n != VICTIM]
        # survivors keep serving while the victim's scrapes start failing
        for name in survivors:
            drive(
                f"http://127.0.0.1:{workers[name][1]}", problems,
                f"{name}-b",
            )
        time.sleep(3.0)  # > --stale-after: victim goes stale too

        snap = _get(fleet_base + "/metrics.json")
        ups = worker_up(snap)
        want = {n: (0.0 if n == VICTIM else 1.0) for n in workers}
        if ups != want:
            return _fail(
                f"fleet.worker_up after kill: {ups} (want {want}) — "
                "must flip for exactly the victim"
            )
        # past stale-after the victim's own series are dropped...
        victim_series = [
            (name, e["labels"])
            for name, m in snap["metrics"].items()
            if not name.startswith("fleet.")
            for e in m.get("values", [])
            if e["labels"].get("worker") == VICTIM
        ]
        if victim_series:
            return _fail(
                f"stale victim still serves series: {victim_series[:5]}"
            )
        # ... while its meta-series survive as the only trace
        for meta in ("fleet.worker_up", "fleet.scrape_failures_total"):
            if not any(
                e["labels"].get("worker") == VICTIM
                for e in snap["metrics"][meta]["values"]
            ):
                return _fail(f"victim lost its {meta} meta-series")

        # ---- fleet SLO over the survivors -----------------------------
        slo_after = _get(fleet_base + "/fleet/slo")
        fl = slo_after["fleet"]["objectives"]
        if fl["avail"]["good"] <= good_before:
            return _fail(
                "fleet availability good-count did not grow from "
                f"survivor traffic: {fl['avail']}"
            )
        if fl["avail"]["bad"] != 0:
            return _fail(f"availability burned: {fl['avail']}")
        # the impossible 1 ms p99 objective: every request is bad, the
        # burn must trip the fleet fast alert and name a worst worker
        if fl["lat"]["bad"] <= 0 or fl["lat"]["burn_fast"] <= 0:
            return _fail(f"lat objective never burned: {fl['lat']}")
        firing = [
            t for t in slo_after["transitions"]
            if t["objective"] == "lat" and t["state"] == "firing"
        ]
        if not firing:
            return _fail(
                f"fleet fast-burn alert never fired: {slo_after['transitions']}"
            )
        if not firing[0].get("worst_worker"):
            return _fail(f"fleet alert names no worst worker: {firing[0]}")
        if not slo_after["workers"]:
            return _fail("fleet SLO lost its per-worker engines")

        # ---- watch --fleet renders the table --------------------------
        res = subprocess.run(
            [
                sys.executable, "-m", "pydcop_tpu", "watch",
                "--fleet", fleet_base, "--once",
            ],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=60,
        )
        if res.returncode != 0:
            return _fail(f"watch --fleet exited {res.returncode}: {res.stderr}")
        out = res.stdout
        if f"{len(survivors)}/{N_WORKERS} workers up" not in out:
            return _fail(f"watch --fleet census wrong:\n{out}")
        if "DOWN" not in out or out.count(" UP") < len(survivors):
            return _fail(f"watch --fleet table missing up/down rows:\n{out}")
        if "fleet slo:" not in out:
            return _fail(f"watch --fleet missing the fleet SLO lines:\n{out}")

        watch.stop()
        if watch.violations:
            return _fail(
                "federated counters went backwards: "
                f"{watch.violations[:5]}"
            )
        if watch.scrapes < 5:
            return _fail(f"monotone watch barely ran: {watch.scrapes}")

        # ---- clean shutdown -------------------------------------------
        fleet_proc.send_signal(signal.SIGTERM)
        rc = fleet_proc.wait(timeout=60)
        if rc != 0:
            return _fail(f"fleet verb exited {rc}")
        with open(fleet_out, "r", encoding="utf-8") as f:
            report = json.load(f)
        if report["workers_up"] != len(survivors):
            return _fail(f"final report census wrong: {report['workers_up']}")
        if report["workers"][VICTIM]["up"] is not False:
            return _fail("final report thinks the victim is up")
        print(
            "FLEET-SMOKE PASS: "
            f"{N_WORKERS} workers federated, {watch.scrapes} scrapes all "
            f"monotone, worker_up flipped for exactly {VICTIM}, fleet "
            f"burn over survivors (worst={firing[0]['worst_worker']}), "
            "watch --fleet renders, clean drain"
        )
        return 0
    finally:
        for _name, (proc, _port) in workers.items():
            if proc.poll() is None:
                proc.kill()
        if fleet_proc is not None and fleet_proc.poll() is None:
            fleet_proc.kill()


if __name__ == "__main__":
    sys.exit(main())
