"""graftserve smoke gate (``make serve-smoke``, docs/serving.md).

Starts a real ``pydcop_tpu serve`` process, submits >= 8 concurrent
tenants spanning TWO shape buckets over HTTP, and fails unless:

- every tenant converges to EXACTLY its sequential-solve cost
  (``serve.solve_one`` on the same compiled problem — the bit-identity
  contract, end-to-end through the HTTP + micro-batch path),
- ``/status`` shows a per-tenant graftpulse row for every done tenant,
- ``/healthz`` reads ready (200, ``serving``) while traffic flows and
  flips to not-ready (503, ``draining``/``drained``) once the drain
  begins — the readiness signal HA routers key worker exclusion on,
- fewer batches were dispatched than tenants (micro-batching actually
  batched something),
- ``POST /shutdown`` drains cleanly: exit code 0, ``drained`` true and
  ZERO dead letters in the final report.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_BIG, N_SMALL = 5, 3  # two buckets, 8 tenants
CYCLES = 30


def make_problems():
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_graph_coloring,
    )
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    docs = []
    for i in range(N_BIG):
        dcop = generate_graph_coloring(
            16, 3, graph="grid", seed=100 + i, extensive=True
        )
        docs.append((f"big{i}", dcop_yaml(dcop), 100 + i))
    for i in range(N_SMALL):
        dcop = generate_graph_coloring(
            9, 3, graph="grid", seed=200 + i, extensive=True
        )
        docs.append((f"small{i}", dcop_yaml(dcop), 200 + i))
    return docs


def reference_costs(docs):
    """Sequential-solve reference per tenant (serve.solve_one on the same
    YAML, compiled exactly like the server compiles it)."""
    from pydcop_tpu.compile.core import compile_dcop
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.serve import SolveRequest, solve_one

    out = {}
    for tenant, yaml_doc, seed in docs:
        compiled = compile_dcop(load_dcop(yaml_doc))
        tr = solve_one(
            SolveRequest(tenant, compiled, "dsa", {}, CYCLES, seed)
        )
        out[tenant] = tr.result.cost
    return out


def main() -> int:
    docs = make_problems()
    refs = reference_costs(docs)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out_path = "/tmp/pydcop_serve_smoke.json"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "--output", out_path,
            "serve", "--port", "0", "--window-ms", "80",
            "--max-batch", "16",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env, cwd=REPO,
    )
    try:
        port = None
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVE_PORT="):
                port = int(line.strip().split("=", 1)[1])
                break
        assert port, "server never announced its port"
        base = f"http://127.0.0.1:{port}"

        # concurrent submission: all 8 tenants race into one batching
        # window (the server groups them into their two buckets)
        tenants = {}
        errors = []

        def submit(tenant, yaml_doc, seed):
            body = json.dumps(
                {
                    "dcop_yaml": yaml_doc, "algo": "dsa",
                    "n_cycles": CYCLES, "seed": seed, "tenant": tenant,
                }
            ).encode()
            req = urllib.request.Request(
                base + "/solve", data=body, method="POST"
            )
            try:
                r = json.loads(
                    urllib.request.urlopen(req, timeout=60).read()
                )
                tenants[tenant] = r["tenant"]
            except Exception as e:  # noqa: BLE001
                errors.append(f"{tenant}: {e}")

        threads = [
            threading.Thread(target=submit, args=d) for d in docs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, f"submissions failed: {errors}"
        assert len(tenants) == len(docs)

        results = {}
        deadline = time.time() + 300
        for tenant in tenants:
            while time.time() < deadline:
                doc = json.loads(
                    urllib.request.urlopen(
                        f"{base}/result/{tenant}", timeout=30
                    ).read()
                )
                if doc["status"] in ("done", "failed", "killed"):
                    results[tenant] = doc
                    break
                time.sleep(0.1)
        for tenant, _yaml, _seed in docs:
            doc = results.get(tenant)
            assert doc and doc["status"] == "done", (
                f"tenant {tenant} did not finish: {doc}"
            )
            assert doc["cost"] == refs[tenant], (
                f"tenant {tenant}: served cost {doc['cost']} != "
                f"sequential {refs[tenant]}"
            )

        status = json.loads(
            urllib.request.urlopen(base + "/status", timeout=30).read()
        )
        pulse_rows = [
            t for t, row in status["tenants"].items() if "pulse" in row
        ]
        assert len(pulse_rows) == len(docs), (
            f"/status pulse rows: {len(pulse_rows)}/{len(docs)}"
        )
        buckets = {
            row.get("bucket") for row in status["tenants"].values()
        }
        assert len(buckets) == 2, f"expected 2 buckets, saw {buckets}"
        assert status["batches"] < len(docs), (
            f"{status['batches']} batches for {len(docs)} tenants: "
            "micro-batching never batched"
        )
        assert status["dead_letters"] == 0

        # readiness: serving answers 200, a draining/drained worker
        # must answer 503 so routers stop placing tenants on it
        health = json.loads(
            urllib.request.urlopen(base + "/healthz", timeout=30).read()
        )
        assert health["state"] == "serving", f"/healthz: {health}"

        req = urllib.request.Request(
            base + "/shutdown", data=b"{}", method="POST"
        )
        urllib.request.urlopen(req, timeout=30).read()
        not_ready = None
        deadline = time.time() + 30
        while time.time() < deadline and not_ready is None:
            try:
                urllib.request.urlopen(base + "/healthz", timeout=5).read()
                time.sleep(0.05)
            except urllib.error.HTTPError as e:
                assert e.code == 503, f"/healthz while draining: {e.code}"
                not_ready = json.loads(e.read())
            except OSError:
                break  # server already gone: drain finished under us
        if not_ready is not None:
            assert not_ready["state"] in ("draining", "drained"), not_ready
        rc = proc.wait(timeout=120)
        assert rc == 0, f"serve exited {rc}"
        with open(out_path, "r", encoding="utf-8") as f:
            report = json.load(f)
        assert report["drained"] is True
        assert report["dead_letters"] == 0
        assert report["solves"] == len(docs)
        print(
            "serve-smoke OK: "
            f"{len(docs)} tenants / {status['batches']} batches over "
            f"{len(buckets)} buckets, all costs == sequential, "
            f"{len(pulse_rows)} pulse rows, healthz ready->not-ready, "
            "clean drain "
            f"(queue p50 {status['queue_ms']['p50']:.1f} ms)"
        )
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
