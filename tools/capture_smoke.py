"""capture-smoke: graftcap end-to-end gate (``make capture-smoke``).

Three checks, all on CPU:

1. **capture** — ``pydcop_tpu capture`` of two fast configs (2: maxsum
   ELL with the full per-op kernel block; 5: dpop) writes a valid
   bundle: manifest + per-config records with ``compile`` / ``census``
   blocks, config 2's per-op attribution present, HLO dumps on disk;
2. **self-diff** — ``capture diff BUNDLE BUNDLE`` reports ZERO
   significant deltas and exits 0 (a diff that finds drift between a
   bundle and itself is broken);
3. **perturbed diff** — against a copy whose config-2 record has one op
   inflated (``ell.minplus`` x4) and the wall doubled, the diff must
   exit 1, call the metric significant, and rank the perturbed op
   FIRST in the attribution table.

Prints PASS/FAIL; exits non-zero on any miss.
"""

import copy
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS = ["2", "5"]
PERTURB_OP = "minplus"          # inside config 2's ELL kernel block
PERTURB_METRIC = "maxsum_1k_random_wall"


def main() -> int:
    from pydcop_tpu.dcop_cli import main as cli
    from pydcop_tpu.telemetry import perfdiff

    failures = []
    tmp = tempfile.mkdtemp(prefix="capture_smoke_")
    bundle = os.path.join(tmp, "bundle")
    try:
        rc = cli([
            "--platform", "cpu",
            "--output", os.path.join(tmp, "capture_result.json"),
            "capture", "-o", bundle,
            "--configs", *CONFIGS, "--no-profiler",
        ])
        if rc != 0:
            failures.append(f"capture exited {rc} (want 0)")
        manifest_path = os.path.join(bundle, "manifest.json")
        if not os.path.exists(manifest_path):
            failures.append("bundle has no manifest.json")
            print("FAIL:", "; ".join(failures))
            return 1
        with open(manifest_path) as fh:
            manifest = json.load(fh)
        if manifest.get("format") != perfdiff.BUNDLE_FORMAT:
            failures.append(f"manifest format {manifest.get('format')!r}")
        missing = [c for c in CONFIGS if c not in manifest.get("configs", {})]
        if missing:
            failures.append(f"configs missing from manifest: {missing}")
        rec_path = os.path.join(bundle, "records", "config_2.json")
        with open(rec_path) as fh:
            rec = json.load(fh)
        for block in ("compile", "census", "telemetry"):
            if block not in rec:
                failures.append(f"config 2 record lacks {block!r} block")
        if perfdiff.attribution_state(rec) != "ok":
            failures.append(
                "config 2 attribution not ok: "
                f"{perfdiff.attribution_state(rec)}"
            )
        if not os.listdir(os.path.join(bundle, "hlo", "config_2")):
            failures.append("no HLO dumps for config 2")

        # 2) self-diff: zero significant deltas, exit 0
        rc = cli(["capture", "diff", bundle, bundle])
        if rc != 0:
            failures.append(f"self-diff exited {rc} (want 0)")
        self_diff = perfdiff.diff_sides(
            perfdiff.load_side(bundle), perfdiff.load_side(bundle)
        )
        if self_diff["significant"] or self_diff["flags"]:
            failures.append(
                f"self-diff not clean: {self_diff['significant']} "
                f"significant, flags={self_diff['flags']}"
            )

        # 3) perturbed copy: the diff must name the inflated op first
        perturbed = os.path.join(tmp, "perturbed")
        shutil.copytree(bundle, perturbed)
        bad = copy.deepcopy(rec)
        bad["value"] = round(rec["value"] * 2.0, 4)
        bad["kernel"]["ops"][PERTURB_OP]["ms"] = round(
            rec["kernel"]["ops"][PERTURB_OP]["ms"] * 4.0, 4
        )
        with open(
            os.path.join(perturbed, "records", "config_2.json"), "w"
        ) as fh:
            json.dump(bad, fh)
        rc = cli(["capture", "diff", bundle, perturbed])
        if rc != 1:
            failures.append(f"perturbed diff exited {rc} (want 1)")
        diff = perfdiff.diff_sides(
            perfdiff.load_side(bundle), perfdiff.load_side(perturbed)
        )
        md = next(
            d for d in diff["metrics"] if d["metric"] == PERTURB_METRIC
        )
        if not md["significant"]:
            failures.append("perturbed metric not flagged significant")
        sig_ops = [r["op"] for r in md["ops"] if r["significant"]]
        if sig_ops[:1] != [f"ell.{PERTURB_OP}"]:
            failures.append(
                f"perturbed op not ranked first: significant ops {sig_ops}"
            )
        if diff["metrics"][0]["metric"] != PERTURB_METRIC:
            failures.append(
                "perturbed metric not ranked first: "
                f"{diff['metrics'][0]['metric']}"
            )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    if failures:
        print("FAIL: " + "; ".join(failures))
        return 1
    print(
        f"PASS: capture bundle ({','.join(CONFIGS)}) valid, self-diff "
        f"clean, perturbed diff ranks ell.{PERTURB_OP} first"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
