"""pulse-smoke: graftpulse end-to-end gate (``make pulse-smoke``).

Three seeded CPU runs against the ISSUE-7 acceptance bars
(docs/observability.md, graftpulse):

1. **stalled run diagnosed** — DSA (zero noise) on a frustrated clique
   (K4, 3 colors: the optimum keeps one violated edge, so parallel local
   search churns the violation around forever without improving) must be
   diagnosed ``stalled-plateau``;
2. **converged run diagnosed** — DSA on a 2-colorable chain with a cycle
   budget long past its settle point must be diagnosed ``converged``;
3. **postmortem flight recorder** — a chaos run whose schedule kills an
   agent, with pulse armed, must leave a parseable ``postmortem.json``
   that ``pydcop_tpu postmortem`` renders.

Exits non-zero (with a diagnosis) on any miss, like trace-smoke.
"""

import itertools
import os
import subprocess
import sys
import tempfile

# run as `python tools/pulse_smoke.py` from the repo root: make the
# package importable without an install
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHAOS_SCHEDULE = "tests/instances/chaos_kill_repair.yaml"
CHAOS_INSTANCE = "tests/instances/graph_coloring.yaml"


def _clique(n: int, colors: int):
    """K_n graph coloring: frustrated when n > colors."""
    from pydcop_tpu.compile.core import compile_dcop
    from pydcop_tpu.dcop import (
        DCOP, Domain, Variable, constraint_from_str,
    )

    d = Domain("c", "", [str(i) for i in range(colors)])
    vs = [Variable(f"v{i}", d) for i in range(n)]
    dcop = DCOP(f"k{n}_{colors}c")
    for i, j in itertools.combinations(range(n), 2):
        dcop += constraint_from_str(
            f"c{i}_{j}", f"10 if v{i} == v{j} else 0", [vs[i], vs[j]]
        )
    dcop.add_agents([])
    return compile_dcop(dcop)


def _chain(n: int):
    """2-colorable path: DSA settles within a few cycles."""
    from pydcop_tpu.compile.core import compile_dcop
    from pydcop_tpu.dcop import (
        DCOP, Domain, Variable, constraint_from_str,
    )

    d = Domain("c", "", ["R", "G"])
    vs = [Variable(f"v{i}", d) for i in range(n)]
    dcop = DCOP("chain")
    for i in range(n - 1):
        dcop += constraint_from_str(
            f"c{i}", f"10 if v{i} == v{i + 1} else 0", [vs[i], vs[i + 1]]
        )
    dcop.add_agents([])
    return compile_dcop(dcop)


def _diagnose(compiled, n_cycles: int, seed: int) -> str:
    from pydcop_tpu.algorithms import dsa
    from pydcop_tpu.telemetry.pulse import pulse

    pulse.reset()
    pulse.enabled = True
    try:
        dsa.solve(compiled, {}, n_cycles=n_cycles, seed=seed)
        return pulse.last_report["analysis"]["diagnosis"]
    finally:
        pulse.enabled = False
        pulse.reset()


def _chaos_postmortem() -> list:
    """Chaos-killed run with pulse armed -> postmortem.json renders."""
    failures = []
    with tempfile.TemporaryDirectory(prefix="pulse_smoke_") as tmp:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        r = subprocess.run(
            [
                sys.executable, "-m", "pydcop_tpu",
                "--output", os.path.join(tmp, "chaos.json"),
                "chaos", "-a", "dsa", "-n", "10", "--seed", "0",
                "-k", "1",
                "--fault-schedule", os.path.join(REPO, CHAOS_SCHEDULE),
                "--pulse-out", os.path.join(tmp, "pulse.jsonl"),
                os.path.join(REPO, CHAOS_INSTANCE),
            ],
            capture_output=True, text=True, timeout=600,
            cwd=tmp, env=env,
        )
        if r.returncode != 0:
            failures.append(f"chaos run failed rc={r.returncode}: {r.stderr[-500:]}")
            return failures
        pm = os.path.join(tmp, "postmortem.json")
        if not os.path.exists(pm):
            failures.append("chaos kill left no postmortem.json")
            return failures
        r2 = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu", "postmortem", pm],
            capture_output=True, text=True, timeout=120, env=env,
        )
        if r2.returncode != 0:
            failures.append(
                f"postmortem verb failed rc={r2.returncode}: {r2.stderr[-500:]}"
            )
        elif "postmortem: agent-crash:" not in r2.stdout:
            failures.append(
                f"postmortem render missing crash reason:\n{r2.stdout}"
            )
        else:
            print("chaos postmortem rendered:")
            print("  " + r2.stdout.splitlines()[0])
    return failures


def main() -> int:
    from pydcop_tpu.utils.platform import pin_cpu

    pin_cpu()

    failures = []

    # 1. forced stall: frustrated K4 under 3 colors, zero noise
    got = _diagnose(_clique(4, 3), n_cycles=60, seed=1)
    print(f"stalled run diagnosis: {got}")
    if got != "stalled-plateau":
        failures.append(f"expected stalled-plateau, got {got}")

    # 2. convergence: 2-colorable chain, budget far past the settle point
    got = _diagnose(_chain(8), n_cycles=60, seed=0)
    print(f"converged run diagnosis: {got}")
    if got != "converged":
        failures.append(f"expected converged, got {got}")

    # 3. flight recorder end-to-end through the chaos runtime
    failures += _chaos_postmortem()

    if failures:
        for f in failures:
            print(f"PULSE-SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print("pulse-smoke: all green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
