"""kernel-smoke: Pallas ELL kernel gate (``make kernel-smoke``).

Three checks, all on CPU (the Pallas interpreter runs the SAME kernel
the TPU lowers, so this smoke is the hardware test's dress rehearsal —
tools/validate_device.py re-runs the same assertions on real TPUs):

1. **kernel bit-agreement** — ``factor_step_ell(use_pallas=True)``
   (interpret mode) is BITWISE equal to the pure-jnp ELL factor step on
   random message planes, for a multi-bucket degree distribution AND the
   single-bucket edge case (every variable the same degree class — the
   ``(b,) = c.buckets`` shape PR 1 hardened);
2. **solve bit-agreement** — a full ``layout="ell_pallas"`` MaxSum solve
   returns the bit-identical assignment/cost of ``layout="ell"``, and
   the lanes layout agrees on violations/cost to float tolerance;
3. **per-op attribution** — ``telemetry.ell_kernel_block`` attributes
   >= 90% of the fused step's wall to its three named ops, and its
   ``pallas`` sub-block records the jnp-vs-pallas micro-benchmark (the
   bench-record datum; interpret-mode walls are plumbing numbers, not
   performance claims).

Prints the kernel block JSON (one line, BENCH-style) and PASS/FAIL;
exits non-zero on any miss.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ATTRIBUTION_PCT = 90.0


def _bit_agreement(compiled, label: str) -> list:
    import jax.numpy as jnp
    import numpy as np

    from pydcop_tpu.compile.kernels import build_ell, factor_step_ell

    failures = []
    ell = build_ell(compiled)
    d = int(compiled.max_domain)
    rng = np.random.default_rng(11)
    v2f = jnp.asarray(
        np.where(
            ell.real_row, rng.normal(size=(d, ell.n_pad)), 0.0
        ).astype(compiled.float_dtype)
    )
    tabs_t = jnp.asarray(ell.tabs_t)
    pair_perm = jnp.asarray(ell.pair_perm)
    real_row = jnp.asarray(ell.real_row)
    ref = factor_step_ell(tabs_t, pair_perm, real_row, v2f)
    pal = factor_step_ell(
        tabs_t, pair_perm, real_row, v2f, use_pallas=True
    )
    if not np.array_equal(np.asarray(ref), np.asarray(pal)):
        diff = int((np.asarray(ref) != np.asarray(pal)).sum())
        failures.append(
            f"{label}: pallas factor step differs from jnp in {diff} "
            f"of {ref.size} entries"
        )
    n_buckets = len({db for _, db in ell.spans})
    print(
        f"kernel-smoke: {label}: [{d}, {ell.n_pad}] planes, "
        f"{n_buckets} degree class(es), pallas == jnp "
        f"{'BITWISE' if not failures else 'FAILED'}"
    )
    return failures


def main() -> int:
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.telemetry import ell_kernel_block

    failures = []

    # -- 1. kernel-level bit-agreement ----------------------------------
    multi = generate_coloring_arrays(
        200, 3, graph="scalefree", m_edge=2, seed=7
    )
    failures += _bit_agreement(multi, "multi-bucket scalefree")
    # complete graph: every variable has the same degree, so the whole
    # layout is ONE degree class — the (b,) = c.buckets edge PR 1 hardened
    clique = generate_coloring_arrays(
        12, 4, graph="random", p_edge=1.0, seed=3
    )
    failures += _bit_agreement(clique, "single-bucket clique")

    # -- 2. full-solve three-way agreement ------------------------------
    base = {"damping": 0.5, "noise": 0.0}
    r_ell = maxsum.solve(
        multi, dict(base, layout="ell"), n_cycles=20, seed=5
    )
    r_pal = maxsum.solve(
        multi, dict(base, layout="ell_pallas"), n_cycles=20, seed=5
    )
    r_lan = maxsum.solve(
        multi, dict(base, layout="lanes"), n_cycles=20, seed=5
    )
    if r_pal.assignment != r_ell.assignment or r_pal.cost != r_ell.cost:
        failures.append(
            "ell_pallas solve diverged from ell "
            f"(cost {r_pal.cost} vs {r_ell.cost})"
        )
    if r_lan.violations != r_ell.violations or (
        abs(r_lan.cost - r_ell.cost) > 1e-4 * max(1.0, abs(r_ell.cost))
    ):
        failures.append(
            f"lanes solve disagrees with ell (cost {r_lan.cost} vs "
            f"{r_ell.cost}, violations {r_lan.violations} vs "
            f"{r_ell.violations})"
        )
    print(
        f"kernel-smoke: solve three-way: ell cost {r_ell.cost:.4f} == "
        f"ell_pallas {r_pal.cost:.4f}, lanes {r_lan.cost:.4f}"
    )

    # -- 3. per-op attribution + jnp-vs-pallas micro-benchmark ----------
    block = ell_kernel_block(multi, reps=10)
    print(json.dumps({"metric": "kernel_smoke_ell", "kernel": block}))
    pct = block.get("attributed_pct")
    if pct is None or pct < ATTRIBUTION_PCT:
        failures.append(
            f"only {pct}% of the ELL step attributed to named ops "
            f"(need >= {ATTRIBUTION_PCT:.0f}%)"
        )
    pallas = block.get("pallas", {})
    if not pallas.get("supported") or "factor_ms" not in pallas:
        failures.append(
            "kernel block carries no jnp-vs-pallas micro-benchmark: "
            f"{pallas}"
        )

    if failures:
        for f_ in failures:
            print(f"FAIL: {f_}")
        return 1
    print("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
