"""On-device validation of the Pallas kernel and bf16 message planes.

Round-3 verdict item 2: `compile/pallas_kernels.py` was pinned
bit-identical to the lanes path only under the interpreter, and the bf16
quality delta was measured on CPU.  This script runs both comparisons on
whatever backend jax resolves (intended: the real TPU chip, via
tools/tpu_window.sh the moment a relay window opens) and prints one JSON
line per check:

    {"check": "pallas_bit_identity", "device": "tpu", "ok": true, ...}
    {"check": "bf16_quality", "device": "tpu", "rel_delta": ..., ...}

Exit code 0 iff every check passed.
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    from pydcop_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache(require_accelerator=False)

    import jax

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    device = str(jax.devices()[0].platform)
    ok = True

    n_vars = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    compiled = generate_coloring_arrays(
        n_vars, 3, graph="scalefree", m_edge=2, seed=7
    )
    dev = to_device(compiled)

    # --- Pallas vs lanes: identical trajectory, assignment and cost ----
    t0 = time.perf_counter()
    lanes = maxsum.solve(
        compiled, {"damping": 0.7, "layout": "lanes", "noise": 0.0},
        n_cycles=20, seed=7, dev=dev,
    )
    lanes_wall = time.perf_counter() - t0
    try:
        t0 = time.perf_counter()
        pallas = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "pallas", "noise": 0.0},
            n_cycles=20, seed=7, dev=dev,
        )
        pallas_wall = time.perf_counter() - t0
        identical = pallas.assignment == lanes.assignment
        ok &= identical
        print(json.dumps({
            "check": "pallas_bit_identity",
            "device": device,
            "n_vars": n_vars,
            "ok": bool(identical),
            "lanes_cost": lanes.cost,
            "pallas_cost": pallas.cost,
            "lanes_wall_s": round(lanes_wall, 4),
            "pallas_wall_s": round(pallas_wall, 4),
        }))
    except Exception as exc:  # noqa: BLE001 — record, don't die
        ok = False
        print(json.dumps({
            "check": "pallas_bit_identity",
            "device": device,
            "ok": False,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
    sys.stdout.flush()

    # --- bf16 planes: quality within the measured envelope of f32, zero
    # extra violations.  The delta is instance- AND hardware-dependent
    # (BP under message rounding): the 100k bench instance measures
    # ~0.2% and the 20k default 1.6% on CPU; the same 20k instance
    # measured 2.22% on real TPU v5e (2026-07-31 capture — the TPU's
    # fma/rounding shifts near-tied argmins), so the accelerator
    # envelope is 3%.  The check flags degradation beyond the known
    # envelope, not the envelope itself -------------------------------
    try:
        f32 = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "lanes"},
            n_cycles=30, seed=7, dev=dev,
        )
        t0 = time.perf_counter()
        bf16 = maxsum.solve(
            compiled,
            {"damping": 0.7, "layout": "lanes", "precision": "bf16"},
            n_cycles=30, seed=7, dev=dev,
        )
        bf16_wall = time.perf_counter() - t0
        rel = (
            abs(bf16.cost - f32.cost) / max(1e-9, abs(f32.cost))
        )
        envelope = 0.02 if device == "cpu" else 0.03  # accelerators: 3%
        good = rel < envelope and bf16.violations <= f32.violations
        ok &= good
        print(json.dumps({
            "check": "bf16_quality",
            "device": device,
            "n_vars": n_vars,
            "ok": bool(good),
            "f32_cost": f32.cost,
            "bf16_cost": bf16.cost,
            "rel_delta": round(rel, 6),
            "envelope": envelope,
            "f32_violations": f32.violations,
            "bf16_violations": bf16.violations,
            "bf16_wall_s": round(bf16_wall, 4),
        }))
    except Exception as exc:  # noqa: BLE001
        ok = False
        print(json.dumps({
            "check": "bf16_quality",
            "device": device,
            "ok": False,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
    sys.stdout.flush()

    # --- ELL layout (the bench layout since round 5) vs lanes on this
    # hardware: same math, different reduction order, so costs must agree
    # to float-reduction noise and violations exactly ------------------
    try:
        lanes_r = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "lanes", "noise": 0.0},
            n_cycles=30, seed=7, dev=dev,
        )
        t0 = time.perf_counter()
        ell_r = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "ell", "noise": 0.0},
            n_cycles=30, seed=7, dev=dev,
        )
        ell_wall = time.perf_counter() - t0
        rel = abs(ell_r.cost - lanes_r.cost) / max(1e-9, abs(lanes_r.cost))
        good = rel < 1e-4 and ell_r.violations == lanes_r.violations
        ok &= good
        print(json.dumps({
            "check": "ell_layout_parity",
            "device": device,
            "n_vars": n_vars,
            "ok": bool(good),
            "lanes_cost": lanes_r.cost,
            "ell_cost": ell_r.cost,
            "rel_delta": round(rel, 8),
            "ell_wall_s": round(ell_wall, 4),
        }))
    except Exception as exc:  # noqa: BLE001
        ok = False
        print(json.dumps({
            "check": "ell_layout_parity",
            "device": device,
            "ok": False,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
    sys.stdout.flush()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
