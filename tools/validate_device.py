"""On-device validation of the Pallas kernel and bf16 message planes.

Round-3 verdict item 2: `compile/pallas_kernels.py` was pinned
bit-identical to the lanes path only under the interpreter, and the bf16
quality delta was measured on CPU.  This script runs both comparisons on
whatever backend jax resolves (intended: the real TPU chip, via
tools/tpu_window.sh the moment a relay window opens) and prints one JSON
line per check:

    {"check": "pallas_bit_identity", "device": "tpu", "ok": true, ...}
    {"check": "bf16_quality", "device": "tpu", "rel_delta": ..., ...}

Exit code 0 iff every check passed.
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Stated bf16 quality budget (docs/usage/algo_ref.md documents the same
# numbers next to the ``precision`` parameter): per config, the bf16
# final cost may regress at most 1% vs f32, with zero hard-constraint
# violations.  Measured deltas for the record (signed, bf16-minus-f32):
# ~+0.2% on the 100k bench instance and +1.6%-abs/<=1%-regression band
# on the 20k default on CPU; +2.22% was observed ONCE on real v5e
# (2026-07-31) and is now a FINDING the budget fails, not an envelope
# the budget hides — if the next TPU window reproduces it, bf16 loses
# its recommendation on that config instead of the gate stretching.
BF16_COST_REGRESSION_BUDGET = 0.01
BF16_VIOLATIONS_BUDGET = 0


def _bf16_configs(compiled, dev, n_vars):
    """The per-config gate set: the CLI-sized scalefree instance plus the
    config-2-shaped random instance (distinct degree distributions reach
    different argmin-tie structure, which is exactly where message
    rounding bites)."""
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    yield (f"scalefree_{n_vars}", compiled, dev)
    random_1k = generate_coloring_arrays(
        1000, 3, graph="random", p_edge=0.005, seed=11
    )
    yield ("random_1k", random_1k, to_device(random_1k))


def main() -> int:
    from pydcop_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache(require_accelerator=False)

    import jax

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    device = str(jax.devices()[0].platform)
    ok = True

    n_vars = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    compiled = generate_coloring_arrays(
        n_vars, 3, graph="scalefree", m_edge=2, seed=7
    )
    dev = to_device(compiled)

    # --- Pallas vs lanes: identical trajectory, assignment and cost ----
    t0 = time.perf_counter()
    lanes = maxsum.solve(
        compiled, {"damping": 0.7, "layout": "lanes", "noise": 0.0},
        n_cycles=20, seed=7, dev=dev,
    )
    lanes_wall = time.perf_counter() - t0
    try:
        t0 = time.perf_counter()
        pallas = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "pallas", "noise": 0.0},
            n_cycles=20, seed=7, dev=dev,
        )
        pallas_wall = time.perf_counter() - t0
        identical = pallas.assignment == lanes.assignment
        ok &= identical
        print(json.dumps({
            "check": "pallas_bit_identity",
            "device": device,
            "n_vars": n_vars,
            "ok": bool(identical),
            "lanes_cost": lanes.cost,
            "pallas_cost": pallas.cost,
            "lanes_wall_s": round(lanes_wall, 4),
            "pallas_wall_s": round(pallas_wall, 4),
        }))
    except Exception as exc:  # noqa: BLE001 — record, don't die
        ok = False
        print(json.dumps({
            "check": "pallas_bit_identity",
            "device": device,
            "ok": False,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
    sys.stdout.flush()

    # --- bf16 planes: the STATED quality budget (PR 8, replacing the 3%
    # envelope that was fit to the last observed failure): per config,
    # the bf16 solve may end at most BF16_COST_REGRESSION_BUDGET WORSE
    # than the f32 final cost (signed — a better bf16 cost passes
    # trivially; abs-delta punished improvements) and must satisfy every
    # hard constraint (0 violations, same bar the f32 run meets on these
    # configs).  One JSON line and one pass/fail PER config ------------
    for cfg_name, cfg_compiled, cfg_dev in _bf16_configs(
        compiled, dev, n_vars
    ):
        try:
            f32 = maxsum.solve(
                cfg_compiled, {"damping": 0.7, "layout": "lanes"},
                n_cycles=30, seed=7, dev=cfg_dev,
            )
            t0 = time.perf_counter()
            bf16 = maxsum.solve(
                cfg_compiled,
                {"damping": 0.7, "layout": "lanes", "precision": "bf16"},
                n_cycles=30, seed=7, dev=cfg_dev,
            )
            bf16_wall = time.perf_counter() - t0
            regression = (bf16.cost - f32.cost) / max(1e-9, abs(f32.cost))
            # the f32 baseline must itself meet the 0-violation bar —
            # otherwise the config cannot judge bf16 and the failure is
            # attributed to the BASELINE, not to message rounding
            baseline_ok = f32.violations == BF16_VIOLATIONS_BUDGET
            bf16_ok = (
                regression <= BF16_COST_REGRESSION_BUDGET
                and bf16.violations == BF16_VIOLATIONS_BUDGET
            )
            good = baseline_ok and bf16_ok
            ok &= good
            rec = {
                "check": "bf16_quality",
                "config": cfg_name,
                "device": device,
                "n_vars": int(cfg_compiled.n_vars),
                "ok": bool(good),
                "f32_cost": f32.cost,
                "bf16_cost": bf16.cost,
                "cost_regression": round(regression, 6),
                "budget": BF16_COST_REGRESSION_BUDGET,
                "f32_violations": f32.violations,
                "bf16_violations": bf16.violations,
                "violations_budget": BF16_VIOLATIONS_BUDGET,
                "bf16_wall_s": round(bf16_wall, 4),
            }
            if not baseline_ok:
                rec["note"] = (
                    "f32 baseline misses the 0-violation bar on this "
                    "config; bf16 is not being judged"
                )
            print(json.dumps(rec))
        except Exception as exc:  # noqa: BLE001
            ok = False
            print(json.dumps({
                "check": "bf16_quality",
                "config": cfg_name,
                "device": device,
                "ok": False,
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }))
        sys.stdout.flush()

    # --- ELL layout (the bench layout since round 5) vs lanes on this
    # hardware: same math, different reduction order, so costs must agree
    # to float-reduction noise and violations exactly ------------------
    try:
        lanes_r = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "lanes", "noise": 0.0},
            n_cycles=30, seed=7, dev=dev,
        )
        t0 = time.perf_counter()
        ell_r = maxsum.solve(
            compiled, {"damping": 0.7, "layout": "ell", "noise": 0.0},
            n_cycles=30, seed=7, dev=dev,
        )
        ell_wall = time.perf_counter() - t0
        rel = abs(ell_r.cost - lanes_r.cost) / max(1e-9, abs(lanes_r.cost))
        good = rel < 1e-4 and ell_r.violations == lanes_r.violations
        ok &= good
        print(json.dumps({
            "check": "ell_layout_parity",
            "device": device,
            "n_vars": n_vars,
            "ok": bool(good),
            "lanes_cost": lanes_r.cost,
            "ell_cost": ell_r.cost,
            "rel_delta": round(rel, 8),
            "ell_wall_s": round(ell_wall, 4),
        }))
    except Exception as exc:  # noqa: BLE001
        ok = False
        print(json.dumps({
            "check": "ell_layout_parity",
            "device": device,
            "ok": False,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
    sys.stdout.flush()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
