#!/usr/bin/env python
"""graftdur durability smoke (``make durability-smoke``; docs/durability.md).

The kill-and-resume soak the subsystem exists for, end-to-end through the
real CLI:

1. **Chaos-killed device solve resumes bit-identically.**  A 1500-variable
   scale-free MaxSum solve (direct mode, ~6k factor-graph computations —
   far past what the thread runtime hosts) runs three times: fault-free
   (the reference trajectory), checkpointing under a graftchaos
   ``kill_process`` schedule that kills the WHOLE PROCESS abruptly
   mid-solve (``os._exit`` — no flushing, no cleanup), and resumed from
   the checkpoints the corpse left behind.  The resumed run must finish
   with the EXACT fault-free assignment and cost — seeded per-cycle keys
   make bit-identity the contract, not a tolerance.

2. **Thread-runtime kill/resume dead-letters nothing.**  The same
   kill-then-resume through the full agent runtime (orchestrator +
   agents) on the small coloring instance: the resumed run must match the
   fault-free assignment and report ZERO dead letters.

Exit 0 on pass; prints a PASS/FAIL line per gate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu")

N_VARS = 1500
N_CYCLES = 12_000
SEED = 2
#: the kill must land MID-SOLVE: after YAML load + compile + the first
#: chunk's jit (~3-4 s on this class of CPU) but before the ~10 s device
#: scan finishes; the seconds-cadence below guarantees early checkpoints
#: on machines where cycles are slow
KILL_AT_S = 6.0
EVERY = 256
EVERY_S = 0.5

failures = []


def gate(name: str, ok: bool, detail: str = "") -> None:
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        failures.append(name)


def cli(*args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV, cwd=REPO,
    )


def solve_json(out_path, *args, timeout=300):
    r = cli("--output", out_path, *args, timeout=timeout)
    if r.returncode != 0:
        return r, None
    with open(out_path, "r", encoding="utf-8") as f:
        return r, json.load(f)


def main() -> int:
    work = tempfile.mkdtemp(prefix="durability_smoke_")
    gc_yaml = os.path.join(work, "gc.yaml")
    kill_yaml = os.path.join(work, "kill.yaml")
    quiet_yaml = os.path.join(work, "quiet.yaml")
    with open(kill_yaml, "w", encoding="utf-8") as f:
        f.write(f"seed: 0\nevents:\n  - kill_process: true\n    at: {KILL_AT_S}\n")
    with open(quiet_yaml, "w", encoding="utf-8") as f:
        f.write("seed: 0\nevents: []\n")

    # -- problem generation (once, shared by all three runs) -----------
    r = cli(
        "generate", "graph_coloring", "-v", str(N_VARS), "-c", "3",
        "-g", "scalefree", "--m_edge", "2", "--seed", "9", "--soft",
    )
    if r.returncode != 0:
        print(r.stderr[-2000:])
        gate("generate problem", False)
        return 1
    with open(gc_yaml, "w", encoding="utf-8") as f:
        f.write(r.stdout)

    solve_args = [
        "solve", "-a", "maxsum", "-p", "damping:0.7",
        "-p", f"stop_cycle:{N_CYCLES}", "-n", str(N_CYCLES),
        "--seed", str(SEED), gc_yaml,
    ]

    # -- part 1: fault-free reference trajectory -----------------------
    r, ref = solve_json(os.path.join(work, "ref.json"), *solve_args)
    gate(
        "fault-free reference solve",
        ref is not None and ref.get("status") == "FINISHED",
        f"cost={ref.get('cost') if ref else None}",
    )
    if ref is None:
        print(r.stderr[-2000:])
        return 1

    # -- part 1: chaos-killed checkpointed run -------------------------
    ck = os.path.join(work, "ck")
    r = cli(
        "--output", os.path.join(work, "killed.json"), *solve_args,
        "--checkpoint", ck, "--checkpoint-every", str(EVERY),
        "--checkpoint-every-seconds", str(EVERY_S), "--checkpoint-keep",
        "4", "--fault-schedule", kill_yaml,
    )
    gate(
        "chaos kill_process killed the run abruptly",
        r.returncode == 137
        and not os.path.exists(os.path.join(work, "killed.json")),
        f"rc={r.returncode}",
    )
    cks = sorted(f for f in os.listdir(ck) if f.endswith(".npz")) if (
        os.path.isdir(ck)
    ) else []
    newest = int(cks[-1][len("ckpt-c"):-len(".npz")]) if cks else None
    gate(
        "checkpoints written before the kill",
        bool(cks) and newest is not None and 0 < newest < N_CYCLES,
        f"{len(cks)} checkpoint(s), newest cycle {newest}",
    )
    if not cks:
        return 1

    # -- part 1: resume to the fault-free assignment -------------------
    r, res = solve_json(
        os.path.join(work, "resumed.json"), *solve_args,
        "--resume", ck, "--checkpoint", ck,
        "--checkpoint-every", str(EVERY), "--checkpoint-keep", "4",
    )
    if res is None:
        print(r.stderr[-2000:])
        gate("resumed solve finished", False)
        return 1
    gate(
        "resumed solve finished",
        res.get("status") == "FINISHED" and res.get("cycle") == ref.get("cycle"),
        f"cycle={res.get('cycle')}",
    )
    gate(
        "resume is bit-identical to the fault-free run",
        res["assignment"] == ref["assignment"]
        and res["cost"] == ref["cost"],
        f"cost {res['cost']} vs {ref['cost']}",
    )

    # -- part 2: thread-runtime kill/resume, zero dead letters ---------
    small = os.path.join(REPO, "tests", "instances", "graph_coloring.yaml")
    small_args = [
        "solve", "-a", "dsa", "-m", "thread", "-n", "80", "--seed", "0",
        small,
    ]
    r, tref = solve_json(os.path.join(work, "tref.json"), *small_args)
    gate(
        "thread-mode reference solve",
        tref is not None and tref.get("status") == "FINISHED",
    )
    ck2 = os.path.join(work, "ck2")
    kill2 = os.path.join(work, "kill2.yaml")
    with open(kill2, "w", encoding="utf-8") as f:
        # the small solve finishes in well under 3 s; the orchestrator
        # waits for the fault timeline (machine-speed-independent
        # replay), so the kill still lands and the result is never
        # written — what survives is the checkpoint trail
        f.write("seed: 0\nevents:\n  - kill_process: true\n    at: 3.0\n")
    r = cli(
        "--output", os.path.join(work, "tkilled.json"), *small_args,
        "--checkpoint", ck2, "--checkpoint-every", "16",
        "--checkpoint-keep", "8", "--fault-schedule", kill2,
    )
    cks2 = sorted(f for f in os.listdir(ck2) if f.endswith(".npz")) if (
        os.path.isdir(ck2)
    ) else []
    gate(
        "thread-runtime run killed with checkpoints on disk",
        r.returncode == 137 and bool(cks2)
        and not os.path.exists(os.path.join(work, "tkilled.json")),
        f"rc={r.returncode}, {len(cks2)} checkpoint(s)",
    )
    # resume from a MID-RUN snapshot (not the final one) so real cycles
    # remain to replay through the thread runtime
    mid = os.path.join(ck2, "ckpt-c000000048.npz")
    r, tres = solve_json(
        os.path.join(work, "tres.json"), *small_args,
        "--resume", mid if os.path.exists(mid) else ck2,
        "--fault-schedule", quiet_yaml,
    )
    if tres is None:
        print(r.stderr[-2000:])
        gate("thread-runtime resume", False)
    else:
        gate(
            "thread-runtime resume matches fault-free assignment",
            tref is not None
            and tres.get("assignment") == tref.get("assignment"),
        )
        dead = (tres.get("chaos") or {}).get("dead_letters")
        gate("zero dead letters", dead == 0, f"dead_letters={dead}")

    print(
        f"\ndurability-smoke: {'PASS' if not failures else 'FAIL'} "
        f"(workdir {work})"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
