#!/bin/bash
# Watch for a TPU window and capture everything the moment one opens.
#
# The tunneled TPU relay in this environment flips between healthy,
# fast-error, and indefinite-hang states, with outages measured in hours
# (see BASELINE.md round-3 notes).  Run this detached —
#
#   setsid nohup tools/tpu_window.sh > /tmp/tpu_window.log 2>&1 &
#
# — and it polls cheaply (subprocess probe, hard timeout) until the relay
# answers, then in one window: runs the benchmark gate (which also warms
# the persistent .jax_cache for later runs), the per-op kernel profiler
# with achieved-GB/s output, and the 1M-variable stretch config.
set -u
cd "$(dirname "$0")/.."
POLL_S=${POLL_S:-170}
TRIES=${TRIES:-200}
for _ in $(seq 1 "$TRIES"); do
  if timeout 45 python -c \
      "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null
  then
    echo "RELAY UP at $(date -u +%H:%M:%S)"
    mkdir -p TPU_CAPTURE
    # generous TPU budget: the round-5 ELL and fused-DPOP programs are
    # new, so their first window pays fresh remote compiles (~2-3 min
    # each) before the persistent .jax_cache warms
    timeout 2100 env BENCH_TPU_BUDGET_S=1800 python bench.py \
      2>/tmp/tpu_bench.err \
      | tee /tmp/tpu_bench.out TPU_CAPTURE/bench.jsonl
    echo "BENCH DONE rc=$? at $(date -u +%H:%M:%S)"
    timeout 900 env PYTHONPATH=/root/.axon_site:"$PWD" \
      python tools/profile_maxsum.py 2>&1 \
      | tee /tmp/tpu_profile.out > TPU_CAPTURE/profile.txt
    echo "PROFILE DONE rc=$? at $(date -u +%H:%M:%S)"
    timeout 900 python tools/validate_device.py 2>&1 \
      | tee /tmp/tpu_validate.out > TPU_CAPTURE/validate.jsonl
    echo "VALIDATE DONE rc=$? at $(date -u +%H:%M:%S)"
    timeout 900 python bench_all.py 6 2>/dev/null \
      | tee /tmp/tpu_1m.out > TPU_CAPTURE/stretch.jsonl
    echo "1M DONE rc=$? at $(date -u +%H:%M:%S)"
    # persist the capture even if nobody is watching the session
    git add TPU_CAPTURE >/dev/null 2>&1 \
      && git commit -q -m "Record TPU window capture (bench, per-op profile, device validation, 1M stretch)

No-Verification-Needed: measurement artifacts only" \
      || echo "git commit of capture failed (continuing)"
    exit 0
  fi
  sleep "$POLL_S"
done
echo "RELAY NEVER CAME UP after $TRIES probes"
exit 1
