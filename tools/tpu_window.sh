#!/bin/bash
# Watch for a TPU window and capture everything the moment one opens.
#
# The tunneled TPU relay in this environment flips between healthy,
# fast-error, and indefinite-hang states, with outages measured in hours
# (see BASELINE.md round-3 notes).  Run this detached —
#
#   setsid nohup tools/tpu_window.sh > /tmp/tpu_window.log 2>&1 &
#
# — and it polls cheaply (subprocess probe, hard timeout) until the relay
# answers, then runs the round-6 capture checklist (ROADMAP item 1):
#
#   1. `pydcop_tpu capture -o captures/tpu_r06` — ONE command, configs
#      1-9 (incl. serving config 8 and partition config 9), with
#      profiling + HLO dumps + kernelprof per-op attribution + the
#      jit/readback census all forced on.  The bundle is self-describing
#      (manifest + per-config records) and is written per-config, so
#      even a window that dies mid-run leaves a valid partial capture.
#      The capture verb warns LOUDLY if configs 2/3/4 lose their per-op
#      block — do not call the window healthy if it does.
#   2. device validation (bit-identity, bf16, pallas) — unchanged.
#   3. the 1M-variable stretch config into the same bundle.
#   4. `pydcop_tpu capture diff captures/r05_tpu captures/tpu_r06` —
#      the round-5-vs-round-6 per-op attribution, captured alongside.
#
# Afterwards, compare against the CPU trajectory with
#   pydcop_tpu capture diff 'BENCH_*.json' captures/tpu_r06
# and let `make bench-gate` judge the records (its failure output now
# carries the same per-op attribution).
set -u
cd "$(dirname "$0")/.."
POLL_S=${POLL_S:-170}
TRIES=${TRIES:-200}
OUT=${OUT:-captures/tpu_r06}
for _ in $(seq 1 "$TRIES"); do
  if timeout 45 python -c \
      "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null
  then
    echo "RELAY UP at $(date -u +%H:%M:%S)"
    # generous TPU budget: first window pays fresh remote compiles
    # (~2-3 min each) before the persistent .jax_cache warms; configs
    # 1-9 = the five BASELINE configs + mixed (7) + serving (8) +
    # partition (9).  --force: resume an interrupted earlier window
    # into the same bundle.
    timeout 3000 python -m pydcop_tpu --platform tpu \
      capture -o "$OUT" --force \
      --configs 1 2 3 4 5 7 8 9 \
      --notes "round-6 TPU window capture (tools/tpu_window.sh)" \
      2>&1 | tee /tmp/tpu_capture.out
    echo "CAPTURE DONE rc=$? at $(date -u +%H:%M:%S)"
    timeout 900 python tools/validate_device.py 2>&1 \
      | tee /tmp/tpu_validate.out > "$OUT"/validate.jsonl
    echo "VALIDATE DONE rc=$? at $(date -u +%H:%M:%S)"
    timeout 1200 python -m pydcop_tpu --platform tpu \
      capture -o "$OUT" --force --configs 6 2>&1 \
      | tee /tmp/tpu_1m.out
    echo "1M DONE rc=$? at $(date -u +%H:%M:%S)"
    # round-5 vs round-6: the per-op story of the window, kept with it
    python -m pydcop_tpu capture diff captures/r05_tpu "$OUT" \
      --json "$OUT"/diff_vs_r05.json 2>&1 | tee /tmp/tpu_diff.out
    echo "DIFF DONE rc=$? at $(date -u +%H:%M:%S)"
    # persist the capture even if nobody is watching the session
    # (profiler traces stay local: captures/tpu_*/profile/ is ignored)
    git add "$OUT" >/dev/null 2>&1 \
      && git commit -q -m "Record TPU round-6 capture bundle (configs 1-9, validation, r05 diff)

No-Verification-Needed: measurement artifacts only" \
      || echo "git commit of capture failed (continuing)"
    exit 0
  fi
  sleep "$POLL_S"
done
echo "RELAY NEVER CAME UP after $TRIES probes"
exit 1
