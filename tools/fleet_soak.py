"""graftha soak gate (``make fleet-soak``, docs/serving.md "HA fleet").

Two phases against real ``pydcop_tpu router``-spawned serve fleets:

- **Placement A/B** — the same two-bucket serial workload driven through
  an affinity-placed fleet and a round-robin fleet (2 workers each).
  Affinity compiles each bucket once FLEET-wide, round-robin once per
  (worker, bucket) pair; with 300 samples the nearest-rank p99 lands on
  a cold compile for round-robin and stays warm for affinity.  Gates:
  both arms drain clean, zero lost tenants, and the soak record shows
  ``p99_affinity < p99_round_robin``.
- **Chaos failover** — 3 spawned workers behind an affinity router with
  a router-local forward-availability SLO.  Mixed-priority traffic,
  then a chaos SIGKILL of the bucket-owning worker mid-solve and a
  restart on the same port.  Gates: zero lost tenants (every non-shed
  tenant terminal ``done``, costs bit-identical to an in-process
  ``solve_one`` reference — rescued tenants re-solve from scratch with
  their original seeds), the fast-burn alert trips AND resolves (low
  shed with ``Retry-After``, normal deferred then released), every
  federated counter stays monotone through the kill, the fleet census
  returns to 3/3 after the restart, and the router drains clean with
  failover/from-scratch accounting in its final report.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fleet_smoke import MonotoneWatch, _get  # noqa: E402

SOAK_RECORD = "/tmp/pydcop_fleet_soak.json"
AB_TENANTS = 300  # nearest-rank p99 boundary: 4 colds flip it, 3 don't


def _fail(msg: str) -> int:
    print(f"FLEET-SOAK FAIL: {msg}")
    return 1


def _post(url, doc, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.getcode(), json.loads(r.read())


def make_bucket_docs():
    """Two DCOPs in DIFFERENT affinity buckets (9 vs 16 variables)."""
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_graph_coloring,
    )
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    return [
        dcop_yaml(generate_graph_coloring(
            n, 3, graph="grid", seed=42, extensive=True
        ))
        for n in (9, 16)
    ]


def reference_cost(doc, n_cycles, seed):
    """The bit-identity oracle: the same spec solved in-process."""
    from pydcop_tpu.compile.core import compile_dcop
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.serve import SolveRequest, solve_one

    req = SolveRequest("ref", compile_dcop(load_dcop(doc)), "dsa", {},
                       n_cycles, seed)
    return solve_one(req).result.cost


def start_router(extra, output, env):
    """Spawn ``pydcop_tpu router``; returns (proc, base_url, workers)
    with workers = {name: {"pid": .., "port": ..}} parsed from the
    machine-readable ROUTER_WORKER announcements."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu", "--output", output, "router"]
        + extra,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env, cwd=REPO,
    )
    workers = {}
    port = None
    deadline = time.time() + 300
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line and proc.poll() is not None:
            break
        if line.startswith("ROUTER_WORKER "):
            fields = dict(
                kv.split("=", 1) for kv in line.split()[1:]
            )
            workers[fields["name"]] = {
                "pid": int(fields["pid"]), "port": int(fields["port"]),
            }
        elif line.startswith("ROUTER_PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
    if port is None:
        proc.kill()
        raise AssertionError("router never announced its port")
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()
    return proc, f"http://127.0.0.1:{port}", workers


def kill_fleet(proc, workers):
    """Last-resort cleanup: a SIGKILLed router can't drain its spawned
    workers, so reap them by pid too."""
    if proc.poll() is None:
        proc.kill()
    for w in workers.values():
        try:
            os.kill(w["pid"], signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def wait_fleet_up(base, n, timeout=60):
    """Block until the router's census reports n live workers (the
    collector needs one scrape sweep before anything is placeable)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = _get(base + "/status")
        if st["workers_up"] == n:
            return st
        time.sleep(0.1)
    raise AssertionError(
        f"census never reached {n} workers: {st['workers_up']}"
    )


def submit(base, doc, tenant, n_cycles=10, seed=0, priority=None):
    body = {
        "dcop_yaml": doc, "algo": "dsa", "n_cycles": n_cycles,
        "seed": seed, "tenant": tenant,
    }
    if priority:
        body["priority"] = priority
    return _post(base + "/solve", body)


def wait_done(base, tenant, timeout=300):
    deadline = time.time() + timeout
    while time.time() < deadline:
        doc = _get(f"{base}/result/{tenant}", timeout=30)
        if doc["status"] in ("done", "failed", "killed"):
            return doc
        time.sleep(0.02)
    raise AssertionError(f"{tenant} never reached a terminal state")


def stop_router(proc, output):
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=300)
    with open(output, "r", encoding="utf-8") as f:
        report = json.load(f)
    return rc, report


# ---------------------------------------------------------------------------
# phase A: placement A/B on measured queue p99
# ---------------------------------------------------------------------------


def run_ab_arm(strategy, docs, env):
    """One A/B arm: 2 spawned workers, 300 serially-driven tenants over
    two buckets, per-tenant submit->done latency measured client-side.
    Serial driving keeps every sample's latency dominated by ITS OWN
    batch (window + solve + compile-if-cold), so the cold count is
    exactly the number of (worker, bucket) first meetings."""
    output = f"/tmp/pydcop_fleet_soak_{strategy}.json"
    state = f"/tmp/pydcop_fleet_soak_state_{strategy}"
    proc, base, _workers = start_router(
        [
            "--spawn", "2", "--placement", strategy, "--port", "0",
            "--interval", "0.25", "--window-ms", "5",
            "--state-dir", state,
        ],
        output, env,
    )
    try:
        wait_fleet_up(base, 2)
        # paired head so round-robin provably sprays both buckets
        # across both workers; then alternate
        seq = [0, 0, 1, 1] + [i % 2 for i in range(AB_TENANTS - 4)]
        lat = []
        for i, b in enumerate(seq):
            tid = f"{strategy[0]}{i}"
            t0 = time.monotonic()
            code, ans = submit(base, docs[b], tid, n_cycles=10, seed=i)
            assert code == 200, f"{strategy} submit {tid}: {code} {ans}"
            rec = wait_done(base, tid)
            assert rec["status"] == "done", f"{strategy} {tid}: {rec}"
            lat.append((time.monotonic() - t0) * 1e3)
        rc, report = stop_router(proc, output)
        assert rc == 0 and report["drained"], (
            f"{strategy} arm did not drain clean: rc={rc}"
        )
        assert report["tenant_counts"].get("done") == AB_TENANTS, (
            f"{strategy} lost tenants: {report['tenant_counts']}"
        )
        from pydcop_tpu.telemetry.metrics import percentile

        lat.sort()
        return {
            "strategy": strategy,
            "tenants": AB_TENANTS,
            "p50_ms": round(percentile(lat, 0.5), 2),
            "p99_ms": round(percentile(lat, 0.99), 2),
            "max_ms": round(lat[-1], 2),
            "placement": report["placement"],
        }
    finally:
        kill_fleet(proc, _workers)


# ---------------------------------------------------------------------------
# phase B: chaos failover under SLO-driven admission
# ---------------------------------------------------------------------------


def run_chaos(docs, env):  # noqa: C901 — one linear chaos script
    output = "/tmp/pydcop_fleet_soak_chaos.json"
    state = "/tmp/pydcop_fleet_soak_state_chaos"
    ref_short = [reference_cost(d, 10, 7) for d in docs]
    ref_long = reference_cost(docs[0], 1500, 11)

    proc, base, workers = start_router(
        [
            "--spawn", "3", "--placement", "affinity", "--port", "0",
            "--interval", "0.5", "--stale-after", "4",
            "--window-ms", "30", "--retry-attempts", "2",
            "--defer-max", "6",
            "--router-slo", "fwd=availability>=99.9%@300s",
            "--state-dir", state,
        ],
        output, env,
    )
    revived = None
    expect_done = {}  # tenant -> expected cost (None = just terminal)
    record = {"workers": workers}
    try:
        wait_fleet_up(base, 3)
        watch = MonotoneWatch(base)
        watch.start()

        # ---- wave 1: mixed-priority traffic, whole fleet up -----------
        prios = ["high", "normal", "low", "normal"]
        for i in range(12):
            tid = f"mix{i}"
            code, _ans = submit(
                base, docs[i % 2], tid, n_cycles=10, seed=7,
                priority=prios[i % 4],
            )
            assert code == 200, f"wave1 {tid} not admitted: {code}"
            expect_done[tid] = ref_short[i % 2]
        for tid in list(expect_done):
            wait_done(base, tid)

        # ---- pick the victim: the worker OWNING bucket 0 --------------
        st = _get(base + "/status")
        from pydcop_tpu.serve.router import affinity_key

        akey0 = affinity_key({"dcop_yaml": docs[0], "algo": "dsa"})
        victim = st["placement"]["buckets"].get(akey0)
        if victim not in workers:
            return _fail(
                f"no worker owns bucket {akey0}: {st['placement']}"
            )
        record["victim"] = victim
        record["bucket"] = akey0

        # let wave-1 forwards age out of the 5s fast-long window so the
        # kill's bad forwards dominate the burn
        time.sleep(6.0)

        # ---- in-flight tenants on the victim, then SIGKILL ------------
        for i in range(3):
            tid = f"long{i}"
            code, ans = submit(
                base, docs[0], tid, n_cycles=1500, seed=11,
                priority="high",
            )
            assert code == 200 and ans["worker"] == victim, (
                f"{tid} not on victim: {ans}"
            )
            expect_done[tid] = ref_long
        os.kill(workers[victim]["pid"], signal.SIGKILL)
        # the next forwards at the dead worker exhaust their retries:
        # bad forward outcomes -> the router's own objective burns
        for i in range(3):
            tid = f"burst{i}"
            code, _ans = submit(
                base, docs[0], tid, n_cycles=10, seed=7,
                priority="normal",
            )
            assert code in (200, 202), f"{tid}: {code}"
            expect_done[tid] = ref_short[0]

        # ---- gate: the fast-burn alert trips, admission reacts --------
        deadline = time.time() + 15
        shedding = False
        while time.time() < deadline:
            st = _get(base + "/status")
            if st["admission"]["mode"] == "shedding":
                shedding = True
                break
            time.sleep(0.1)
        if not shedding:
            return _fail(
                "fast-burn alert never tripped after the kill: "
                f"{st['admission']}"
            )
        try:
            submit(base, docs[1], "shed-me", n_cycles=10, seed=7,
                   priority="low")
            return _fail("low-priority tenant admitted while shedding")
        except urllib.error.HTTPError as e:
            if e.code != 503 or not e.headers.get("Retry-After"):
                return _fail(
                    f"shed answered {e.code} without Retry-After"
                )
            body = json.loads(e.read())
            if not body.get("shed") or not body.get("peers"):
                return _fail(f"shed 503 not structured: {body}")
        code, ans = submit(
            base, docs[1], "parked", n_cycles=10, seed=7,
            priority="normal",
        )
        if code != 202 or not ans.get("deferred"):
            return _fail(f"normal not deferred while shedding: {ans}")
        expect_done["parked"] = ref_short[1]
        record["shed_alerts"] = st["admission"]["alerts"]

        # ---- restart the victim on the SAME port ----------------------
        vport = workers[victim]["port"]
        revived = subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "serve",
                "--port", str(vport), "--window-ms", "30",
                "--checkpoint", os.path.join(state, victim),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=REPO,
        )
        deadline = time.time() + 120
        announced = False
        while time.time() < deadline:
            line = revived.stdout.readline()
            if line.startswith("SERVE_PORT="):
                announced = int(line.strip().split("=", 1)[1]) == vport
                break
        if not announced:
            return _fail(f"revived {victim} never bound port {vport}")
        threading.Thread(
            target=lambda: [None for _ in revived.stdout], daemon=True
        ).start()

        # ---- gate: alert resolves, census back to 3/3 -----------------
        deadline = time.time() + 60
        recovered = False
        while time.time() < deadline:
            st = _get(base + "/status")
            if (
                st["workers_up"] == 3
                and st["admission"]["mode"] == "open"
            ):
                recovered = True
                break
            time.sleep(0.2)
        if not recovered:
            return _fail(
                f"fleet never recovered: up={st['workers_up']} "
                f"admission={st['admission']['mode']}"
            )
        slo = _get(base + "/slo")
        states = {
            (t["objective"], t["state"]) for t in slo["transitions"]
        }
        if ("fwd", "firing") not in states or (
            "fwd", "resolved"
        ) not in states:
            return _fail(
                f"router SLO never tripped AND recovered: {slo['transitions']}"
            )

        # ---- recovery traffic, then: zero lost tenants ----------------
        for i in range(4):
            tid = f"post{i}"
            code, _ans = submit(
                base, docs[i % 2], tid, n_cycles=10, seed=7,
            )
            assert code in (200, 202), f"{tid}: {code}"
            expect_done[tid] = ref_short[i % 2]
        bad_costs = []
        for tid, want in expect_done.items():
            rec = wait_done(base, tid)
            if rec["status"] != "done":
                return _fail(f"tenant {tid} lost: {rec}")
            if want is not None and rec.get("cost") != want:
                bad_costs.append((tid, rec.get("cost"), want))
        if bad_costs:
            return _fail(
                "costs drifted from the in-process reference "
                f"(bit-identity broken): {bad_costs}"
            )

        watch.stop()
        if watch.violations:
            return _fail(
                f"federated counters went backwards: {watch.violations[:5]}"
            )
        if watch.scrapes < 5:
            return _fail(f"monotone watch barely ran: {watch.scrapes}")

        # ---- clean drain + failover accounting ------------------------
        rc, report = stop_router(proc, output)
        if rc != 0 or not report["drained"]:
            return _fail(f"router exited {rc}, drained={report['drained']}")
        adm = report["admission"]
        if adm["failovers"] < 1 or adm["from_scratch"] < 3:
            return _fail(f"failover accounting wrong: {adm}")
        if adm["shed"] < 1 or adm["deferred"] < 1:
            return _fail(f"admission accounting wrong: {adm}")
        trans = {
            (t["objective"], t["state"])
            for t in report.get("router_slo_transitions", [])
        }
        if ("fwd", "firing") not in trans:
            return _fail(f"final report lost the alert history: {trans}")
        record.update(
            {
                "tenants": len(expect_done),
                "admission": adm,
                "monotone_scrapes": watch.scrapes,
                "transitions": sorted(
                    f"{o}:{s}" for o, s in trans
                ),
            }
        )
        return record
    finally:
        kill_fleet(proc, workers)
        if revived is not None and revived.poll() is None:
            revived.kill()


def main() -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYDCOP_TPU_STATE_DIR"] = "/tmp/pydcop_fleet_soak_state"
    docs = make_bucket_docs()

    arms = {}
    for strategy in ("affinity", "round_robin"):
        arms[strategy] = run_ab_arm(strategy, docs, env)
        print(f"fleet-soak arm {strategy}: {arms[strategy]}")
    if not arms["affinity"]["p99_ms"] < arms["round_robin"]["p99_ms"]:
        return _fail(
            "affinity placement did not beat round-robin on queue p99: "
            f"{arms['affinity']['p99_ms']} vs "
            f"{arms['round_robin']['p99_ms']} ms"
        )

    chaos = run_chaos(docs, env)
    if isinstance(chaos, int):
        return chaos

    record = {"placement_ab": arms, "chaos": chaos}
    with open(SOAK_RECORD, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=2, sort_keys=True)
    print(
        "FLEET-SOAK PASS: affinity p99 "
        f"{arms['affinity']['p99_ms']}ms < round-robin "
        f"{arms['round_robin']['p99_ms']}ms over {AB_TENANTS} tenants/arm; "
        f"chaos kill of {chaos['victim']} rescued every tenant "
        f"(from_scratch={chaos['admission']['from_scratch']}, "
        f"shed={chaos['admission']['shed']}, "
        f"deferred={chaos['admission']['deferred']}), alert tripped and "
        f"recovered, {chaos['monotone_scrapes']} scrapes monotone, "
        f"clean drain -> {SOAK_RECORD}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
