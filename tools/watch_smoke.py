"""watch-smoke: graftwatch end-to-end gate (``make watch-smoke``).

One thread-mode run with tracing + the live metrics surface on, asserting
the two graftwatch acceptance bars:

1. **trace stitching quality** — >= 95% of message send flows (``"s"``)
   pair with a delivery flow event (``"t"``/``"f"``) on the receiving
   side (ISSUE 4 acceptance);
2. **live surface availability** — at least one successful ``/metrics``
   scrape lands MID-RUN (Prometheus text with known series), plus a
   ``/status`` read.

Exits non-zero (with a diagnosis) on any miss, like trace-smoke.
"""

import json
import os
import sys
import threading
import time
import urllib.request

# run as `python tools/watch_smoke.py` from the repo root: make the
# package importable without an install
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PASS_PCT = 95.0
INSTANCE = "tests/instances/graph_coloring.yaml"


def main() -> int:
    from pydcop_tpu.utils.platform import pin_cpu

    pin_cpu()

    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.infrastructure.run import run_local_thread_dcop
    from pydcop_tpu.telemetry import (
        flow_stats,
        metrics_registry,
        telemetry_off,
        tracer,
    )

    tracer.service = "orchestrator"
    tracer.reset()
    tracer.enabled = True
    metrics_registry.reset()
    metrics_registry.enabled = True

    scrapes = []
    status_docs = []
    stop_polling = threading.Event()

    def poll(port: int) -> None:
        while not stop_polling.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=1.0
                ) as r:
                    text = r.read().decode("utf-8")
                if "comms_messages_sent_total" in text:
                    scrapes.append(text)
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=1.0
                ) as r:
                    status_docs.append(json.loads(r.read()))
            except OSError:
                pass
            time.sleep(0.05)

    # a small message delay stretches the run so the poller demonstrably
    # scrapes MID-run, not after the fact
    orchestrator = run_local_thread_dcop(
        "dsa",
        load_dcop_from_file([INSTANCE]),
        n_cycles=5,
        delay=0.02,
        metrics_port=0,
    )
    poller = threading.Thread(
        target=poll, args=(orchestrator.metrics_server.port,), daemon=True
    )
    poller.start()
    try:
        orchestrator.deploy_computations()
        orchestrator.run(timeout=60)
        mid_run_scrapes = len(scrapes)
    finally:
        stop_polling.set()
        poller.join(timeout=5)
        try:
            orchestrator.stop_agents()
        finally:
            orchestrator.stop()

    events = tracer.events()
    stats = flow_stats(events)
    telemetry_off()

    failures = []
    if not stats["sends"]:
        failures.append("no message send flows recorded at all")
    elif stats["match_pct"] < PASS_PCT:
        failures.append(
            f"flow pairing {stats['match_pct']}% < {PASS_PCT}% "
            f"({stats['matched']}/{stats['sends']} sends matched)"
        )
    if mid_run_scrapes < 1:
        failures.append("no successful /metrics scrape landed mid-run")
    if not any(d.get("status") == "RUNNING" for d in status_docs):
        failures.append("/status never reported a RUNNING run")

    print(
        f"watch-smoke: {stats['sends']} sends, {stats['matched']} matched "
        f"({stats['match_pct']}%), {mid_run_scrapes} mid-run /metrics "
        f"scrapes, {len(status_docs)} /status reads"
    )
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("watch-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
