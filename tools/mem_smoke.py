"""mem-smoke: graftmem end-to-end gate (``make mem-smoke``).

Four seeded CPU checks against the ISSUE-20 acceptance bars
(docs/observability.md, graftmem):

1. **model vs measured** — a real maxsum solve with the opportunistic
   graftprof ``memory_analysis()`` path on: the analytic prediction must
   land within ±20% of XLA's own peak;
2. **OOM guardrail, direct path** — an explicit 1 KiB limit turns any
   real solve into a loud ``MemoryBudgetExceeded`` naming the breach
   (predicted vs budget, dominant component), never an XLA crash;
3. **live plane degradation** — CPU offers no ``memory_stats()``: the
   sampler must return None, COUNT the degradation
   (``mem.stats_unavailable``) and still publish the limit gauge;
4. **memplan verb** — the device-free capacity answers render through
   the real CLI (FITS verdict + max-vars answer, rc 0).

Exits non-zero (with a diagnosis) on any miss, like pulse-smoke.
"""

import os
import subprocess
import sys

# run as `python tools/mem_smoke.py` from the repo root: make the
# package importable without an install
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _model_vs_measured() -> list:
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.telemetry import metrics_registry, telemetry_off
    from pydcop_tpu.telemetry.memplane import (
        measured_peak_bytes, predict_solve_bytes,
    )
    from pydcop_tpu.telemetry.profiling import profiling

    failures = []
    # off-round size: a fresh XLA compile guarantees the analysis fires
    c = generate_coloring_arrays(509, 3, graph="random", p_edge=0.01, seed=20)
    metrics_registry.reset()
    metrics_registry.enabled = True
    profiling.opportunistic_memory = True
    try:
        maxsum.solve(c, {"damping": 0.5}, n_cycles=8, seed=0)
        peak = measured_peak_bytes()
    finally:
        telemetry_off()
    if peak is None:
        return ["no measured peak: opportunistic memory_analysis() missing"]
    pred = predict_solve_bytes(c, "maxsum", {"damping": 0.5}, n_cycles=8)
    ratio = pred["total_bytes"] / peak
    print(
        f"model vs measured: predicted {pred['total_bytes']:,} B, "
        f"XLA peak {peak:,.0f} B, ratio {ratio:.3f}"
    )
    if not 0.8 <= ratio <= 1.2:
        failures.append(f"model ratio {ratio:.3f} outside ±20%")
    return failures


def _guard_refusal() -> list:
    from pydcop_tpu.algorithms import dsa
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.telemetry import telemetry_off
    from pydcop_tpu.telemetry.memplane import (
        MemoryBudgetExceeded, memguard,
    )

    failures = []
    c = generate_coloring_arrays(49, 3, graph="grid", seed=1)
    memguard.configure(enabled=True, reserve_pct=10.0, limit_bytes=1024)
    try:
        dsa.solve(c, {}, n_cycles=5, seed=0)
        failures.append("guard never fired under a 1 KiB limit")
    except MemoryBudgetExceeded as e:
        print(f"guard refusal: {str(e)[:96]}...")
        if e.breach["reason"] != "memory_budget":
            failures.append(f"breach reason {e.breach['reason']!r}")
        if not e.breach["dominant_component"]:
            failures.append("breach names no dominant component")
    finally:
        telemetry_off()
    return failures


def _live_plane_degradation() -> list:
    from pydcop_tpu.telemetry import metrics_registry, telemetry_off
    from pydcop_tpu.telemetry.memplane import (
        memguard, memory_status, sample_device_memory,
    )

    failures = []
    metrics_registry.reset()
    metrics_registry.enabled = True
    memguard.configure(limit_bytes=16 << 30)
    try:
        sample = sample_device_memory("smoke")
        snap = metrics_registry.snapshot()["metrics"]
        if sample is None:
            # degraded backend: the miss must be counted, not silent
            if "mem.stats_unavailable" not in snap:
                failures.append("degraded sampler did not count the miss")
            else:
                print("live plane: memory_stats() unavailable (counted)")
        else:
            print(f"live plane: in_use {sample['bytes_in_use']:,} B")
        limit = snap.get("mem.limit_bytes")
        if not limit or limit["values"][0]["value"] != float(16 << 30):
            failures.append("mem.limit_bytes gauge not published")
        st = memory_status()
        if st["guard"]["limit_bytes"] != 16 << 30:
            failures.append("memory_status() missing the guard config")
    finally:
        telemetry_off()
    return failures


def _memplan_verb() -> list:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [
            sys.executable, "-m", "pydcop_tpu", "memplan",
            "--algo", "maxsum", "--n-vars", "100000", "--domain", "3",
            "--degree", "4", "--device", "v5e", "--max-vars",
        ],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    if r.returncode != 0:
        return [f"memplan rc={r.returncode}: {r.stderr[-500:]}"]
    failures = []
    for needle in ("verdict: FITS", "max vars/device"):
        if needle not in r.stdout:
            failures.append(f"memplan output missing {needle!r}")
    if not failures:
        print("memplan verb:")
        for line in r.stdout.splitlines():
            if line.startswith(("verdict:", "max vars")):
                print("  " + line)
    return failures


def main() -> int:
    from pydcop_tpu.utils.platform import pin_cpu

    pin_cpu()

    failures = []
    failures += _model_vs_measured()
    failures += _guard_refusal()
    failures += _live_plane_degradation()
    failures += _memplan_verb()

    if failures:
        for f in failures:
            print(f"MEM-SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print("mem-smoke: all green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
