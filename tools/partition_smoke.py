#!/usr/bin/env python
"""graftpart smoke: the partitioning subsystem proven end to end on CPU.

Four checks, each printed as a JSON line and asserted:

1. **Incidence drop** — a 10k-variable scale-free coloring instance
   (config-4 generator, smaller) partitioned for 8 shards: the
   multilevel strategy must beat the BFS baseline on
   ``cross_shard_incidence`` by at least 35% relative (measured drops
   are ~2x).
2. **Sharded-solve cost bit-identity** — the partitioned instance solved
   with MaxSum over an 8-device virtual CPU mesh (the real shard-major
   ELL cycle) must produce EXACTLY the single-device cost.
3. **ICI model vs gauge** — the analytic ``partition/icimodel.py``
   incidence must equal the ``mesh.ell_cross_frac`` gauge the sharded
   solve emitted (the measured cross-shard fraction of the built
   layout), within 1e-6: the model MULTICHIP records carry is validated
   against the measured quantity.
4. **Headline instance** — the 100k scale-free config-4 graph
   partitioned for 8 shards (partition only, no 100k solve in a smoke):
   BFS and multilevel incidence printed side by side (ROADMAP item 2's
   explicit ask; the multilevel bar is asserted at <= 0.40 absolute —
   measured ~0.37 vs ~0.82 BFS, a 2.2x ICI-traffic reduction).

Usage:  python tools/partition_smoke.py [--skip-100k]
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N_DEVICES = 8


def main() -> int:
    from pydcop_tpu.utils.platform import pin_cpu

    pin_cpu(N_DEVICES)

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device
    from pydcop_tpu.parallel.mesh import (
        make_mesh,
        pad_device_dcop,
        shard_device_dcop,
    )
    from pydcop_tpu.parallel.placement import (
        cross_shard_incidence,
        partition_compiled,
    )
    from pydcop_tpu.partition import ici_model
    from pydcop_tpu.telemetry.metrics import metrics_registry

    # --- 1: incidence drop on the 10k instance ----------------------
    compiled = generate_coloring_arrays(
        10_000, 3, graph="scalefree", m_edge=2, seed=7
    )
    t0 = time.perf_counter()
    placed = partition_compiled(
        compiled, strategy="multilevel", n_shards=N_DEVICES
    )
    order_wall = time.perf_counter() - t0
    bfs = partition_compiled(compiled, strategy="bfs")
    inc_ml = cross_shard_incidence(placed, N_DEVICES)
    inc_bfs = cross_shard_incidence(bfs, N_DEVICES)
    print(json.dumps({
        "check": "incidence_drop_10k",
        "n_vars": 10_000,
        "n_shards": N_DEVICES,
        "incidence_bfs": round(inc_bfs, 4),
        "incidence_multilevel": round(inc_ml, 4),
        "order_wall_s": round(order_wall, 2),
    }))
    sys.stdout.flush()
    assert inc_ml < 0.65 * inc_bfs, (
        f"multilevel incidence {inc_ml:.3f} did not drop >= 35% below "
        f"BFS {inc_bfs:.3f}"
    )

    # --- 2 + 3: sharded solve bit-identity and model-vs-gauge -------
    params = {"damping": 0.7, "noise": 0.0, "stop_cycle": 20}
    single = maxsum.solve(placed, dict(params), n_cycles=20, seed=7)
    mesh = make_mesh(N_DEVICES)
    sharded_dev = shard_device_dcop(
        pad_device_dcop(to_device(placed), mesh.size), mesh
    )
    metrics_registry.enabled = True
    try:
        sharded = maxsum.solve(
            placed, dict(params), n_cycles=20, seed=7, dev=sharded_dev
        )
        gauge = metrics_registry.get("mesh.ell_cross_frac")
        measured = gauge.value() if gauge is not None else None
    finally:
        metrics_registry.enabled = False
        metrics_registry.reset()
    model = ici_model(placed, None, N_DEVICES)
    print(json.dumps({
        "check": "sharded_cost_identity_10k",
        "cost_single": float(single.cost),
        "cost_sharded": float(sharded.cost),
        "measured_ell_cross_frac": (
            round(float(measured), 6) if measured is not None else None
        ),
        "ici_model_incidence": round(model["incidence"], 6),
        "ici_model_bytes_per_cycle": model["bytes_per_cycle"],
    }))
    sys.stdout.flush()
    assert sharded.cost == single.cost, (
        f"sharded cost {sharded.cost} != single-device {single.cost}"
    )
    assert measured is not None, "sharded solve emitted no cross-frac gauge"
    assert abs(model["incidence"] - measured) < 1e-6, (
        f"ICI model incidence {model['incidence']} drifted from the "
        f"measured gauge {measured}"
    )

    # --- 4: the 100k headline instance (partition only) -------------
    if "--skip-100k" not in sys.argv:
        big = generate_coloring_arrays(
            100_000, 3, graph="scalefree", m_edge=2, seed=7
        )
        t0 = time.perf_counter()
        big_placed = partition_compiled(
            big, strategy="multilevel", n_shards=N_DEVICES
        )
        order_wall = time.perf_counter() - t0
        big_bfs = partition_compiled(big, strategy="bfs")
        inc_ml = cross_shard_incidence(big_placed, N_DEVICES)
        inc_bfs = cross_shard_incidence(big_bfs, N_DEVICES)
        model = ici_model(big_placed, None, N_DEVICES)
        print(json.dumps({
            "check": "incidence_100k_headline",
            "n_vars": 100_000,
            "n_shards": N_DEVICES,
            "incidence_bfs": round(inc_bfs, 4),
            "incidence_multilevel": round(inc_ml, 4),
            "ici_bytes_per_cycle_multilevel": model["bytes_per_cycle"],
            "order_wall_s": round(order_wall, 2),
        }))
        sys.stdout.flush()
        assert inc_ml <= 0.40, (
            f"100k multilevel incidence {inc_ml:.3f} above the 0.40 bar"
        )
        assert inc_ml < 0.5 * inc_bfs, (
            f"100k multilevel {inc_ml:.3f} not below half of BFS "
            f"{inc_bfs:.3f}"
        )

    print("PARTITION SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
