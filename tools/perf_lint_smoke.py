"""perf-lint-smoke: graftperf end-to-end gate (``make perf-lint-smoke``).

Three checks, all against the real repo (no fixtures):

1. **cold lint** — the full six-pass graftlint run (pass 6 included)
   over ``pydcop_tpu/`` against the checked-in baseline must be clean
   (the baseline is EMPTY: every accepted perf exception is an inline
   ``# graftperf: disable=`` with a written-down reason, not a ratchet
   entry);
2. **warm lint** — the identical run again must be served from the
   content-hash finding cache (same verdict, and measurably not
   re-parsing: the warm run reports a cache summary) — this is what
   keeps pass 6 cheap enough to sit in the default ``make lint``;
3. **budget ratchet** — ``analysis.budget.check_budget`` re-derives the
   dispatch/readback site census for every engine path named in
   ``tools/perf_budget.json`` and diffs it against the pinned counts;
   any mismatch (an engine edit that moved/added a dispatch or readback
   site, or drifted TIMEOUT_CHUNK/MAX_CHUNK) fails with the exact
   region and delta.

The runtime half of the budget (graftprof's jit_census/readback
counters for warm solves) is covered by tests/test_analysis_perf.py in
the tier-1 flow; this smoke stays pure-AST so it runs anywhere in
under a couple of seconds.

Exits non-zero with a diagnosis on any miss, like the other smokes.
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")


def _lint(state_dir: str, label: str) -> "subprocess.CompletedProcess":
    env = dict(os.environ, PYDCOP_TPU_STATE_DIR=state_dir)
    proc = subprocess.run(
        [
            sys.executable, "-m", "pydcop_tpu.analysis",
            "--baseline", BASELINE, "--quiet", "pydcop_tpu/",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        print(f"FAIL: {label} lint run exited {proc.returncode}")
    return proc


def main() -> int:
    import json

    with open(BASELINE) as fh:
        entries = json.load(fh).get("findings", [])
    if entries:
        print(f"FAIL: baseline is not empty ({len(entries)} entries) — "
              f"fix or inline-suppress instead of ratcheting")
        return 1

    state_dir = tempfile.mkdtemp(prefix="pydcop_perf_lint_smoke_")

    cold = _lint(state_dir, "cold")
    if cold.returncode != 0:
        return 1
    print(f"cold lint: clean ({cold.stdout.strip().splitlines()[-1]})")

    warm = _lint(state_dir, "warm")
    if warm.returncode != 0:
        return 1
    if warm.stdout.strip() != cold.stdout.strip():
        print("FAIL: warm (cached) lint verdict differs from cold run")
        print(f"  cold: {cold.stdout.strip()!r}")
        print(f"  warm: {warm.stdout.strip()!r}")
        return 1
    print("warm lint: cache served the same clean verdict")

    from pydcop_tpu.analysis.budget import (
        check_budget,
        chunk_count,
        load_manifest,
    )

    manifest = load_manifest(
        os.path.join(REPO, "tools", "perf_budget.json")
    )
    problems = check_budget(manifest, root=REPO)
    if problems:
        for p in problems:
            print(f"  budget: {p}")
        print(f"FAIL: {len(problems)} budget pin(s) no longer hold — "
              f"an engine edit changed the dispatch/readback census; "
              f"re-derive and re-pin tools/perf_budget.json consciously")
        return 1
    n_regions = len(manifest.get("static", {}))
    print(
        f"budget: {n_regions} engine regions match the pinned census "
        f"(chunk schedule: {chunk_count(40, manifest)} chunks for a "
        f"40-cycle timeout solve)"
    )
    print("PASS: perf-lint-smoke")
    return 0


if __name__ == "__main__":
    sys.exit(main())
