"""graftslo smoke gate (``make slo-smoke``, docs/observability.md).

Three serve runs through the real ``ServeServer`` + ``SloEngine`` stack:

1. **Quiet run** (fresh executables, HTTP surface on): tenants across
   two shape buckets, generous objectives.  Must trip ZERO alerts, keep
   the full error budget, answer ``/slo``, serve OpenMetrics with
   request-trace exemplars on ``/metrics`` (Accept negotiation), and
   leave a request span tree — ``serve.request`` root plus
   queued/assemble/dispatch/solve/readback slices, the cold-compile
   stall slice for the first (unwarmed) batch, and exemplar trace ids
   that RESOLVE to that tenant's spans in the stitched trace.
2. + 3. **Chaos runs** (same seeded schedule twice): a ``delay`` rule
   holds the ``lag*`` tenants 2.5 s against a 1 s p99 objective.  The
   fast-burn alert must fire in BOTH runs with the IDENTICAL transition
   sequence and identical good/bad classification (bit-reproducibility
   by seed), the availability objective must stay silent, and the trip
   must leave a postmortem ``pydcop_tpu postmortem`` can render, naming
   the violated objective.
"""

import json
import os
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CYCLES = 20
PM_PATH = "/tmp/pydcop_slo_smoke_postmortem.json"


def _fail(msg: str) -> int:
    print(f"SLO-SMOKE FAIL: {msg}")
    return 1


def make_requests():
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.serve import SolveRequest

    reqs = []
    for i in range(4):
        reqs.append(SolveRequest(
            f"ok{i}",
            generate_coloring_arrays(9, 3, graph="grid", seed=500 + i),
            "dsa", {}, CYCLES, i,
        ))
    for i in range(4):
        reqs.append(SolveRequest(
            f"lag{i}",
            generate_coloring_arrays(16, 3, graph="grid", seed=600 + i),
            "dsa", {}, CYCLES, i,
        ))
    return reqs


def run_serve(reqs, objectives, schedule=None, port=None, trace_out=None):
    """One serve run; returns (engine, status, trace events)."""
    from pydcop_tpu.serve import ServeServer
    from pydcop_tpu.telemetry.metrics import metrics_registry
    from pydcop_tpu.telemetry.slo import SloEngine, parse_objective
    from pydcop_tpu.telemetry.tracing import tracer

    metrics_registry.reset()
    metrics_registry.enabled = True
    tracer.reset()
    tracer.enabled = True
    if os.path.exists(PM_PATH):
        os.remove(PM_PATH)
    engine = SloEngine(
        [parse_objective(s) for s in objectives],
        eval_interval_s=0.1,
        postmortem_path=PM_PATH,
    )
    srv = ServeServer(
        port=port, window_ms=30.0, max_batch=8,
        fault_schedule=schedule, slo=engine,
    )
    scrapes = {}
    try:
        tids = [srv.submit(r) for r in reqs]
        for t in tids:
            rec = srv.wait(t, timeout=300)
            assert rec["status"] == "done", rec
        if srv.http is not None:
            base = f"http://127.0.0.1:{srv.http.port}"
            with urllib.request.urlopen(base + "/slo", timeout=5) as r:
                scrapes["slo"] = json.loads(r.read())
            req = urllib.request.Request(
                base + "/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                scrapes["openmetrics"] = r.read().decode()
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                scrapes["classic"] = r.read().decode()
    finally:
        srv.shutdown(drain=True)
        if trace_out:
            tracer.export_chrome(trace_out)
        tracer.enabled = False
        metrics_registry.enabled = False
    return engine, srv.status(), tracer.events(), scrapes


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from pydcop_tpu.chaos.schedule import FaultSchedule, MessageRule
    from pydcop_tpu.telemetry.pulse import load_postmortem, render_postmortem
    from pydcop_tpu.telemetry.prom import parse_prometheus_text

    reqs = make_requests()

    # ---- run 1: quiet — zero alerts, full surface ---------------------
    trace_path = "/tmp/pydcop_slo_smoke_trace.json"
    engine, status, events, scrapes = run_serve(
        reqs,
        ["p99<30s", "availability>=99%", "dead_letter_rate<=1%"],
        port=0,
        trace_out=trace_path,
    )
    if engine.transitions:
        return _fail(f"quiet run tripped alerts: {engine.transitions}")
    rep = scrapes["slo"]
    for ob in rep["objectives"]:
        if ob["bad"] or ob["budget_remaining"] < 0.999:
            return _fail(f"quiet run burned budget: {ob}")
        if ob["good"] != len(reqs):
            return _fail(f"objective {ob['name']} missed requests: {ob}")
    om = scrapes["openmetrics"]
    if "# EOF" not in om:
        return _fail("OpenMetrics scrape lacks # EOF terminator")
    if "# EOF" in scrapes["classic"]:
        return _fail("classic scrape must not carry OpenMetrics syntax")
    parsed = parse_prometheus_text(om)
    exemplars = [
        s["exemplar"]["labels"].get("trace_id")
        for s in parsed["samples"]
        if s["name"] == "serve_request_seconds_bucket" and s["exemplar"]
    ]
    if not exemplars:
        return _fail("no exemplar trace ids on serve_request_seconds")
    # exemplar trace ids must RESOLVE to spans of that request's tree
    by_trace = {}
    for e in events:
        t = (e.get("args") or {}).get("trace")
        if t:
            by_trace.setdefault(t, set()).add(e["name"])
    for ex in exemplars:
        if "serve.request" not in by_trace.get(ex, set()):
            return _fail(
                f"exemplar trace id {ex} resolves to no serve.request span"
            )
    names = {e["name"] for e in events}
    need = {
        "serve.request", "serve.queued", "serve.batch", "serve.assemble",
        "serve.dispatch", "serve.solve", "serve.readback",
        "serve.cold_compile", "serve.submit", "serve.result",
    }
    if not need <= names:
        return _fail(f"span tree incomplete: missing {need - names}")
    req_spans = [
        e for e in events
        if e["name"] == "serve.request" and e.get("args", {}).get("bucket")
    ]
    if not req_spans or not any(
        e["args"].get("cold_compile") for e in req_spans
    ):
        return _fail(
            "no serve.request span carries its bucket + cold-compile bit"
        )
    # the acceptance path: exported trace -> `telemetry stitch` -> the
    # stitched timeline still shows a tenant's full submit->result tree
    # with its batch/bucket and the cold-compile stall
    from pydcop_tpu.telemetry.stitch import stitch_traces

    stitched, _report = stitch_traces([trace_path])
    snames = {e.get("name") for e in stitched["traceEvents"]}
    if not {"serve.request", "serve.queued", "serve.cold_compile"} <= snames:
        return _fail(f"stitched trace lost the request tree: {sorted(snames)[:20]}")
    print(
        f"quiet run: {len(reqs)} tenants, 0 alerts, "
        f"{len(exemplars)} exemplar(s) resolved, span tree complete, "
        "stitched trace keeps it"
    )

    # ---- runs 2+3: seeded chaos delay, bit-reproducible fast burn -----
    schedule = FaultSchedule(seed=7, events=[
        MessageRule(action="delay", pattern="solve", dest="lag*",
                    seconds=2.5),
    ])
    objectives = ["p99<1s@720s", "availability>=99%@720s"]
    outcomes = []
    for run in (1, 2):
        engine, status, _events, _ = run_serve(
            reqs, objectives, schedule=schedule,
        )
        canonical = [
            (t["objective"], t["severity"], t["state"])
            for t in engine.transitions
        ]
        counts = {
            ob["name"]: (ob["good"], ob["bad"])
            for ob in engine.report()["objectives"]
        }
        outcomes.append((canonical, counts))
        print(f"chaos run {run}: transitions={canonical} counts={counts}")
    (c1, n1), (c2, n2) = outcomes
    if ("p99_latency", "fast", "firing") not in c1:
        return _fail(f"chaos schedule did not trip the fast-burn alert: {c1}")
    if any(t[0] == "availability" for t in c1):
        return _fail(f"availability wrongly tripped: {c1}")
    if c1 != c2 or n1 != n2:
        return _fail(
            f"chaos runs diverged: {c1}/{n1} vs {c2}/{n2} — "
            "burn alerting is not bit-reproducible by seed"
        )
    if not os.path.exists(PM_PATH):
        return _fail("tripped alert left no postmortem")
    doc = load_postmortem(PM_PATH)
    rendered = render_postmortem(doc)
    if "p99_latency" not in rendered or "slo violated" not in rendered:
        return _fail(
            f"postmortem does not name the violated objective:\n{rendered}"
        )
    print("postmortem renders and names the violated objective:")
    print("  " + rendered.splitlines()[1])
    print("SLO-SMOKE PASS: quiet run clean, fast-burn alert "
          "bit-reproducible by seed, postmortem renderable")
    return 0


if __name__ == "__main__":
    sys.exit(main())
