"""All five BASELINE.json target configs on one device (VERDICT item 3).

Prints one JSON line per config: wall time for the measured solve (after a
compile warm-up), iterations/sec, final cost/violations, and the device.
``python bench_all.py --cpu`` pins the CPU platform (for use when the TPU
relay is down); without the flag the default backend is used, so run it
under a watchdog if the relay state is unknown (see bench.py).

The headline driver gate remains bench.py (config #4 only, one line).
"""

import argparse
import json
import os
import sys
import time


def _hbm_peak_gbps():
    # the generation->bandwidth table lives in telemetry/kernelprof.py
    # (single source of truth with the per-op kernel block)
    from pydcop_tpu.telemetry import hbm_peak_gbps

    return hbm_peak_gbps()


def _maxsum_traffic_bytes(dev) -> int:
    """Analytic minimum HBM traffic of ONE MaxSum cycle (same model as
    tools/profile_maxsum.py): the two [n_edges, D] message planes are each
    read ~3x and written ~1x, the joint tables are read once, plus the
    int32 edge index arrays."""
    import numpy as np

    itemsize = np.dtype(dev.unary.dtype).itemsize
    table_elems = sum(int(b.tables_flat.size) for b in dev.buckets)
    plane = int(dev.n_edges) * int(dev.max_domain)
    return itemsize * (8 * plane + table_elems) + 4 * 3 * int(dev.n_edges)


def _sum_metric(reg, name, field=None):
    """Sum a metric's values across label sets from its snapshot (the
    registry API is per-label-set; bench records want totals)."""
    m = reg.get(name)
    if m is None:
        return 0.0
    total = 0.0
    for entry in m.snapshot().get("values", []):
        v = entry.get("value")
        if isinstance(v, dict):  # histogram
            v = v.get(field or "sum", 0.0)
        total += float(v or 0.0)
    return total


def _compile_block(reg):
    """graftprof compile observability for the BENCH record, captured
    over the warm-up run (that is where the XLA compiles happen): how
    many programs were built vs served from cache, the compile wall, and
    the cost-analysis totals that feed the roofline columns."""
    return {
        "jit_compiles": int(_sum_metric(reg, "compile.jit_compiles")),
        "jit_cache_hits": int(_sum_metric(reg, "compile.jit_cache_hits")),
        "compile_s": round(
            _sum_metric(reg, "compile.jit_seconds", "sum"), 4
        ),
        "host_compile_s": round(
            _sum_metric(reg, "compile.host_seconds", "sum"), 4
        ),
        "flops": int(_sum_metric(reg, "compile.flops_total")),
        "bytes_accessed": int(
            _sum_metric(reg, "compile.bytes_accessed_total")
        ),
        "analysis_unavailable": int(
            _sum_metric(reg, "compile.analysis_unavailable")
        ),
    }


def _memory_block(reg):
    """graftmem block for the BENCH record, captured over the warm-up
    run: the analytic model's predicted per-device bytes, the
    memory_analysis() measured peak (when the backend offered it), the
    device limit and the headroom left — ROADMAP item 1's HBM numbers
    next to the wall they were achieved at."""
    from pydcop_tpu.telemetry.memplane import (
        device_limit_bytes,
        measured_peak_bytes,
    )

    predicted = reg.gauge("mem.predicted_bytes").value()
    peak = measured_peak_bytes(fn="")  # max over every jit entry point
    limit = device_limit_bytes()
    block = {
        "predicted_bytes": int(predicted) if predicted else None,
        "measured_peak_bytes": int(peak) if peak else None,
        "limit_bytes": int(limit) if limit else None,
        "headroom_pct": None,
    }
    basis = peak or predicted
    if limit and basis:
        block["headroom_pct"] = round(100.0 * (1.0 - basis / limit), 2)
    if predicted and peak:
        # the cross-validation ratio the ±20% model test pins
        block["model_ratio"] = round(predicted / peak, 3)
    return block


def _telemetry_block(reg):
    """Solver-path breakdown from the metrics registry for the BENCH
    record: readback windows/bytes/latency and device cycles, so BENCH
    files carry where the wall went, not just its total."""
    windows = reg.counter("solve.windows").value()
    rb = reg.histogram("solve.readback_seconds")
    rb_count = rb.count()
    return {
        "windows": int(windows),
        "device_cycles": int(reg.counter("solve.device_cycles").value()),
        "readback_bytes": int(reg.counter("solve.readback_bytes").value()),
        "readback_ms_mean": (
            round(1000.0 * rb.sum() / rb_count, 3) if rb_count else None
        ),
        "upload_bytes": int(reg.counter("solve.upload_bytes").value()),
    }


# decimation bound for the anytime profile embedded in BENCH records: the
# curve is evidence of convergence shape, not a full trajectory dump
CURVE_POINTS = 64


def _decimate(curve, points=CURVE_POINTS):
    from pydcop_tpu.telemetry import decimate_series

    return decimate_series([round(float(c), 6) for c in curve], points)


def _bench(name, solve_fn, n_cycles, traffic_bytes=None, kernel_fn=None):
    """Warm-up (compile) + timed run of a solve closure.

    ``solve_fn`` must accept keyword overrides (``**kw -> SolveResult``):
    the timed run calls it bare, then one untimed ``collect_curve=True``
    pass captures the anytime profile (cost curve + cycles-to-best) for
    the record's ``telemetry`` block — separate on purpose, so the
    headline wall number stays comparable with pre-curve BENCH files.

    ``traffic_bytes``: analytic minimum HBM traffic of one cycle; when
    given, the record carries achieved GB/s and — on a TPU whose
    generation is recognized — the % of HBM peak (the memory-bound
    analogue of MFU; round-3 verdict item 8).

    ``kernel_fn``: nullary producing the per-op ``kernel`` block
    (telemetry/kernelprof.py) — runs AFTER the timed passes so the
    per-op dispatches can never contaminate the headline wall; a failure
    inside it degrades to a ``{"error": ...}`` block, never a lost
    record."""
    from pydcop_tpu.telemetry import metrics_registry

    # warm-up with metrics ON: the XLA compiles happen here, so this is
    # where graftprof's compile.* counters (and the cost-analysis flops
    # feeding the roofline columns) are captured; reset afterwards so the
    # timed run's solve.* numbers stay measured-run-only.
    # graftmem rides the warm-up too: the OOM guard's prediction
    # (mem.predicted_bytes, no limit -> never refuses here) and an
    # opportunistic memory_analysis() peak — the AOT compile it needs
    # happens outside any timed window, so the headline wall and the
    # compile.jit_seconds histogram stay comparable with older BENCH
    # files
    from pydcop_tpu.telemetry import memguard, profiling

    metrics_registry.reset()
    metrics_registry.enabled = True
    guard_was = memguard.enabled
    opportunistic_was = profiling.opportunistic_memory
    memguard.enabled = True
    profiling.opportunistic_memory = True
    try:
        solve_fn()
    finally:
        metrics_registry.enabled = False
        memguard.enabled = guard_was
        profiling.opportunistic_memory = opportunistic_was
    compile_block = _compile_block(metrics_registry)
    memory_block = _memory_block(metrics_registry)
    # metrics ride along the measured run: a handful of counter bumps per
    # readback window, noise next to one device dispatch
    metrics_registry.reset()
    metrics_registry.enabled = True
    try:
        t0 = time.perf_counter()
        result = solve_fn()
        wall = time.perf_counter() - t0
    finally:
        metrics_registry.enabled = False
    import jax

    telemetry = _telemetry_block(metrics_registry)
    # graftcap census of the MEASURED run: per-label jit dispatch counts
    # and the readback window census.  Any compiles>0 here means the warm
    # executable was rebuilt mid-measurement — the exact recompile hazard
    # `capture diff` is built to flag
    from pydcop_tpu.telemetry.profiling import jit_census, readback_census

    census = {"jit": jit_census(), "readback": readback_census()}
    # anytime profile (untimed): curve-collecting variant of the same
    # solve; a solver without the parameter skips — but a TypeError from
    # INSIDE a solver's curve path is a real regression and must fail
    # the bench, not silently drop the profile
    # graftpulse rides the same untimed pass (NOT the measured run: the
    # health hook compiles extra reductions into the loop, and the
    # headline wall number must stay comparable across BENCH files)
    from pydcop_tpu.telemetry import pulse

    prev_pm_path = pulse.postmortem_path
    try:
        metrics_registry.enabled = True
        pulse.reset()
        pulse.enabled = True
        # a timed-out curve pass arms the flight recorder; keep its dump
        # in the bench state dir, not the cwd (same no-littering rule as
        # the campaign progress markers)
        from pydcop_tpu.commands.batch import state_dir

        pulse.postmortem_path = os.path.join(
            state_dir(), "postmortem.json"
        )
        curve_result = solve_fn(collect_curve=True)
        curve = curve_result.cost_curve
    except TypeError as exc:
        if "collect_curve" not in str(exc):
            raise
        curve = None
    finally:
        pulse.enabled = False
        pulse.postmortem_path = prev_pm_path
        metrics_registry.enabled = False
    if curve:
        telemetry["cost_curve"] = _decimate(curve)
        # the curve pass just set the gauge (every run_cycles path does
        # now), so 0 is the real "initial assignment never improved on",
        # not "unmeasured"
        c2b = metrics_registry.gauge("solve.cycles_to_best").value()
        telemetry["cycles_to_best"] = int(c2b)
    pulse_block = None
    if pulse.last_report is not None:
        a = pulse.last_report.get("analysis", {})
        # point-in-time values (same semantics as /status), not the
        # analysis window maxima — "converged" with a high early-window
        # churn would read as contradictory
        pulse_block = {
            "diagnosis": pulse.last_report["diagnosis"],
            "cycles": pulse.last_report["cycles"],
            "churn": round(float(a.get("churn_now", 0.0)), 4),
            "residual": float(a.get("residual_now", 0.0)),
            "violations": int(a.get("violations", 0)),
        }
        fs = pulse.last_report.get("flip_summary")
        if fs:
            pulse_block["frozen_frac"] = round(float(fs["frozen_frac"]), 4)

    record = {
        "metric": name,
        "value": round(wall, 4),
        "unit": "s",
        "cycles_per_s": round(n_cycles / wall, 1) if wall > 0 else None,
        "cost": result.cost,
        "violations": result.violations,
        "cycles": n_cycles,
        "device": str(jax.devices()[0].platform),
        "telemetry": telemetry,
        "compile": compile_block,
        "memory": memory_block,
        "census": census,
    }
    if pulse_block is not None:
        # solver-health verdict of the curve pass (graftpulse): did this
        # config actually converge inside its cycle budget, and how much
        # of the problem settled
        record["pulse"] = pulse_block
    # roofline-style achieved-vs-theoretical columns (graftprof): the
    # analytic traffic model gives achieved GB/s vs the chip's HBM peak;
    # the compiled programs' cost_analysis gives an achieved GFLOP/s
    # (total flops of the programs built for this solve over the timed
    # wall — a same-machine trend line, not an MFU claim)
    roofline = {}
    peak = _hbm_peak_gbps()
    if traffic_bytes and wall > 0:
        gbps = traffic_bytes * n_cycles / wall / 1e9
        record["achieved_gbps"] = round(gbps, 2)
        roofline["traffic_bytes_per_cycle"] = int(traffic_bytes)
        roofline["achieved_gbps"] = round(gbps, 2)
        roofline["peak_gbps"] = peak
        if peak:
            record["hbm_peak_pct"] = round(100.0 * gbps / peak, 2)
            roofline["hbm_peak_pct"] = record["hbm_peak_pct"]
    if compile_block.get("flops") and wall > 0:
        roofline["achieved_gflops"] = round(
            compile_block["flops"] / wall / 1e9, 3
        )
    if roofline:
        record["roofline"] = roofline
    if kernel_fn is not None:
        # metrics ON so the mgm2 phase histograms land and a degraded
        # attribution block is COUNTED (kernelprof.degraded), not just
        # silently embedded — capture reads the counter to warn loudly
        metrics_registry.enabled = True
        try:
            record["kernel"] = kernel_fn()
        except Exception as exc:  # noqa: BLE001
            record["kernel"] = {
                "error": f"{type(exc).__name__}: {exc}"[:200]
            }
            metrics_registry.counter("kernelprof.degraded").inc(
                reason=type(exc).__name__
            )
        finally:
            metrics_registry.enabled = False
    return record


#: config 1's input lives in the reference checkout, which containers
#: legitimately lack — its absence is a SKIP, not a failure (bench_gate
#: reports the record as SKIPPED so the gate can go green without it)
REFERENCE_COLORING_50 = "/root/reference/docs/tutorials/graph_coloring_50.yaml"


def config_1_dsa50(n_cycles=100):
    from pydcop_tpu.algorithms import dsa
    from pydcop_tpu.compile.core import compile_dcop
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

    if not os.path.exists(REFERENCE_COLORING_50):
        return {
            "metric": "dsa_coloring50_wall",
            "value": None,
            "skipped": (
                f"reference checkout not present ({REFERENCE_COLORING_50})"
            ),
        }
    dcop = load_dcop_from_file([REFERENCE_COLORING_50])
    compiled = compile_dcop(dcop)
    return _bench(
        "dsa_coloring50_wall",
        lambda **kw: dsa.solve(
            compiled, {}, n_cycles=n_cycles, seed=0, **kw
        ),
        n_cycles,
    )


def config_2_maxsum1k(n_cycles=60):
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )

    from pydcop_tpu.compile.kernels import to_device

    from pydcop_tpu.telemetry import ell_kernel_block

    compiled = generate_coloring_arrays(
        1000, 3, graph="random", p_edge=0.005, seed=11
    )
    dev = to_device(compiled)
    return _bench(
        "maxsum_1k_random_wall",
        lambda **kw: maxsum.solve(
            compiled, {"damping": 0.5, "stop_cycle": n_cycles},
            n_cycles=n_cycles, seed=0, dev=dev, **kw
        ),
        n_cycles,
        traffic_bytes=_maxsum_traffic_bytes(dev),
        kernel_fn=lambda: ell_kernel_block(compiled, reps=10),
    )


def config_3_mgm2_ising10k(n_cycles=30):
    from pydcop_tpu.algorithms import mgm2
    from pydcop_tpu.commands.generators.ising import generate_ising_arrays

    from pydcop_tpu.telemetry import mgm2_phase_block

    compiled = generate_ising_arrays(100, 100, seed=3)
    return _bench(
        "mgm2_ising10k_wall",
        lambda **kw: mgm2.solve(
            compiled, {}, n_cycles=n_cycles, seed=0, **kw
        ),
        n_cycles,
        # per-phase wall decomposition (VERDICT round-5 next #7: config
        # 3's 0.597s-vs-0.138s TPU gap becomes attributable per phase)
        kernel_fn=lambda: mgm2_phase_block(compiled, reps=5),
    )


def config_4_maxsum100k(n_cycles=30):
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    compiled = generate_coloring_arrays(
        100_000, 3, graph="scalefree", m_edge=2, seed=7
    )
    dev = to_device(compiled)
    # ELL layout (round 5): degree-bucketed dense fan-in/fan-out with one
    # partner gather per cycle — the on-device profile showed the lanes
    # layout's CSR gathers at ~2 ms each were the whole cycle cost.
    # Identical solution to lanes (pinned by tests), measured faster on
    # CPU too (0.58 s vs 0.67 s steady at this scale)
    from pydcop_tpu.telemetry import ell_kernel_block

    record = _bench(
        "maxsum_100k_scalefree_wall",
        lambda **kw: maxsum.solve(
            compiled, {"damping": 0.7, "layout": "ell"},
            n_cycles=n_cycles, seed=7, dev=dev, **kw
        ),
        n_cycles,
        traffic_bytes=_maxsum_traffic_bytes(dev),
        # the headline config carries the full per-op roofline: where
        # inside the ELL cycle the device time goes (gather vs min-plus
        # vs variable step), vs each op's analytic HBM floor — plus the
        # graftpart ici sub-block (modeled cross-shard bytes/cycle at 8
        # shards, BFS vs multilevel) extending the numbers to multi-chip
        kernel_fn=lambda: dict(
            ell_kernel_block(compiled, reps=10),
            ici=_ici_block_100k(compiled=compiled),
        ),
    )
    record["durability"] = _checkpoint_overhead(
        lambda: maxsum.solve(
            compiled, {"damping": 0.7, "layout": "ell"},
            n_cycles=n_cycles, seed=7, dev=dev,
        ),
        record.get("value"),
    )
    return record


def _checkpoint_overhead(solve_fn, fused_wall, every=8):
    """graftdur cost-of-durability on the headline config: the SAME solve
    with checkpointing every ``every`` cycles (the chunked engine +
    state-pytree writes), as a percentage over the fused timed wall.
    One warm-up pass first — the chunked loop is a different compiled
    program than the fused one, and its jit must not bill the overhead
    number.  Runs AFTER the timed passes; a failure degrades to an
    error block, never a lost record."""
    import shutil
    import tempfile

    try:
        from pydcop_tpu.durability import CheckpointManager, durability

        ck_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
        try:
            for timed in (False, True):
                durability.configure(
                    manager=CheckpointManager(
                        ck_dir, every_cycles=every, keep=2
                    )
                )
                try:
                    t0 = time.perf_counter()
                    solve_fn()
                    wall = time.perf_counter() - t0
                finally:
                    durability.reset()
            out = {
                "checkpoint_every": every,
                "checkpointed_wall_s": round(wall, 4),
            }
            if fused_wall:
                out["checkpoint_overhead_pct"] = round(
                    100.0 * (wall - fused_wall) / fused_wall, 2
                )
            return out
        finally:
            shutil.rmtree(ck_dir, ignore_errors=True)
    except Exception as exc:  # noqa: BLE001
        return {"error": f"{type(exc).__name__}: {exc}"[:200]}


def config_5_dpop_meetings():
    from pydcop_tpu.algorithms import dpop
    from pydcop_tpu.commands.generators.meetingscheduling import (
        generate_meeting_scheduling,
    )
    from pydcop_tpu.compile.core import compile_dcop

    # 30 meetings (round-2 verdict item 4's bar); 30 resources keeps the
    # PEAV induced width exactly solvable — denser resource sharing grows
    # the separator exponentially for ANY exact solver, reference included
    dcop = generate_meeting_scheduling(
        slots_count=8, resources_count=30, events_count=30,
        max_resources_event=2, seed=5,
    )
    compiled = compile_dcop(dcop)
    return _bench(
        "dpop_meetings_wall",
        lambda **kw: dpop.solve(compiled, {}, n_cycles=1, seed=0, **kw),
        1,
    )


def config_6_maxsum1m(n_cycles=30):
    """Stretch config (manual; not in the driver gate): 1 MILLION variables,
    ~4M factor-graph edges — an order of magnitude past the headline and
    ~3 orders past anything the reference's thread-per-agent runtime can
    host.  Same algorithm/params as config 4."""
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device

    compiled = generate_coloring_arrays(
        1_000_000, 3, graph="scalefree", m_edge=2, seed=7
    )
    dev = to_device(compiled)
    return _bench(
        "maxsum_1m_scalefree_wall",
        lambda **kw: maxsum.solve(
            compiled, {"damping": 0.7, "layout": "ell"},
            n_cycles=n_cycles, seed=7, dev=dev, **kw
        ),
        n_cycles,
        traffic_bytes=_maxsum_traffic_bytes(dev),
    )


def config_7_mixeddsa(n_cycles=50):
    """Hard+soft mixed constraints (manual; not in the driver gate):
    MixedDSA on its natural workload from ``generate mixed_problem`` —
    2k variables, ~40% hard disequalities, soft distance constraints."""
    from pydcop_tpu.algorithms import mixeddsa
    from pydcop_tpu.commands.generators.mixedproblem import (
        generate_mixed_problem,
    )
    from pydcop_tpu.compile.core import compile_dcop

    dcop = generate_mixed_problem(
        2000, 2000, 0.4, arity=2, domain_range=5, density=0.0025, seed=13
    )
    compiled = compile_dcop(dcop)
    return _bench(
        "mixeddsa_2k_mixed_wall",
        lambda **kw: mixeddsa.solve(
            compiled, {}, n_cycles=n_cycles, seed=0, **kw
        ),
        n_cycles,
    )


def config_8_serving(batch=32, n_cycles=16, reps=5):
    """graftserve throughput (ROADMAP item 3): ``batch`` tutorial-scale
    tenant solves (the reference's own 10-variable-coloring class) across
    two shape buckets vs the same solves as a sequential loop through
    the identical plan/padding (``serve.solve_one`` — the comparison
    isolates BATCHING, not padding or layout).  The headline wall is the
    fleet-fusion path (one block-diagonal union program,
    serve/union.py); the bit-exact vmap path is recorded alongside.  The
    ``serving`` block carries sustained solves/sec, batched-vs-sequential
    speedup, p50/p99 queue latency through a live micro-batching
    ServeServer, and the fresh-compile count of the warm vmap pass (must
    be 0: warm buckets reuse their executables)."""
    import statistics

    import numpy as np

    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.serve import (
        ServeServer,
        SolveRequest,
        solve_batched,
        solve_one,
    )
    from pydcop_tpu.telemetry import metrics_registry

    n_small = batch // 4
    reqs = []
    for i in range(batch - n_small):
        reqs.append(
            SolveRequest(
                f"b{i}",
                generate_coloring_arrays(9, 3, graph="grid", seed=300 + i),
                "dsa", {}, n_cycles, i,
            )
        )
    for i in range(n_small):
        reqs.append(
            SolveRequest(
                f"s{i}",
                generate_coloring_arrays(
                    16, 3, graph="grid", seed=400 + i
                ),
                "dsa", {}, n_cycles, i,
            )
        )

    from pydcop_tpu.algorithms import dsa

    def med_interleaved(fns):
        """Median wall per candidate, reps interleaved so machine-load
        noise lands on every candidate equally."""
        walls = [[] for _ in fns]
        for _ in range(reps):
            for i, fn in enumerate(fns):
                t0 = time.perf_counter()
                fn()
                walls[i].append(time.perf_counter() - t0)
        return [statistics.median(w) for w in walls]

    # warm-up: compiles for all paths
    solve_batched(reqs, mode="fused")
    solve_batched(reqs, mode="vmap")
    for r in reqs:
        solve_one(r)
        dsa.solve(r.compiled, {}, n_cycles=r.n_cycles, seed=r.seed)
    results = solve_batched(reqs, mode="fused")
    # two sequential baselines: the STRICT one (solve_one — identical
    # plan/padding/caching, so the delta is purely batching) and the
    # pre-serve API loop (dsa.solve per request, per-call device upload
    # — what a user's loop ran before graftserve existed)
    seq_wall, api_wall, fused_wall = med_interleaved([
        lambda: [solve_one(r) for r in reqs],
        lambda: [
            dsa.solve(r.compiled, {}, n_cycles=r.n_cycles, seed=r.seed)
            for r in reqs
        ],
        lambda: solve_batched(reqs, mode="fused"),
    ])
    # bit-exact vmap path, with the compile census riding along so the
    # record can PROVE the warm buckets compiled nothing
    metrics_registry.reset()
    metrics_registry.enabled = True
    try:
        (vmap_wall,) = med_interleaved(
            [lambda: solve_batched(reqs, mode="vmap")]
        )
    finally:
        metrics_registry.enabled = False
    fresh = int(_sum_metric(metrics_registry, "compile.jit_compiles"))
    hits = int(_sum_metric(metrics_registry, "compile.jit_cache_hits"))
    costs = [
        tr.result.cost for tr in results.values() if tr.result is not None
    ]
    violations = sum(
        tr.result.violations for tr in results.values()
        if tr.result is not None
    )
    # queue-latency percentiles through a live server: same requests
    # submitted into one micro-batching window.  graftslo rides along —
    # the record's `slo` block carries budget consumption and per-phase
    # p50/p99 through the same engine the serve verb runs (thresholds
    # generous on purpose: the bench documents budget state, it must not
    # trip alerts on slow containers)
    from pydcop_tpu.commands.batch import state_dir
    from pydcop_tpu.telemetry.slo import SloEngine, parse_objective

    engine = SloEngine(
        [parse_objective("p99<30s"), parse_objective("availability>=99%")],
        eval_interval_s=0.2,
        postmortem_path=os.path.join(state_dir(), "slo_postmortem.json"),
    )
    metrics_registry.reset()
    metrics_registry.enabled = True
    try:
        srv = ServeServer(
            port=None, window_ms=10.0, max_batch=batch, mode="fused",
            slo=engine,
        )
        for r in reqs:
            srv.submit(r._replace(tenant="q" + r.tenant))
        for r in reqs:
            srv.wait("q" + r.tenant, timeout=300)
        status = srv.status()
        srv.shutdown(drain=True)
    finally:
        metrics_registry.enabled = False
    slo_block = engine.bench_block()
    slo_block["alerts"] = len(engine.transitions)
    import jax

    record = {
        "metric": "serving_batch32_wall",
        "value": round(fused_wall, 4),
        "unit": "s",
        "cost": round(float(np.sum(costs)), 6),
        "violations": int(violations),
        "cycles": n_cycles,
        "device": str(jax.devices()[0].platform),
        "serving": {
            "tenants": batch,
            "buckets": 2,
            "n_cycles": n_cycles,
            "fused_wall_s": round(fused_wall, 4),
            "vmap_wall_s": round(vmap_wall, 4),
            # the sequential-loop baseline: the pre-serve way to serve
            # these requests (algo.solve per request in a loop, per-call
            # device upload).  The strict variant isolates pure batching
            # (solve_one: same plan/padding/warm caches, only the
            # dispatch is per-tenant).
            "sequential_wall_s": round(api_wall, 4),
            "sequential_strict_wall_s": round(seq_wall, 4),
            "speedup": round(api_wall / fused_wall, 2)
            if fused_wall > 0 else None,
            "speedup_vs_strict_loop": round(seq_wall / fused_wall, 2)
            if fused_wall > 0 else None,
            "vmap_speedup": round(api_wall / vmap_wall, 2)
            if vmap_wall > 0 else None,
            "solves_per_s": round(batch / fused_wall, 1)
            if fused_wall > 0 else None,
            "warm_fresh_compiles": fresh,
            "warm_cache_hits": hits,
            "queue_p50_ms": round(status["queue_ms"]["p50"], 2)
            if status["queue_ms"]["p50"] is not None else None,
            "queue_p99_ms": round(status["queue_ms"]["p99"], 2)
            if status["queue_ms"]["p99"] is not None else None,
            "dead_letters": status["dead_letters"],
        },
        "slo": slo_block,
    }
    return record


#: one partition of the 100k config-4 graph per bench process: config
#: 4's kernel.ici sub-block and config 9 both want the identical
#: ici_block (same generator args, shards, effort), and the multilevel
#: order is a deterministic ~9 s of host work — share it.
_ICI_100K_CACHE = {}


def _ici_block_100k(n_shards=8, compiled=None):
    # keyed by the problem CONTENT (durability fingerprint), not just the
    # shard count: if config 4's and config 9's generator args ever
    # drift apart, each gets its own block instead of silently sharing
    # whichever graph ran first
    from pydcop_tpu.durability import problem_fingerprint
    from pydcop_tpu.partition import ici_block

    if compiled is None:
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        compiled = generate_coloring_arrays(
            100_000, 3, graph="scalefree", m_edge=2, seed=7
        )
    key = (n_shards, problem_fingerprint(compiled))
    if key not in _ICI_100K_CACHE:
        _ICI_100K_CACHE[key] = ici_block(
            compiled, n_shards, effort="fast"
        )
    return _ICI_100K_CACHE[key]


def config_9_partition100k(n_shards=8):
    """graftpart quality as a first-class gate metric (ROADMAP item 2):
    partition the config-4 graph (100k scale-free) for 8 row-block
    shards and record the cross-shard incidence of the multilevel
    strategy as the VALUE — bench-gate then fails the build if partition
    quality regresses, exactly like a wall-clock regression.  The
    ``partition`` block carries order wall, BFS-vs-multilevel incidence
    and the modeled ICI bytes/cycle side by side (partition/icimodel.py;
    deterministic pipeline, so the number is noise-free)."""
    block = _ici_block_100k(n_shards)
    return {
        "metric": "partition_100k_incidence",
        "value": block["multilevel"]["incidence"],
        "unit": "frac",
        "n_vars": 100_000,
        "n_shards": n_shards,
        # the block's own per-strategy walls (NOT a wall measured around
        # _ici_block_100k — config 4 usually warmed the cache already,
        # which would record the partition as free)
        "order_wall_s": block["multilevel"]["order_wall_s"],
        "partition": block,
    }


def config_10_maxsum1m_sharded(n_cycles=10, n_shards=8):
    """Stretch config (manual; not in the driver gate): the 1M-variable
    scale-free MaxSum SHARDED over an 8-device virtual CPU mesh with the
    multilevel-partitioned layout — the mechanics rehearsal for the 10M
    multi-chip headline.  Virtual devices time-share one host, so the
    wall measures SPMD overhead, not silicon speedup; the record's value
    is that the partitioned sharded program compiles, runs, and matches
    the single-device cost exactly, with the ``partition`` block
    carrying the layout quality the mesh would enjoy on real ICI.

    Needs 8 devices: run as ``python bench_all.py --cpu 10`` (main pins
    8 virtual CPU devices when config 10 is requested)."""
    import jax

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device
    from pydcop_tpu.parallel.mesh import (
        make_mesh,
        pad_device_dcop,
        shard_device_dcop,
    )
    from pydcop_tpu.parallel.placement import (
        cross_shard_incidence,
        partition_compiled,
    )
    from pydcop_tpu.partition import ici_model

    if len(jax.devices()) < n_shards:
        raise RuntimeError(
            f"config 10 needs {n_shards} devices, have "
            f"{len(jax.devices())}; run `python bench_all.py --cpu 10`"
        )
    compiled = generate_coloring_arrays(
        1_000_000, 3, graph="scalefree", m_edge=2, seed=7
    )
    t0 = time.perf_counter()
    placed = partition_compiled(
        compiled, strategy="multilevel", n_shards=n_shards
    )
    order_wall = time.perf_counter() - t0
    inc = cross_shard_incidence(placed, n_shards)
    inc_raw = cross_shard_incidence(compiled, n_shards)
    model = ici_model(placed, None, n_shards)
    mesh = make_mesh(n_shards)
    dev = shard_device_dcop(
        pad_device_dcop(to_device(placed), mesh.size), mesh
    )
    params = {"damping": 0.7, "noise": 0.0, "stop_cycle": n_cycles}
    single = maxsum.solve(
        placed, dict(params), n_cycles=n_cycles, seed=7
    )
    record = _bench(
        "maxsum_1m_sharded_wall",
        lambda **kw: maxsum.solve(
            placed, dict(params), n_cycles=n_cycles, seed=7, dev=dev,
            **kw
        ),
        n_cycles,
    )
    record["devices"] = n_shards
    record["cost_single_device"] = float(single.cost)
    record["cost_bit_identical"] = record.get("cost") == single.cost
    record["partition"] = {
        "n_shards": n_shards,
        "order_wall_s": round(order_wall, 2),
        "incidence_unordered": round(inc_raw, 4),
        "incidence_multilevel": round(inc, 4),
        "ici_bytes_per_cycle": model["bytes_per_cycle"],
    }
    return record


CONFIGS = {
    "1": config_1_dsa50,
    "2": config_2_maxsum1k,
    "3": config_3_mgm2_ising10k,
    "4": config_4_maxsum100k,
    "5": config_5_dpop_meetings,
    "6": config_6_maxsum1m,
    "7": config_7_mixeddsa,
    "8": config_8_serving,
    "9": config_9_partition100k,
    "10": config_10_maxsum1m_sharded,
}

# what a bare `python bench_all.py` runs: the five BASELINE configs, the
# graftserve throughput config and the graftpart quality config; the
# 1M-variable stretch configs (6, 10) must be asked for explicitly
DEFAULT_CONFIGS = ["1", "2", "3", "4", "5", "8", "9"]

# configs whose records MUST carry a per-op/per-phase kernel block
# (graftcap refuses to call a capture healthy when one of these comes
# back with attribution missing/skipped/error)
KERNEL_CONFIGS = {"2", "3", "4"}

# single source of truth for metric names (bench.py's fallback placeholders
# must stay in sync with the names the config functions emit)
METRIC_NAMES = {
    "1": "dsa_coloring50_wall",
    "2": "maxsum_1k_random_wall",
    "3": "mgm2_ising10k_wall",
    "4": "maxsum_100k_scalefree_wall",
    "5": "dpop_meetings_wall",
    "6": "maxsum_1m_scalefree_wall",
    "7": "mixeddsa_2k_mixed_wall",
    "8": "serving_batch32_wall",
    "9": "partition_100k_incidence",
    "10": "maxsum_1m_sharded_wall",
}


def run_config(key: str) -> dict:
    """One config -> one record; errors become a {value: None, error} record
    so one bad config never silences the rest.  Shared by bench.py's
    watchdog children."""
    try:
        record = CONFIGS[key]()
    except Exception as exc:  # noqa: BLE001
        record = {
            "metric": METRIC_NAMES[key],
            "value": None,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }
    record["config"] = key
    # headline extras: vs_baseline = speedup vs the 10 s north-star budget
    # (set here, not in bench.py's parent, so records are final when they
    # stream out of the watchdog child line by line).  The baseline is a
    # TPU target: a CPU-fallback run REFUSES to claim it (round-3 verdict
    # item 8 — a CPU number must never masquerade as the headline)
    if key == "4" and record.get("value"):
        if record.get("device") == "tpu":
            record["vs_baseline"] = round(10.0 / record["value"], 2)
        else:
            record["vs_baseline"] = None
            record["vs_baseline_note"] = (
                f"not claimed: ran on {record.get('device')}, the "
                "baseline target is TPU"
            )
        record.setdefault("n_vars", 100_000)
    return record


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="pin CPU platform")
    ap.add_argument(
        "configs", nargs="*", default=DEFAULT_CONFIGS,
        help="config numbers to run (default: all)",
    )
    args = ap.parse_args()
    from pydcop_tpu.utils.platform import enable_compilation_cache, pin_cpu

    wanted = args.configs or DEFAULT_CONFIGS
    if args.cpu:
        # config 10 shards over a virtual mesh: the device count must be
        # pinned before the first backend build.  Pinning changes the
        # XLA host backend for the WHOLE process, which would silently
        # skew every co-requested config's timed wall against its
        # single-backend BENCH history — so config 10 must run alone.
        if "10" in wanted and wanted != ["10"]:
            ap.error(
                "config 10 pins 8 virtual CPU devices and must run "
                "alone: `python bench_all.py --cpu 10`"
            )
        pin_cpu(8 if wanted == ["10"] else None)
    else:
        enable_compilation_cache()
    for key in wanted:
        print(json.dumps(run_config(key)))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
