"""Fault schedules: the declarative half of graftchaos.

A :class:`FaultSchedule` is a seed plus a list of fault events — timed
crashes (*kill agent a2 at t=0.2s*), message-stream rules (*drop messages
matching a pattern with probability p*, delay, duplicate, reorder,
transport errors) and one-shot device-step faults.  Schedules load from
YAML (``--fault-schedule`` / the ``chaos`` verb) or are built
programmatically in tests.

Determinism contract (docs/chaos.md): probabilistic decisions are NOT
drawn from a shared PRNG stream — thread interleaving would then change
which message consumes which draw.  Instead every decision is a keyed
hash of ``(seed, rule, message stream, per-stream sequence number)``
(:func:`unit_draw`), so the decision for the n-th message of a given
(src, dest, type) stream is a pure function of the schedule.  The fault
event log sorted by (stream, n) is therefore bit-identical across runs
with the same seed and schedule, no matter how the threads race.

Stdlib-only except for the optional YAML loader (PyYAML ships with the
rest of the project's YAML formats).
"""

from __future__ import annotations

import fnmatch
import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

__all__ = [
    "KillEvent",
    "KillProcessEvent",
    "MessageRule",
    "DeviceFault",
    "FaultSchedule",
    "load_fault_schedule",
    "unit_draw",
    "MESSAGE_ACTIONS",
]

#: message-stream actions a rule may apply
MESSAGE_ACTIONS = ("drop", "delay", "duplicate", "reorder", "transport_error")


def unit_draw(seed: int, stream: str, n: int) -> float:
    """Deterministic uniform draw in [0, 1) keyed by (seed, stream, n).

    blake2b keeps this stable across processes and Python versions
    (``hash()`` is salted per process and would break replay)."""
    digest = hashlib.blake2b(
        f"{seed}|{stream}|{n}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2**64


@dataclass(frozen=True)
class KillEvent:
    """Crash ``agent`` abruptly ``at`` seconds after the run starts: no
    clean shutdown, no queue draining, inbound transport dies with it.
    The orchestrator then repairs the orphans like any real failure."""

    agent: str
    at: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {"kill": self.agent, "at": self.at}


@dataclass(frozen=True)
class KillProcessEvent:
    """Kill THIS WHOLE PROCESS abruptly ``at`` seconds after the run
    starts: ``os._exit(exit_code)`` — no atexit hooks, no stream
    flushing, no queue draining.  The crash model of the graftdur
    kill-and-resume soak (``make durability-smoke``): everything that
    should survive must already be on disk, which is exactly what the
    atomic checkpoint writes guarantee.  Default exit code 137 mirrors a
    SIGKILL death."""

    at: float = 0.0
    exit_code: int = 137

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kill_process": True, "at": self.at,
            "exit_code": self.exit_code,
        }


@dataclass(frozen=True)
class MessageRule:
    """A message-stream fault active for the whole run.

    ``action``: one of :data:`MESSAGE_ACTIONS`.  ``pattern`` fnmatch-es
    the message *type*; ``dest``/``src`` optionally fnmatch the
    destination/sender computation names.  ``p`` is the per-message
    firing probability (decided by :func:`unit_draw`); ``count`` caps
    total firings (globally, first-come — only deterministic when the
    rule matches a single stream); ``seconds`` sizes delays (``delay``
    sleeps exactly ``seconds``; ``reorder`` sleeps ``seconds * draw`` so
    racing senders interleave differently)."""

    action: str
    pattern: str = "*"
    dest: Optional[str] = None
    src: Optional[str] = None
    p: float = 1.0
    count: Optional[int] = None
    seconds: float = 0.05

    def __post_init__(self) -> None:
        if self.action not in MESSAGE_ACTIONS:
            raise ValueError(
                f"invalid fault action {self.action!r}: "
                f"expected one of {MESSAGE_ACTIONS}"
            )
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"probability p={self.p} outside [0, 1]")

    def matches(
        self, src_comp: str, dest_comp: str, msg_type: str
    ) -> bool:
        if not fnmatch.fnmatchcase(msg_type, self.pattern):
            return False
        if self.dest is not None and not fnmatch.fnmatchcase(
            dest_comp, self.dest
        ):
            return False
        if self.src is not None and not fnmatch.fnmatchcase(
            src_comp, self.src
        ):
            return False
        return True

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {self.action: self.pattern, "p": self.p}
        if self.dest is not None:
            out["dest"] = self.dest
        if self.src is not None:
            out["src"] = self.src
        if self.count is not None:
            out["count"] = self.count
        if self.action in ("delay", "reorder"):
            out["seconds"] = self.seconds
        return out


@dataclass(frozen=True)
class DeviceFault:
    """Fail the next ``count`` device solve steps once each (the
    orchestrator's device-solve retry absorbs them)."""

    count: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return {"device_fault": self.count}


FaultEvent = Union[KillEvent, KillProcessEvent, MessageRule, DeviceFault]


@dataclass
class FaultSchedule:
    """A seed + fault events; see the module docstring for determinism."""

    seed: int = 0
    events: List[FaultEvent] = field(default_factory=list)

    @property
    def kills(self) -> List[KillEvent]:
        return [e for e in self.events if isinstance(e, KillEvent)]

    @property
    def process_kills(self) -> List[KillProcessEvent]:
        return [
            e for e in self.events if isinstance(e, KillProcessEvent)
        ]

    @property
    def rules(self) -> List[MessageRule]:
        return [e for e in self.events if isinstance(e, MessageRule)]

    @property
    def device_faults(self) -> int:
        return sum(
            e.count for e in self.events if isinstance(e, DeviceFault)
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "events": [e.to_dict() for e in self.events],
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FaultSchedule":
        if not isinstance(data, dict):
            raise ValueError(
                f"fault schedule must be a mapping, got {type(data).__name__}"
            )
        events: List[FaultEvent] = []
        for i, raw in enumerate(data.get("events") or []):
            events.append(_parse_event(raw, i))
        return cls(seed=int(data.get("seed", 0)), events=events)


def _parse_event(raw: Dict[str, Any], index: int) -> FaultEvent:
    if not isinstance(raw, dict):
        raise ValueError(f"event {index}: must be a mapping, got {raw!r}")
    if "kill_process" in raw:
        # accept `kill_process: true` + `at: T` and the `kill_process: T`
        # shorthand; `kill_process: false`/empty must NOT silently mean
        # "kill at t=0" — a templated schedule toggling the event off
        # would nuke the process instead
        kp = raw["kill_process"]
        if kp is None or kp is False:
            raise ValueError(
                f"event {index}: kill_process must be true or a time "
                f"in seconds (got {kp!r}); delete the event to disable it"
            )
        at = raw.get("at")
        if at is None and isinstance(kp, (int, float)) and not isinstance(
            kp, bool
        ):
            at = kp
        return KillProcessEvent(
            at=float(at or 0.0), exit_code=int(raw.get("exit_code", 137))
        )
    if "kill" in raw:
        return KillEvent(
            agent=str(raw["kill"]), at=float(raw.get("at", 0.0))
        )
    if "device_fault" in raw:
        return DeviceFault(count=int(raw["device_fault"]))
    for action in MESSAGE_ACTIONS:
        if action in raw:
            return MessageRule(
                action=action,
                pattern=str(raw[action]),
                dest=raw.get("dest"),
                src=raw.get("src"),
                p=float(raw.get("p", 1.0)),
                count=(
                    int(raw["count"]) if raw.get("count") is not None
                    else None
                ),
                seconds=float(raw.get("seconds", 0.05)),
            )
    raise ValueError(
        f"event {index}: unknown fault kind in {sorted(raw)} — expected "
        f"'kill', 'kill_process', 'device_fault' or one of "
        f"{MESSAGE_ACTIONS}"
    )


def load_fault_schedule(source: str) -> FaultSchedule:
    """A schedule from a YAML file path or an inline YAML string."""
    import os

    import yaml

    text = source
    if os.path.exists(source):
        with open(source, "r", encoding="utf-8") as f:
            text = f.read()
    data = yaml.safe_load(text)
    if isinstance(data, str):
        raise ValueError(
            f"fault schedule {source!r}: not a mapping (is the path right?)"
        )
    return FaultSchedule.from_dict(data or {})
