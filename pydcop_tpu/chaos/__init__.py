"""graftchaos: deterministic, seeded fault injection (docs/chaos.md).

The reference pyDCOP's resilience machinery — k-replication plus
repair-as-a-DCOP — is fully ported here, but a failure path that is
never exercised is a failure path that does not work.  This package
turns failures into a first-class, replayable input:

- :class:`FaultSchedule` (schedule.py): YAML or programmatic fault
  events — timed agent kills, message drop/delay/duplicate/reorder,
  transport errors, one-shot device-step faults — under one seed.
- :class:`ChaosController` (controller.py): live decisions + the
  deterministic fault event log (bit-identical for the same seed and
  schedule, thread races notwithstanding).
- :class:`ChaosCommunicationLayer` (layer.py): wraps any communication
  layer and injects the message faults on the outbound path.

Surface: ``--fault-schedule`` on ``run``/``solve``, the
``pydcop_tpu chaos`` verb, ``chaos.events`` in the telemetry registry,
and the seeded soak scenarios in ``tests/test_resilience.py``.
"""

from .controller import ChaosController, FaultDecision
from .layer import ChaosCommunicationLayer
from .schedule import (
    DeviceFault,
    FaultSchedule,
    KillEvent,
    KillProcessEvent,
    MessageRule,
    load_fault_schedule,
    unit_draw,
)

__all__ = [
    "ChaosController",
    "ChaosCommunicationLayer",
    "DeviceFault",
    "FaultDecision",
    "FaultSchedule",
    "KillEvent",
    "KillProcessEvent",
    "MessageRule",
    "load_fault_schedule",
    "unit_draw",
]
