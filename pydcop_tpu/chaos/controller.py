"""Chaos controller: turns a :class:`~.schedule.FaultSchedule` into live
fault decisions and a deterministic event log.

One controller serves a whole run: every wrapped communication layer
(:class:`~.layer.ChaosCommunicationLayer`) asks it what to do with each
outbound message, the orchestrator asks it whether to fail a device step,
and a timeline thread fires the timed kill events.  All decisions are
keyed-hash draws (schedule.unit_draw), so the log — sorted canonically —
is bit-identical for the same seed + schedule (docs/chaos.md).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..telemetry.metrics import metrics_registry
from .schedule import FaultSchedule, MessageRule, unit_draw

__all__ = ["ChaosController", "FaultDecision"]

logger = logging.getLogger("pydcop_tpu.chaos")

_m_chaos_events = metrics_registry.counter(
    "chaos.events", "injected fault events, by action"
)


class FaultDecision:
    """What to do with one outbound message: the matched actions in rule
    order.  ``drop``/``transport_error`` are terminal; ``delay_s`` > 0
    means sleep before sending; ``duplicates`` adds extra sends."""

    __slots__ = ("drop", "transport_error", "delay_s", "duplicates")

    def __init__(self) -> None:
        self.drop = False
        self.transport_error = False
        self.delay_s = 0.0
        self.duplicates = 0

    @property
    def clean(self) -> bool:
        return not (
            self.drop
            or self.transport_error
            or self.delay_s
            or self.duplicates
        )


class ChaosController:
    """Live fault injection driven by a schedule.

    Thread-safe: per-stream sequence counters and the event log are
    guarded by one lock; no message send ever happens under it."""

    def __init__(self, schedule: FaultSchedule) -> None:
        self.schedule = schedule
        self.seed = schedule.seed
        self._rules: List[MessageRule] = schedule.rules
        self._lock = threading.Lock()
        self._stream_seq: Dict[str, int] = {}
        self._rule_firings: Dict[int, int] = {}
        self._log: List[Dict[str, Any]] = []
        self._device_faults_left = schedule.device_faults
        self._device_fault_n = 0
        # written once by start() (idempotence guarded by _timeline_started
        # under the lock), then only read — never touched under the lock
        self._kill_thread: Optional[threading.Thread] = None
        self._timeline_started = False
        self._stop_evt = threading.Event()
        self._action_counts: Dict[str, int] = {}

    # -- message faults ------------------------------------------------

    def on_send(
        self,
        src_agent: str,
        dest_agent: str,
        sender_comp: str,
        dest_comp: str,
        msg_type: str,
    ) -> FaultDecision:
        """Decide the fate of one outbound message.  One keyed draw per
        matching rule; every firing is logged."""
        decision = FaultDecision()
        if not self._rules:
            return decision
        stream = f"{sender_comp}>{dest_comp}:{msg_type}"
        fired: List[Dict[str, Any]] = []
        with self._lock:
            n = self._stream_seq.get(stream, 0)
            self._stream_seq[stream] = n + 1
            for rule_id, rule in enumerate(self._rules):
                if not rule.matches(sender_comp, dest_comp, msg_type):
                    continue
                draw = unit_draw(self.seed, f"{rule_id}|{stream}", n)
                if draw >= rule.p:
                    continue
                if rule.count is not None:
                    if self._rule_firings.get(rule_id, 0) >= rule.count:
                        continue
                    self._rule_firings[rule_id] = (
                        self._rule_firings.get(rule_id, 0) + 1
                    )
                entry = {
                    "stream": stream,
                    "n": n,
                    "rule": rule_id,
                    "action": rule.action,
                    "draw": round(draw, 9),
                }
                self._log.append(entry)
                fired.append(entry)
                self._action_counts[rule.action] = (
                    self._action_counts.get(rule.action, 0) + 1
                )
                if rule.action == "drop":
                    decision.drop = True
                elif rule.action == "transport_error":
                    decision.transport_error = True
                elif rule.action == "delay":
                    decision.delay_s += rule.seconds
                elif rule.action == "reorder":
                    decision.delay_s += rule.seconds * draw
                elif rule.action == "duplicate":
                    decision.duplicates += 1
        for entry in fired:
            if metrics_registry.enabled:
                _m_chaos_events.inc(action=entry["action"])
            logger.debug(
                "chaos: %s %s#%d (rule %d)",
                entry["action"], entry["stream"], entry["n"], entry["rule"],
            )
        return decision

    # -- device faults ---------------------------------------------------

    def device_fault(self) -> bool:
        """True exactly once per scheduled device fault: the caller must
        fail that solve step."""
        with self._lock:
            if self._device_faults_left <= 0:
                return False
            self._device_faults_left -= 1
            n = self._device_fault_n
            self._device_fault_n += 1
            self._log.append(
                {"stream": "_device", "n": n, "action": "device_fault"}
            )
            self._action_counts["device_fault"] = (
                self._action_counts.get("device_fault", 0) + 1
            )
        if metrics_registry.enabled:
            _m_chaos_events.inc(action="device_fault")
        logger.warning("chaos: injecting device step fault #%d", n)
        return True

    # -- kill timeline ---------------------------------------------------

    def start(self, kill_cb: Optional[Callable[[str], None]]) -> None:
        """Start the timeline thread firing the schedule's kill events —
        agent kills through ``kill_cb(agent_name)``, whole-process kills
        (graftdur's crash model) via ``os._exit``.  ``kill_cb=None``
        (direct-mode runs: no agents exist) arms ONLY the process kills;
        scheduled agent kills are logged as skipped.  Idempotent per
        controller."""
        kills = sorted(
            list(self.schedule.kills) + list(self.schedule.process_kills),
            key=lambda k: (
                k.at, getattr(k, "agent", ""),
            ),
        )
        with self._lock:
            if self._timeline_started:
                return
            self._timeline_started = True
        if not kills:
            return
        self._kill_thread = threading.Thread(
            target=self._run_timeline,
            args=(kills, kill_cb),
            name="chaos-timeline",
            daemon=True,
        )
        self._kill_thread.start()

    def _run_timeline(self, kills, kill_cb) -> None:
        from .schedule import KillProcessEvent

        t0 = time.monotonic()
        for n, k in enumerate(kills):
            wait = k.at - (time.monotonic() - t0)
            if wait > 0 and self._stop_evt.wait(wait):
                return
            if self._stop_evt.is_set():
                return
            if isinstance(k, KillProcessEvent):
                # abrupt whole-process death: nothing below this line runs.
                # The log entry cannot outlive the process — what survives
                # is what was already durably on disk (the graftdur
                # checkpoints this event exists to exercise)
                logger.warning(
                    "chaos: killing PROCESS (t=%.3fs, exit %d)",
                    k.at, k.exit_code,
                )
                import os
                import sys

                try:
                    sys.stderr.flush()
                    sys.stdout.flush()
                except Exception:  # noqa: BLE001 — dying anyway
                    pass
                os._exit(k.exit_code)
            if kill_cb is None:
                logger.warning(
                    "chaos: agent kill of %s skipped — no agent runtime "
                    "in this mode (direct-mode run)", k.agent,
                )
                continue
            # logged at FIRE time, not schedule time: a run whose timeout
            # cancels the tail of the timeline must not report kills that
            # never happened (Orchestrator.run waits for the timeline, so
            # a completed run always fires — and logs — the full schedule)
            with self._lock:
                self._log.append(
                    {
                        "stream": "_timeline",
                        "n": n,
                        "action": "kill",
                        "agent": k.agent,
                        "at": k.at,
                    }
                )
                self._action_counts["kill"] = (
                    self._action_counts.get("kill", 0) + 1
                )
            if metrics_registry.enabled:
                _m_chaos_events.inc(action="kill")
            logger.warning("chaos: killing agent %s (t=%.3fs)", k.agent, k.at)
            try:
                kill_cb(k.agent)
            except Exception:
                logger.exception("chaos: kill of %s failed", k.agent)

    def wait_timeline(self, timeout: Optional[float] = None) -> bool:
        """Block until every timeline event has fired AND its callback
        (crash + repair) returned.  The schedule defines the run's fault
        timeline: a kill due at t=0.15s happens even when the solve
        returned at t=0.05s — otherwise replaying the same schedule would
        exercise different faults depending on machine speed.  Returns
        False if the timeline is still running at ``timeout``."""
        t = self._kill_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def stop(self) -> None:
        """Cancel pending timeline events (already-fired ones stand)."""
        self._stop_evt.set()
        t = self._kill_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    # -- the event log ---------------------------------------------------

    def event_log(self) -> List[Dict[str, Any]]:
        """Canonical log: sorted by (stream, n, rule) so two runs of the
        same seed + schedule compare bit-identical regardless of thread
        interleaving."""
        with self._lock:
            return sorted(
                (dict(e) for e in self._log),
                key=lambda e: (e["stream"], e["n"], e.get("rule", -1)),
            )

    def action_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._action_counts)

    def dump(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "seed": self.seed,
                    "events": self.event_log(),
                    "counts": self.action_counts(),
                },
                f,
                indent=2,
                sort_keys=True,
            )
            f.write("\n")
