"""ChaosCommunicationLayer: fault-injecting transport wrapper.

Wraps any :class:`~..infrastructure.communication.CommunicationLayer`
and applies the controller's per-message decisions on the OUTBOUND path:

- ``drop``: the message vanishes in flight — the sender sees a
  successful send (that is what a dropped datagram looks like), the
  receiver sees nothing.
- ``delay`` / ``reorder``: the sending thread sleeps before the real
  send.  Per-sender order is preserved (like TCP); messages from racing
  senders interleave differently, which is exactly the reorder hazard
  parked-message replay must survive.
- ``duplicate``: the message is sent again after the first send — the
  at-least-once delivery failure mode.
- ``transport_error``: the send behaves like a transport failure under
  the layer's ``on_error`` contract — ``fail`` raises
  ``UnreachableAgent``, ``ignore``/``retry`` report ``False`` (the inner
  layer never sees the message, so its own retries are not consumed).

Inbound delivery is untouched: the wrapper's address is the inner
layer's, so peers deliver straight to it and every fault is accounted
exactly once, on the sending side.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ..infrastructure.communication import (
    CommunicationLayer,
    UnknownComputation,
    UnreachableAgent,
)
from ..telemetry.metrics import metrics_registry
from .controller import ChaosController

__all__ = ["ChaosCommunicationLayer"]

logger = logging.getLogger("pydcop_tpu.chaos")

# same metric the HTTP transport uses for exhausted retries: an injected
# transport error that loses a message must be countable the same way
_m_send_failures = metrics_registry.counter(
    "comms.send_failures",
    "sends abandoned after exhausting retries, by agent and destination",
)


class ChaosCommunicationLayer(CommunicationLayer):
    """Fault-injecting decorator around a real communication layer."""

    def __init__(
        self, inner: CommunicationLayer, controller: ChaosController
    ) -> None:
        # no super().__init__: on_error lives on (and is validated by)
        # the inner layer; messaging is forwarded below so the inner
        # layer can deliver inbound messages itself
        self.inner = inner
        self.controller = controller

    @property
    def on_error(self) -> str:
        return self.inner.on_error

    @property
    def messaging(self) -> Any:
        return self.inner.messaging

    @messaging.setter
    def messaging(self, value: Any) -> None:
        self.inner.messaging = value

    @property
    def address(self) -> Any:
        return self.inner.address

    def send_msg(
        self, src_agent, dest_agent, address, sender_comp, dest_comp, msg,
        prio,
    ) -> bool:
        decision = self.controller.on_send(
            src_agent, dest_agent, sender_comp, dest_comp, msg.type
        )
        if decision.drop:
            logger.debug(
                "chaos: dropped %s %s -> %s", msg.type, sender_comp,
                dest_comp,
            )
            return True
        if decision.transport_error:
            if self.on_error == "fail":
                raise UnreachableAgent(
                    f"chaos: injected transport error sending to "
                    f"{dest_agent} at {address}"
                )
            # same loudness contract as the HTTP layer's exhausted
            # retries: a False return is invisible at call sites, so the
            # loss itself must be logged and counted
            logger.error(
                "giving up on message %s -> %s for %s (chaos: injected "
                "transport error)", sender_comp, dest_comp, dest_agent,
            )
            if metrics_registry.enabled:
                _m_send_failures.inc(agent=src_agent, dest=dest_agent)
            return False
        if decision.delay_s:
            time.sleep(decision.delay_s)
        delivered = self.inner.send_msg(
            src_agent, dest_agent, address, sender_comp, dest_comp, msg,
            prio,
        )
        for _ in range(decision.duplicates):
            try:
                self.inner.send_msg(
                    src_agent, dest_agent, address, sender_comp, dest_comp,
                    msg, prio,
                )
            except UnknownComputation:
                # the destination vanished between the primary send and
                # the duplicate (e.g. a chaos kill): the PRIMARY delivery
                # stands — letting this escape would make post_msg re-park
                # an already-delivered message
                logger.debug(
                    "chaos: duplicate of %s -> %s not deliverable",
                    sender_comp, dest_comp,
                )
        return delivered

    def shutdown(self) -> None:
        self.inner.shutdown()

    def __repr__(self) -> str:
        return f"ChaosCommunicationLayer({self.inner!r})"
