"""Removal analysis: what breaks when agents leave.

Role parity with /root/reference/pydcop/reparation/removal.py
(_removal_orphaned_computations:38, _removal_candidate_agents:61,
_removal_candidate_computation_info:101, _removal_candidate_agt_info:145):
given departed agents, compute the orphaned computations, the candidate host
agents (replica holders when replication ran, every survivor otherwise) and
the per-candidate info needed to set the repair DCOP up.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = [
    "removal_orphaned_computations",
    "removal_candidate_agents",
    "removal_candidate_computation_info",
]


def removal_orphaned_computations(
    distribution, removed_agent: str
) -> List[str]:
    """Computations that lose their host when ``removed_agent`` leaves
    (reference removal.py:38)."""
    return list(distribution.computations_hosted(removed_agent))


def removal_candidate_agents(
    orphans: List[str],
    survivors: Dict[str, Any],
    replica_hosts: Optional[Dict[str, List[str]]] = None,
) -> Dict[str, List[str]]:
    """Candidate hosts per orphan: the surviving replica holders when
    replication ran (reference removal.py:61 — only agents holding a replica
    can take a computation over), otherwise every survivor."""
    out: Dict[str, List[str]] = {}
    for comp in orphans:
        if replica_hosts and replica_hosts.get(comp):
            cands = [a for a in replica_hosts[comp] if a in survivors]
            if not cands:  # all replica holders died too: fall back to all
                cands = sorted(survivors)
        else:
            cands = sorted(survivors)
        out[comp] = cands
    return out


def removal_candidate_computation_info(
    comp: str, cg, distribution, removed_agent: str
) -> Dict[str, Any]:
    """The neighbor info a candidate host needs to price taking ``comp`` over
    (reference removal.py:101): neighbor computations and their current
    hosting agents (excluding the departed one)."""
    node = cg.computation(comp)
    neighbors: Dict[str, str] = {}
    for n in node.neighbors:
        try:
            a = distribution.agent_for(n)
        except (KeyError, ValueError):
            continue
        if a != removed_agent:
            neighbors[n] = a
    return {"computation": comp, "neighbors": neighbors}
