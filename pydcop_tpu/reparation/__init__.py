"""Repair DCOPs: re-hosting orphaned computations after agent failures.

Role parity with /root/reference/pydcop/reparation/__init__.py — the four
constraint builders over binary variables ``x_(computation, agent)``:
``create_computation_hosted_constraint`` (:39, hard: each orphan hosted
exactly once), ``create_agent_capacity_constraint`` (:70, hard),
``create_agent_hosting_constraint`` (:117, soft hosting costs) and
``create_agent_comp_comm_constraint`` (:158, soft communication costs =
algorithm ``communication_load`` x route costs).

The reference solves this DCOP with MGM-2 distributed across the surviving
agents (infrastructure/agents.py:1047-1258).  The TPU build frames repair
exactly the same way — *as just another DCOP* — and therefore solves it on
device with the batched MGM-2 solver (SURVEY.md §7.7): ``repair_dcop`` builds
the problem, ``repair_distribution`` solves it and applies the result.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ..dcop.dcop import DCOP
from ..dcop.objects import AgentDef, BinaryVariable
from ..dcop.relations import NAryFunctionRelation
from .removal import (
    removal_candidate_agents,
    removal_orphaned_computations,
)

__all__ = [
    "create_computation_hosted_constraint",
    "create_agent_capacity_constraint",
    "create_agent_hosting_constraint",
    "create_agent_comp_comm_constraint",
    "repair_dcop",
    "repair_distribution",
]

logger = logging.getLogger("pydcop_tpu.reparation")

HARD = 10000.0


def binary_var_name(computation: str, agent: str) -> str:
    return f"x_{computation}__{agent}"


def create_computation_hosted_constraint(
    computation: str, candidate_vars: List[BinaryVariable]
):
    """Hard constraint: exactly one candidate agent hosts ``computation``
    (reference reparation/__init__.py:39)."""

    def hosted(**kw) -> float:
        return 0.0 if sum(kw.values()) == 1 else HARD

    return NAryFunctionRelation(
        hosted, candidate_vars, name=f"hosted_{computation}"
    )


def create_agent_capacity_constraint(
    agent: AgentDef,
    remaining_capacity: float,
    footprints: Dict[str, float],
    candidate_vars: Dict[str, BinaryVariable],
):
    """Hard constraint: the footprints of the orphans accepted by ``agent``
    must fit its remaining capacity (reference :70)."""
    comps = sorted(candidate_vars)
    variables = [candidate_vars[c] for c in comps]

    def capacity_ok(**kw) -> float:
        load = sum(
            footprints[c]
            for c in comps
            if kw[candidate_vars[c].name]
        )
        return 0.0 if load <= remaining_capacity else HARD

    return NAryFunctionRelation(
        capacity_ok, variables, name=f"capacity_{agent.name}"
    )


def create_agent_hosting_constraint(
    agent: AgentDef, candidate_vars: Dict[str, BinaryVariable]
):
    """Soft constraint: sum of hosting costs of the accepted orphans
    (reference :117)."""
    comps = sorted(candidate_vars)
    variables = [candidate_vars[c] for c in comps]

    def hosting(**kw) -> float:
        return float(
            sum(
                agent.hosting_cost(c)
                for c in comps
                if kw[candidate_vars[c].name]
            )
        )

    return NAryFunctionRelation(
        hosting, variables, name=f"hosting_{agent.name}"
    )


def create_agent_comp_comm_constraint(
    agent: AgentDef,
    computation: str,
    neighbor_agents: Dict[str, str],
    comm_loads: Dict[str, float],
    var: BinaryVariable,
):
    """Soft constraint: if ``agent`` hosts ``computation``, pay the
    communication cost to each neighbor computation's hosting agent —
    ``communication_load(comp, neighbor) x route(agent, neighbor_agent)``
    (reference :158).

    ``neighbor_agents``: neighbor computation -> hosting agent;
    ``comm_loads``: neighbor computation -> message load.
    """

    def comm(x) -> float:
        if not x:
            return 0.0
        return float(
            sum(
                comm_loads[n] * agent.route(neighbor_agents[n])
                for n in neighbor_agents
            )
        )

    return NAryFunctionRelation(
        comm, [var], name=f"comm_{computation}_{agent.name}", f_kwargs=False
    )


def _footprint(cg, comp_name: str, algo) -> float:
    from ..algorithms import load_algorithm_module

    mod = load_algorithm_module(algo.algo)
    fn = getattr(mod, "computation_memory", None)
    if fn is None:
        return 1.0
    try:
        return float(fn(cg.computation(comp_name)))
    except (NotImplementedError, ValueError, AttributeError):
        return 1.0


def _comm_load(cg, comp_name: str, neighbor: str, algo) -> float:
    from ..algorithms import load_algorithm_module

    mod = load_algorithm_module(algo.algo)
    fn = getattr(mod, "communication_load", None)
    if fn is None:
        return 1.0
    try:
        return float(fn(cg.computation(comp_name), neighbor))
    except (NotImplementedError, ValueError, AttributeError):
        return 1.0


def repair_dcop(
    cg,
    agent_defs: List[AgentDef],
    distribution,
    removed_agent: str,
    algo,
    replica_hosts: Optional[Dict[str, List[str]]] = None,
) -> Tuple[DCOP, Dict[str, Dict[str, BinaryVariable]]]:
    """Build the reparation DCOP for the orphans of ``removed_agent``.

    Returns (dcop, candidate_vars) with candidate_vars[comp][agent] the
    binary decision variable "agent hosts comp".
    """
    orphans = removal_orphaned_computations(distribution, removed_agent)
    survivors = {a.name: a for a in agent_defs if a.name != removed_agent}
    if not survivors:
        raise ValueError("no surviving agent to repair onto")

    candidates = removal_candidate_agents(
        orphans, survivors, replica_hosts
    )

    dcop = DCOP(f"repair_{removed_agent}", "min")
    candidate_vars: Dict[str, Dict[str, BinaryVariable]] = {}
    for comp in orphans:
        candidate_vars[comp] = {}
        for a in candidates[comp]:
            v = BinaryVariable(binary_var_name(comp, a))
            candidate_vars[comp][a] = v
            dcop.add_variable(v)

    # hard: each orphan hosted exactly once
    for comp in orphans:
        dcop.add_constraint(
            create_computation_hosted_constraint(
                comp, list(candidate_vars[comp].values())
            )
        )

    # per-agent: capacity (hard) + hosting costs (soft)
    footprints = {c: _footprint(cg, c, algo) for c in orphans}
    for a_name, a_def in survivors.items():
        agent_vars = {
            comp: candidate_vars[comp][a_name]
            for comp in orphans
            if a_name in candidate_vars[comp]
        }
        if not agent_vars:
            continue
        used = sum(
            _footprint(cg, c, algo)
            for c in distribution.computations_hosted(a_name)
        )
        remaining = max(0.0, float(a_def.capacity) - used)
        dcop.add_constraint(
            create_agent_capacity_constraint(
                a_def, remaining, footprints, agent_vars
            )
        )
        dcop.add_constraint(
            create_agent_hosting_constraint(a_def, agent_vars)
        )
        # soft: communication costs to the orphan's neighbors, priced at
        # their *current* hosting agents
        for comp, var in agent_vars.items():
            node = cg.computation(comp)
            neighbor_agents = {}
            comm_loads = {}
            for n in node.neighbors:
                try:
                    n_agent = distribution.agent_for(n)
                except (KeyError, ValueError):
                    continue
                if n_agent == removed_agent:
                    # neighbors orphaned with us have no current host; the
                    # reference excludes the departed agent the same way
                    # (removal.py:101)
                    continue
                neighbor_agents[n] = n_agent
                comm_loads[n] = _comm_load(cg, comp, n, algo)
            if neighbor_agents:
                dcop.add_constraint(
                    create_agent_comp_comm_constraint(
                        a_def, comp, neighbor_agents, comm_loads, var
                    )
                )
    dcop.add_agents(list(survivors.values()))
    return dcop, candidate_vars


def repair_distribution(
    cg,
    agent_defs: List[AgentDef],
    distribution,
    removed_agent: str,
    algo,
    replica_hosts: Optional[Dict[str, List[str]]] = None,
    n_cycles: int = 30,
    seed: int = 0,
):
    """Solve the repair DCOP with batched MGM-2 on device and apply the
    winning placement (the reference's decentralized repair,
    agents.py:1260-1372, re-expressed as a compiled solve).

    Returns (new_distribution, metrics).
    """
    from ..api import solve_result
    from ..distribution.objects import Distribution

    dcop, candidate_vars = repair_dcop(
        cg, agent_defs, distribution, removed_agent, algo, replica_hosts
    )
    try:
        r = solve_result(dcop, "mgm2", n_cycles=n_cycles, seed=seed)
        assignment = r["assignment"]
        status = {
            "repair_status": r["status"],
            "repair_cost": r["cost"],
            "repair_violation": r["violation"],
            "repair_cycles": r["cycle"],
        }
    except NotImplementedError:
        # an agent with many orphan candidates makes its capacity/hosting
        # constraints span >MAX_TABLE_ELEMS assignments (compile/core.py
        # dense-tabulation guard).  The reference's per-agent MGM-2 has no
        # such limit, so rather than failing the repair, fall back to a
        # greedy per-orphan placement (largest footprint first, cheapest
        # fitting agent).
        logger.warning(
            "repair DCOP too large to tabulate; using greedy placement"
        )
        assignment, n_relaxed, greedy_cost = _greedy_repair_assignment(
            cg, agent_defs, distribution, removed_agent, algo,
            candidate_vars,
        )
        status = {
            "repair_status": "GREEDY",
            "repair_cost": greedy_cost,
            # placements that only fit by relaxing an agent's capacity are
            # real constraint violations and must be reported as such
            "repair_violation": n_relaxed,
            "repair_cycles": 0,
        }

    mapping = {
        a: list(distribution.computations_hosted(a))
        for a in distribution.agents
        if a != removed_agent
    }
    agent_defs_by_name = {a.name: a for a in agent_defs}
    migrated: Dict[str, str] = {}
    for comp, by_agent in candidate_vars.items():
        chosen = [a for a, v in by_agent.items() if assignment[v.name] == 1]
        if len(chosen) != 1:
            # repair solve failed to satisfy the hard hosted-exactly-once
            # constraint (0 hosts) or over-selected (2+): fall back to the
            # cheapest candidate by hosting cost (among the mgm2 picks when
            # there are several)
            logger.warning(
                "repair: orphan %s got %d hosts from mgm2, using greedy "
                "fallback", comp, len(chosen),
            )
            pool = chosen if chosen else sorted(by_agent)
            chosen = [
                min(
                    pool,
                    key=lambda a: (
                        agent_defs_by_name[a].hosting_cost(comp)
                        if a in agent_defs_by_name
                        else 0.0,
                        a,
                    ),
                )
            ]
        mapping.setdefault(chosen[0], []).append(comp)
        migrated[comp] = chosen[0]
    new_dist = Distribution(mapping)
    metrics = dict(status, migrated=migrated)
    return new_dist, metrics


def _greedy_repair_assignment(
    cg,
    agent_defs: List[AgentDef],
    distribution,
    removed_agent: str,
    algo,
    candidate_vars: Dict[str, Dict[str, BinaryVariable]],
) -> Tuple[Dict[str, int], int]:
    """Greedy per-orphan placement as a binary-variable assignment: largest
    footprint first, cheapest (hosting cost) candidate with remaining
    capacity; capacity is relaxed when nothing fits (mirrors the hard/soft
    split of the repair DCOP's constraints).

    Returns (assignment, n_relaxed, hosting_cost): n_relaxed counts
    placements that needed the capacity relaxation; hosting_cost is the
    summed hosting cost of the chosen placement."""
    survivors = {a.name: a for a in agent_defs if a.name != removed_agent}
    remaining = {}
    for name, a_def in survivors.items():
        used = sum(
            _footprint(cg, c, algo)
            for c in distribution.computations_hosted(name)
        )
        remaining[name] = max(0.0, float(a_def.capacity) - used)
    footprints = {c: _footprint(cg, c, algo) for c in candidate_vars}

    assignment = {
        v.name: 0
        for by_agent in candidate_vars.values()
        for v in by_agent.values()
    }
    n_relaxed = 0
    hosting_cost = 0.0
    for comp in sorted(candidate_vars, key=lambda c: (-footprints[c], c)):
        by_agent = candidate_vars[comp]
        fits = [
            a for a in by_agent if remaining.get(a, 0.0) >= footprints[comp]
        ]
        if not fits:
            n_relaxed += 1
        pool = fits or sorted(by_agent)
        chosen = min(
            pool,
            key=lambda a: (
                survivors[a].hosting_cost(comp) if a in survivors else 0.0,
                a,
            ),
        )
        remaining[chosen] = remaining.get(chosen, 0.0) - footprints[comp]
        if chosen in survivors:
            hosting_cost += float(survivors[chosen].hosting_cost(comp))
        assignment[by_agent[chosen].name] = 1
    return assignment, n_relaxed, hosting_cost
