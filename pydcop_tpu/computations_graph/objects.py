"""Computation-graph base objects.

Role parity with /root/reference/pydcop/computations_graph/objects.py
(ComputationNode:37, Link:136, ComputationGraph:197).  Nodes are serializable
(they are the unit shipped to agents at deploy time); links may be hyperedges.

TPU-first note: these graphs are *host-side metadata*.  `pydcop_tpu.compile`
lowers a graph once into gather/scatter index arrays; the solve path never
walks these objects.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Set

from ..utils.simple_repr import SimpleRepr

__all__ = ["ComputationNode", "Link", "ComputationGraph"]


class Link(SimpleRepr):
    """A (hyper)edge between computation nodes, with a type tag."""

    _repr_fields = ("link_type", "nodes")

    def __init__(self, nodes: Iterable[str], link_type: str = "link") -> None:
        self._nodes = tuple(sorted(nodes))
        self._link_type = link_type

    @property
    def nodes(self) -> Sequence[str]:
        return self._nodes

    @property
    def type(self) -> str:
        return self._link_type

    def has_node(self, name: str) -> bool:
        return name in self._nodes

    @classmethod
    def _from_repr(cls, link_type, nodes):
        # always rebuild a BASE link: subclasses (PseudoTreeLink, OrderLink,
        # FactorGraphLink) have richer constructors but links only ship as
        # graph metadata (see ComputationNode docstring)
        return Link(nodes, link_type)

    def __eq__(self, other):
        return (
            isinstance(other, Link)
            and other._nodes == self._nodes
            and other._link_type == self._link_type
        )

    def __hash__(self):
        return hash((self._nodes, self._link_type))

    def __repr__(self) -> str:
        return f"Link({self._link_type}, {self._nodes})"


class ComputationNode(SimpleRepr):
    """A node in a computation graph: a named unit of computation with links.

    ``type`` identifies the node kind for the algorithm (e.g. VariableComputation
    vs FactorComputation in a factor graph).

    Serialization note: nodes are shipped to agents at deploy/replication time
    (inside ComputationDefs).  They deserialize as *base* ComputationNodes —
    name, type and links (so neighbors survive) — because the TPU runtime
    recompiles device arrays from the DCOP itself; algorithm-specific node
    payloads (Variable/Constraint objects) never need to travel.
    """

    _repr_fields = ("name", "node_type", "links")

    def __init__(
        self,
        name: str,
        node_type: str = "computation",
        links: Optional[Iterable[Link]] = None,
    ) -> None:
        self._name = name
        self._node_type = node_type
        self._links = list(links) if links else []

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._node_type

    @property
    def links(self) -> List[Link]:
        return list(self._links)

    @property
    def neighbors(self) -> List[str]:
        out: List[str] = []
        for l in self._links:
            for n in l.nodes:
                if n != self._name and n not in out:
                    out.append(n)
        return out

    def add_link(self, link: Link) -> None:
        self._links.append(link)

    @classmethod
    def _from_repr(cls, name, node_type, links):
        # always rebuild a BASE node (see class docstring): subclasses carry
        # runtime-only payloads that are not shipped
        return ComputationNode(name, node_type, links)

    def __eq__(self, other):
        return (
            isinstance(other, ComputationNode)
            and other.name == self.name
            and other.type == self.type
        )

    def __hash__(self):
        return hash((self._name, self._node_type))

    def __repr__(self) -> str:
        return f"ComputationNode({self._name}, {self._node_type})"


class ComputationGraph:
    """Base class for computation graphs.

    Subclasses set ``graph_type`` and provide ``nodes``; links are derived.
    """

    graph_type = "generic"

    def __init__(
        self, nodes: Optional[Iterable[ComputationNode]] = None
    ) -> None:
        self._nodes: Dict[str, ComputationNode] = {}
        for n in nodes or []:
            self.add_node(n)

    def add_node(self, node: ComputationNode) -> None:
        self._nodes[node.name] = node

    @property
    def nodes(self) -> List[ComputationNode]:
        return list(self._nodes.values())

    def computation(self, name: str) -> ComputationNode:
        try:
            return self._nodes[name]
        except KeyError:
            raise KeyError(f"no computation {name} in graph")

    def computations(self) -> List[ComputationNode]:
        return list(self._nodes.values())

    @property
    def links(self) -> List[Link]:
        seen: Set[Link] = set()
        out: List[Link] = []
        for n in self._nodes.values():
            for l in n.links:
                if l not in seen:
                    seen.add(l)
                    out.append(l)
        return out

    def neighbors(self, name: str) -> List[str]:
        return self.computation(name).neighbors

    def node_count(self) -> int:
        return len(self._nodes)

    def link_count(self) -> int:
        return len(self.links)

    def density(self) -> float:
        n = self.node_count()
        if n <= 1:
            return 0.0
        return 2 * self.link_count() / (n * (n - 1))

    def __contains__(self, name: str) -> bool:
        return name in self._nodes

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}({self.node_count()} nodes, "
            f"{self.link_count()} links)"
        )
