"""Ordered chain of variables, for SyncBB.

Role parity with /root/reference/pydcop/computations_graph/ordered_graph.py
(OrderLink:119, OrderedConstraintGraph:168, build_computation_graph:182 —
lexical order).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link

__all__ = [
    "OrderLink",
    "OrderedVarNode",
    "OrderedConstraintGraph",
    "build_computation_graph",
]


class OrderLink(Link):
    """Chain link: type 'next' or 'previous'."""

    def __init__(self, link_type: str, source: str, target: str) -> None:
        if link_type not in ("next", "previous"):
            raise ValueError("order link type must be 'next' or 'previous'")
        super().__init__((source, target), link_type)
        self.source = source
        self.target = target


class OrderedVarNode(ComputationNode):
    def __init__(
        self,
        variable: Variable,
        constraints: List[Constraint],
        prev_node: Optional[str],
        next_node: Optional[str],
        position: int,
    ) -> None:
        links = []
        if prev_node:
            links.append(OrderLink("previous", variable.name, prev_node))
        if next_node:
            links.append(OrderLink("next", variable.name, next_node))
        super().__init__(variable.name, "OrderedVariableComputation", links)
        self.variable = variable
        self.constraints = list(constraints)
        self.prev_node = prev_node
        self.next_node = next_node
        self.position = position


class OrderedConstraintGraph(ComputationGraph):
    graph_type = "ordered_graph"

    def ordered_nodes(self) -> List[OrderedVarNode]:
        return sorted(self.nodes, key=lambda n: n.position)


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[Constraint]] = None,
) -> OrderedConstraintGraph:
    """Lexically ordered chain; each constraint attached to its *last* variable
    in the order (so SyncBB can evaluate it as soon as the partial assignment
    reaches that variable)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    ordered = sorted(variables, key=lambda v: v.name)
    pos = {v.name: i for i, v in enumerate(ordered)}

    cons_at: Dict[str, List[Constraint]] = {v.name: [] for v in ordered}
    for c in constraints:
        scope = [v.name for v in c.dimensions if v.name in pos]
        if not scope:
            continue
        last = max(scope, key=lambda n: pos[n])
        cons_at[last].append(c)

    graph = OrderedConstraintGraph()
    for i, v in enumerate(ordered):
        prev_node = ordered[i - 1].name if i > 0 else None
        next_node = ordered[i + 1].name if i < len(ordered) - 1 else None
        graph.add_node(
            OrderedVarNode(v, cons_at[v.name], prev_node, next_node, i)
        )
    return graph
