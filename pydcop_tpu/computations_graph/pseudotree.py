"""DFS pseudo-tree: tree + pseudo-parent edges, for DPOP/NCBB.

Role parity with /root/reference/pydcop/computations_graph/pseudotree.py
(PseudoTreeLink:51, PseudoTreeNode:122, _generate_dfs_tree:325 with
max-degree root heuristic :350, constraint-to-lowest-node rule :452,
build_computation_graph:472 handling forests :533-540).

TPU-first design difference: the reference builds the tree with a distributed
token-passing protocol between agents; here the DFS is a plain host-side graph
traversal (deterministic, iterative), since tree construction is compile-time
work.  The output also carries the *schedule*: nodes grouped by depth level so
DPOP's UTIL wave can run one tensor-contraction level at a time.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link

__all__ = [
    "PseudoTreeLink",
    "PseudoTreeNode",
    "ComputationPseudoTree",
    "build_computation_graph",
    "get_dfs_relations",
]


class PseudoTreeLink(Link):
    """Link types: 'parent' (tree edge) or 'pseudo_parent' (back edge)."""

    def __init__(self, link_type: str, source: str, target: str) -> None:
        super().__init__((source, target), link_type)
        self.source = source
        self.target = target

    def __repr__(self):
        return f"PseudoTreeLink({self.type}, {self.source} -> {self.target})"


class PseudoTreeNode(ComputationNode):
    """A variable node of the pseudo-tree, with its DFS relations and the
    constraints attached to it (lowest-node rule)."""

    def __init__(
        self,
        variable: Variable,
        parent: Optional[str],
        pseudo_parents: List[str],
        children: List[str],
        pseudo_children: List[str],
        constraints: List[Constraint],
        depth: int,
    ) -> None:
        links = []
        if parent:
            links.append(PseudoTreeLink("parent", variable.name, parent))
        for pp in pseudo_parents:
            links.append(PseudoTreeLink("pseudo_parent", variable.name, pp))
        for c in children:
            links.append(PseudoTreeLink("parent", c, variable.name))
        for pc in pseudo_children:
            links.append(PseudoTreeLink("pseudo_parent", pc, variable.name))
        super().__init__(variable.name, "PseudoTreeComputation", links)
        self.variable = variable
        self.parent = parent
        self.pseudo_parents = list(pseudo_parents)
        self.children = list(children)
        self.pseudo_children = list(pseudo_children)
        self.constraints = list(constraints)
        self.depth = depth


def get_dfs_relations(
    node: PseudoTreeNode,
) -> Tuple[Optional[str], List[str], List[str], List[str]]:
    """(parent, pseudo_parents, children, pseudo_children) — reference
    pseudotree.py:178."""
    return (
        node.parent,
        list(node.pseudo_parents),
        list(node.children),
        list(node.pseudo_children),
    )


class ComputationPseudoTree(ComputationGraph):
    graph_type = "pseudotree"

    def __init__(self, nodes: Iterable[PseudoTreeNode]) -> None:
        super().__init__(nodes)

    @property
    def roots(self) -> List[PseudoTreeNode]:
        return [n for n in self.nodes if n.parent is None]

    def levels(self) -> List[List[PseudoTreeNode]]:
        """Nodes grouped by depth — the DPOP UTIL/VALUE wave schedule."""
        by_depth: Dict[int, List[PseudoTreeNode]] = {}
        for n in self.nodes:
            by_depth.setdefault(n.depth, []).append(n)
        return [by_depth[d] for d in sorted(by_depth)]


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[Constraint]] = None,
) -> ComputationPseudoTree:
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    var_names = [v.name for v in variables]
    by_name = {v.name: v for v in variables}

    # variable adjacency via shared constraints
    adjacency: Dict[str, Set[str]] = {n: set() for n in var_names}
    for c in constraints:
        scope = [v.name for v in c.dimensions if v.name in adjacency]
        for a in scope:
            for b in scope:
                if a != b:
                    adjacency[a].add(b)

    parent: Dict[str, Optional[str]] = {}
    depth: Dict[str, int] = {}
    order: Dict[str, int] = {}  # DFS visit order (ancestor test)
    children: Dict[str, List[str]] = {n: [] for n in var_names}
    visited: Set[str] = set()
    counter = 0

    unvisited = set(var_names)
    while unvisited:
        # max-degree root heuristic, ties broken by name for determinism
        root = max(
            sorted(unvisited), key=lambda n: (len(adjacency[n]), n)
        )
        # iterative DFS
        stack: List[Tuple[str, Optional[str]]] = [(root, None)]
        while stack:
            node, par = stack.pop()
            if node in visited:
                continue
            visited.add(node)
            unvisited.discard(node)
            parent[node] = par
            depth[node] = 0 if par is None else depth[par] + 1
            order[node] = counter
            counter += 1
            if par is not None:
                children[par].append(node)
            # deterministic order: visit higher-degree neighbors first
            neighs = sorted(
                (n for n in adjacency[node] if n not in visited),
                key=lambda n: (len(adjacency[n]), n),
            )
            for n in neighs:
                stack.append((n, node))

    # ancestor sets for pseudo-parent classification
    def ancestors(n: str) -> Set[str]:
        out = set()
        p = parent[n]
        while p is not None:
            out.add(p)
            p = parent[p]
        return out

    anc = {n: ancestors(n) for n in var_names}

    pseudo_parents: Dict[str, List[str]] = {n: [] for n in var_names}
    pseudo_children: Dict[str, List[str]] = {n: [] for n in var_names}
    for n in var_names:
        for m in sorted(adjacency[n], key=lambda x: order[x]):
            if m == parent[n] or n == parent.get(m):
                continue
            if m in anc[n]:
                pseudo_parents[n].append(m)
                if n not in pseudo_children[m]:
                    pseudo_children[m].append(n)

    # lowest-node rule: each constraint attached to the deepest (latest in DFS
    # order) variable of its scope (reference pseudotree.py:452)
    constraints_of: Dict[str, List[Constraint]] = {n: [] for n in var_names}
    for c in constraints:
        scope = [v.name for v in c.dimensions if v.name in order]
        if not scope:
            continue
        lowest = max(scope, key=lambda n: order[n])
        constraints_of[lowest].append(c)

    nodes = [
        PseudoTreeNode(
            by_name[n],
            parent[n],
            pseudo_parents[n],
            children[n],
            pseudo_children[n],
            constraints_of[n],
            depth[n],
        )
        for n in var_names
    ]
    return ComputationPseudoTree(nodes)
