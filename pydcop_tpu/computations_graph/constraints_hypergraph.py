"""Constraints hypergraph: one computation per variable, hyperedges = constraints.

Role parity with
/root/reference/pydcop/computations_graph/constraints_hypergraph.py
(VariableComputationNode:49, ConstraintLink:113,
ComputationConstraintsHyperGraph:149, build_computation_graph:176).  Used by
dsa/adsa/mgm/mgm2/dba/gdba/mixeddsa/dsatuto.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link

__all__ = [
    "VariableComputationNode",
    "ConstraintLink",
    "ComputationConstraintsHyperGraph",
    "build_computation_graph",
]


class ConstraintLink(Link):
    """Hyperedge over the variables of one constraint."""

    def __init__(self, constraint_name: str, nodes: Iterable[str]) -> None:
        super().__init__(nodes, "constraint_link")
        self.constraint_name = constraint_name

    def __eq__(self, other):
        return (
            isinstance(other, ConstraintLink)
            and other.constraint_name == self.constraint_name
            and other.nodes == self.nodes
        )

    def __hash__(self):
        return hash((self.constraint_name, self.nodes))

    def __repr__(self):
        return f"ConstraintLink({self.constraint_name}, {self.nodes})"


class VariableComputationNode(ComputationNode):
    def __init__(
        self, variable: Variable, constraints: Iterable[Constraint]
    ) -> None:
        self.variable = variable
        self.constraints = list(constraints)
        links = [
            ConstraintLink(c.name, [v.name for v in c.dimensions])
            for c in self.constraints
        ]
        super().__init__(variable.name, "VariableComputation", links)

    def _simple_repr(self):
        from ..utils.simple_repr import simple_repr

        return {
            "__qualname__": type(self).__qualname__,
            "__module__": type(self).__module__,
            "variable": simple_repr(self.variable),
            "constraints": [simple_repr(c) for c in self.constraints],
        }

    @classmethod
    def _from_repr(cls, variable, constraints):
        from ..utils.simple_repr import from_repr

        return cls(
            from_repr(variable), [from_repr(c) for c in constraints]
        )


class ComputationConstraintsHyperGraph(ComputationGraph):
    graph_type = "constraints_hypergraph"

    def density(self) -> float:
        # same definition as the reference (:166): edge endpoints over n^2
        n = self.node_count()
        if n == 0:
            return 0.0
        ends = sum(len(l.nodes) for l in self.links)
        return ends / (n * n)


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[Constraint]] = None,
) -> ComputationConstraintsHyperGraph:
    """One node per variable; each constraint links all its variables.

    Unary constraints are kept (they influence the local cost) but create no
    inter-node link.
    """
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    cons_of = {v.name: [] for v in variables}
    for c in constraints:
        for v in c.dimensions:
            if v.name in cons_of:
                cons_of[v.name].append(c)

    graph = ComputationConstraintsHyperGraph()
    for v in variables:
        graph.add_node(VariableComputationNode(v, cons_of[v.name]))
    return graph
