"""Factor graph: one computation per variable AND per constraint.

Role parity with /root/reference/pydcop/computations_graph/factor_graph.py
(FactorComputationNode:45, VariableComputationNode:104,
ComputationsFactorGraph:210, build_computation_graph:245).  Used by
maxsum/amaxsum (GRAPH_TYPE="factor_graph").
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link

__all__ = [
    "VariableComputationNode",
    "FactorComputationNode",
    "FactorGraphLink",
    "ComputationsFactorGraph",
    "build_computation_graph",
]


class FactorGraphLink(Link):
    def __init__(self, variable_node: str, factor_node: str) -> None:
        super().__init__((variable_node, factor_node), "var_factor")


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable, factor_names: Iterable[str]) -> None:
        links = [FactorGraphLink(variable.name, f) for f in factor_names]
        super().__init__(variable.name, "VariableComputation", links)
        self.variable = variable

    def _simple_repr(self):
        from ..utils.simple_repr import simple_repr

        return {
            "__qualname__": type(self).__qualname__,
            "__module__": type(self).__module__,
            "variable": simple_repr(self.variable),
            "factor_names": [
                n for l in self.links for n in l.nodes if n != self.name
            ],
        }

    @classmethod
    def _from_repr(cls, variable, factor_names):
        from ..utils.simple_repr import from_repr

        return cls(from_repr(variable), factor_names)


class FactorComputationNode(ComputationNode):
    def __init__(self, factor: Constraint) -> None:
        links = [FactorGraphLink(v.name, factor.name) for v in factor.dimensions]
        super().__init__(factor.name, "FactorComputation", links)
        self.factor = factor

    @property
    def variables(self) -> List[Variable]:
        return self.factor.dimensions

    def _simple_repr(self):
        from ..utils.simple_repr import simple_repr

        return {
            "__qualname__": type(self).__qualname__,
            "__module__": type(self).__module__,
            "factor": simple_repr(self.factor),
        }

    @classmethod
    def _from_repr(cls, factor):
        from ..utils.simple_repr import from_repr

        return cls(from_repr(factor))


class ComputationsFactorGraph(ComputationGraph):
    graph_type = "factor_graph"

    @property
    def variable_nodes(self) -> List[VariableComputationNode]:
        return [n for n in self.nodes if isinstance(n, VariableComputationNode)]

    @property
    def factor_nodes(self) -> List[FactorComputationNode]:
        return [n for n in self.nodes if isinstance(n, FactorComputationNode)]

    def density(self) -> float:
        # bipartite density: edges / (vars * factors)
        nv, nf = len(self.variable_nodes), len(self.factor_nodes)
        if not nv or not nf:
            return 0.0
        return self.link_count() / (nv * nf)


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[Constraint]] = None,
) -> ComputationsFactorGraph:
    """Build the bipartite variable/factor graph for a DCOP (reference
    factor_graph.py:245).  Unary variable costs stay attached to the variable
    (they do not become factors).

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> c = constraint_from_str('c1', 'x + y', [x, y])
    >>> g = build_computation_graph(variables=[x, y], constraints=[c])
    >>> sorted(n.name for n in g.nodes)
    ['c1', 'x', 'y']
    >>> sorted(g.neighbors('c1'))
    ['x', 'y']
    """
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    factors_of = {v.name: [] for v in variables}
    for c in constraints:
        for v in c.dimensions:
            if v.name in factors_of:
                factors_of[v.name].append(c.name)

    graph = ComputationsFactorGraph()
    for v in variables:
        graph.add_node(VariableComputationNode(v, factors_of[v.name]))
    for c in constraints:
        graph.add_node(FactorComputationNode(c))
    return graph
