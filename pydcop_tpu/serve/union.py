"""Fleet fusion: K tenant problems as ONE block-diagonal union solve.

The vmap engine (serve/batch.py) keeps every tenant's PRNG stream
bit-identical to its solo solve — but on a serial CPU backend a vmapped
program costs ~K x one instance (XLA:CPU executes the batch axis
serially), so batching only amortizes per-solve dispatch overhead.  This
module trades seed-reproducibility for raw throughput: the K compiled
problems are concatenated into ONE disjoint-union ``CompiledDCOP``
(variables, edges, constraints and tables block-shifted), and the union
solves through the ordinary sequential fused path — every kernel runs in
its efficient unbatched form at K x the size, which is exactly the
regime the solver already excels in (the 1M-variable configs).

Semantics: the union IS a legitimate instance of the same algorithm —
each tenant's block evolves under its own local costs with iid
per-variable randomness of the same distribution as a solo solve; only
the seed mapping differs (one fleet key instead of per-tenant keys), so
per-tenant trajectories are not reproducible against solo runs.  Tenants
needing bit-exact seed reproducibility use the vmap mode
(``solve_batched(..., mode="vmap")``, the default).  Per-tenant results
are exact: values are sliced per block and costed through EACH tenant's
own compiled problem on host; anytime-best is the better of the final
and union-best slices per tenant.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Tuple

import numpy as np

from ..compile.core import ArityBucket, CompiledDCOP

__all__ = ["union_compiled", "fleet_seed"]


def fleet_seed(seeds: List[int]) -> int:
    """One deterministic fleet seed from the tenants' seeds (crc32 of the
    ordered tuple — stable across processes, unlike hash())."""
    return zlib.crc32(
        ",".join(str(int(s)) for s in seeds).encode()
    ) & 0x7FFFFFFF


def union_compiled(
    parts: List[CompiledDCOP],
) -> Tuple[CompiledDCOP, List[Tuple[int, int]]]:
    """Disjoint union of K compiled problems (block-diagonal): returns
    the union ``CompiledDCOP`` plus each tenant's ``(lo, hi)`` variable
    block.  All parts must share max_domain, float dtype and objective;
    every index array is shifted by its block's offsets, so the union is
    exactly the compiled form of the disjoint graph union (edge order
    stays var-sorted because block i's variable ids all precede block
    i+1's)."""
    if not parts:
        raise ValueError("union of zero problems")
    d0 = parts[0]
    for c in parts[1:]:
        if (
            c.max_domain != d0.max_domain
            or np.dtype(c.float_dtype) != np.dtype(d0.float_dtype)
            or c.objective != d0.objective
        ):
            raise ValueError(
                "fleet fusion needs equal max_domain/dtype/objective "
                "across tenants"
            )
    blocks: List[Tuple[int, int]] = []
    v_off = e_off = c_off = 0
    var_names: List[str] = []
    domains = []
    con_names: List[str] = []
    by_arity: Dict[int, Dict[str, list]] = {}
    dsz, vmask, unary, evar, econ, vdeg = [], [], [], [], [], []
    constant = 0.0
    for i, c in enumerate(parts):
        blocks.append((v_off, v_off + c.n_vars))
        var_names.extend(f"u{i}.{n}" for n in c.var_names)
        domains.extend(c.domains)
        con_names.extend(f"u{i}.{n}" for n in c.con_names)
        dsz.append(np.asarray(c.domain_size))
        vmask.append(np.asarray(c.valid_mask))
        unary.append(np.asarray(c.unary, dtype=d0.float_dtype))
        vdeg.append(np.asarray(c.var_degree))
        if c.n_edges:
            evar.append(np.asarray(c.edge_var) + v_off)
            econ.append(np.asarray(c.edge_con) + c_off)
        for b in c.buckets:
            acc = by_arity.setdefault(
                b.arity,
                {"tables": [], "var_slots": [], "edge_ids": [],
                 "con_ids": []},
            )
            acc["tables"].append(np.asarray(b.tables, dtype=d0.float_dtype))
            acc["var_slots"].append(np.asarray(b.var_slots) + v_off)
            acc["edge_ids"].append(np.asarray(b.edge_ids) + e_off)
            acc["con_ids"].append(np.asarray(b.con_ids) + c_off)
        constant += float(c.constant_cost)
        v_off += c.n_vars
        e_off += c.n_edges
        c_off += c.n_constraints
    buckets = [
        ArityBucket(
            arity=a,
            tables=np.concatenate(acc["tables"]),
            var_slots=np.concatenate(acc["var_slots"]).astype(np.int32),
            edge_ids=np.concatenate(acc["edge_ids"]).astype(np.int32),
            con_ids=np.concatenate(acc["con_ids"]).astype(np.int32),
        )
        for a, acc in sorted(by_arity.items())
    ]
    union = CompiledDCOP(
        dcop=None,
        objective=d0.objective,
        var_names=var_names,
        var_index={n: i for i, n in enumerate(var_names)},
        domains=domains,
        n_vars=v_off,
        max_domain=d0.max_domain,
        domain_size=np.concatenate(dsz).astype(np.int32),
        valid_mask=np.concatenate(vmask),
        unary=np.concatenate(unary),
        constant_cost=constant,
        buckets=buckets,
        n_edges=e_off,
        edge_var=(
            np.concatenate(evar).astype(np.int32)
            if evar else np.zeros(0, dtype=np.int32)
        ),
        edge_con=(
            np.concatenate(econ).astype(np.int32)
            if econ else np.zeros(0, dtype=np.int32)
        ),
        var_degree=np.concatenate(vdeg).astype(np.int32),
        con_names=con_names,
        float_dtype=d0.float_dtype,
    )
    return union, blocks
