"""The serving front-end: async request queue + micro-batching window.

``ServeServer`` is what ``pydcop_tpu serve`` runs: tenants submit solves
(programmatically or over POST /solve on the shared metrics port), a
single worker thread collects requests inside a micro-batching window
(``window_ms``), groups them by shape bucket and dispatches each group as
ONE vmapped device program (serve/batch.py).  Results, per-tenant
anytime-cost and graftpulse health rows stream over the existing
``/status`` + ``/metrics`` surface (infrastructure/ui.py), and shutdown
drains the queue — zero dead letters unless a chaos schedule killed a
tenant on purpose.

graftchaos composition: a ``FaultSchedule``'s timed kills match tenant
ids (fnmatch, like agent kills).  A tenant killed mid-batch has its
result DROPPED and dead-letter accounted — the co-batched tenants'
results are untouched, because the batch math never depended on which
tenants survive the readback.  ``telemetry_off()`` mid-flight only stops
the streams; the serve loop re-checks the singletons per dispatch, so
solving continues undisturbed.
"""

from __future__ import annotations

import fnmatch
import itertools
import logging
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry.memplane import (
    MemoryBudgetExceeded,
    memguard,
    memory_status,
)
from ..telemetry.metrics import metrics_registry, percentile as _percentile
from ..telemetry.pulse import analyze as analyze_pulse
from ..telemetry.tracing import tracer
from .batch import SolveRequest, TenantResult, solve_batched

__all__ = ["ServeServer"]

logger = logging.getLogger("pydcop_tpu.serve.server")

#: tenant lifecycle states (docs/serving.md)
TENANT_STATES = ("queued", "running", "done", "failed", "killed")

#: request lifecycle phases (graftslo): what the per-bucket
#: ``serve.phase_seconds`` histogram decomposes a request latency into
PHASES = ("queue", "assemble", "dispatch", "solve", "readback")

#: cap on the /status tenants block: the newest rows win (a long-lived
#: server must not grow its status document without bound)
STATUS_TENANTS = 256

#: retention cap on TERMINAL tenant records (done/failed/killed): beyond
#: it the oldest terminal records — full assignments included — are
#: evicted and GET /result answers 'unknown' for them.  Queued/running
#: tenants are never evicted.  This bounds the server's memory, not just
#: its status document.
TENANT_RETAIN = 4096

#: queue-latency samples kept for the p50/p99 surface (matches the
#: status read window; older samples carry no extra information)
LATENCY_SAMPLES = 2048

_m_queue_seconds = metrics_registry.histogram(
    "serve.queue_seconds",
    "tenant queue latency (submit to batch dispatch start)",
)
_m_dead_letters = metrics_registry.counter(
    "serve.dead_letters",
    "tenant results dropped (chaos kills, failed solves)",
)
_m_tenants = metrics_registry.gauge(
    "serve.tenants", "tenants known to the serve loop, by state"
)
_m_fleet_ckpt = metrics_registry.counter(
    "serve.fleet_checkpoints",
    "fleet checkpoints written by graceful drains (graftdur)",
)
# graftslo: phase-decomposed latency (per shape bucket, exemplar-linked
# to request trace ids) + the saturation gauges an SLO investigation
# starts from (queue watermarks, batch occupancy, executable-cache
# pressure)
_m_request_seconds = metrics_registry.histogram(
    "serve.request_seconds",
    "end-to-end request latency (submit to result-ready)",
)
_m_phase_seconds = metrics_registry.histogram(
    "serve.phase_seconds",
    "request latency per lifecycle phase and shape bucket",
)
_m_queue_depth = metrics_registry.gauge(
    "serve.queue_depth", "tenants waiting in the micro-batching queue"
)
_m_queue_hwm = metrics_registry.gauge(
    "serve.queue_depth_watermark",
    "high-water mark of the micro-batching queue this run",
)
_m_occupancy = metrics_registry.gauge(
    "serve.batch_occupancy_pct",
    "real (non-pad) fraction of the last dispatched batch, percent",
)
_m_bucket_census = metrics_registry.gauge(
    "serve.bucket_cache_size",
    "distinct shape buckets dispatched so far (executable-cache pressure)",
)
_m_chaos_delays = metrics_registry.counter(
    "serve.chaos_delays",
    "tenants held back by a chaos delay rule before dispatch",
)


def _bucket_str(key: Any) -> str:
    """Compact bucket label shared by /status rows, phase-metric labels
    and trace span args (``dsa/v16e24d4n128``; fused groups are already
    strings)."""
    if isinstance(key, str):
        return key
    return (
        f"{key.algo}/v{key.dims.n_vars}e{key.dims.n_edges}"
        f"d{key.dims.max_domain}n{key.n_pad}"
    )


class ServeServer:
    """Micro-batching solve server (one worker thread, one device)."""

    def __init__(
        self,
        port: Optional[int] = None,
        window_ms: float = 25.0,
        max_batch: int = 32,
        fault_schedule: Any = None,
        host: str = "127.0.0.1",
        mode: str = "vmap",
        checkpoint_dir: Optional[str] = None,
        slo: Any = None,
        peers: Optional[Sequence[str]] = None,
    ) -> None:
        if mode not in ("vmap", "fused"):
            raise ValueError(f"unknown serve batch mode {mode!r}")
        self.window_s = max(0.0, window_ms) / 1e3
        #: graftha: fellow workers' base URLs, handed to rejected clients
        #: so they can fail over without guessing (``--peer`` on the
        #: verb; sibling fleet manifests fill in the rest — peers())
        self._peers = [str(p).rstrip("/") for p in (peers or []) if p]
        self.max_batch = max(1, int(max_batch))
        self.fault_schedule = fault_schedule
        #: graftslo: an ``SloEngine`` classifying every terminal request
        #: against its objectives; mounts ``/slo``, feeds the ``/status``
        #: slo block, and its burn-rate evaluator runs for the server's
        #: lifetime (needs ``metrics_registry.enabled`` — the serve verb
        #: turns it on)
        self.slo = slo
        #: graftdur: a graceful drain writes a fleet checkpoint here —
        #: the tenant census with terminal results, so a restarted
        #: server (or an operator) can account for every tenant the
        #: dying fleet owned (docs/durability.md)
        self.checkpoint_dir = checkpoint_dir
        self.fleet_checkpoint_path: Optional[str] = None
        #: "vmap" = bit-exact per-tenant trajectories + shared warm
        #: executables; "fused" = block-diagonal fleet fusion for maximal
        #: throughput (docs/serving.md)
        self.mode = mode
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._tenants: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._state = "serving"
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._ids = itertools.count()
        self._t0 = time.monotonic()
        self._kills_fired: set = set()
        self._latencies: List[float] = []
        self._queue_hwm = 0
        self._buckets_seen: set = set()
        self._batch_seq = itertools.count(1)
        self.batches = 0
        self.solves = 0
        self.dead_letters = 0
        self.http = None
        self._host = host
        if self.slo is not None:
            self.slo.start()
        self._worker = threading.Thread(
            target=self._run, name="serve-worker", daemon=True
        )
        self._worker.start()
        if port is not None:
            from ..infrastructure.ui import MetricsHttpServer

            routes = {
                ("POST", "/solve"): self._http_solve,
                ("GET", "/result"): self._http_result,
                ("GET", "/healthz"): self._http_healthz,
                ("POST", "/window"): self._http_window,
                ("POST", "/shutdown"): self._http_shutdown,
            }
            if self.slo is not None:
                routes[("GET", "/slo")] = self._http_slo
            self.http = MetricsHttpServer(
                port=port,
                host=host,
                status_cb=self.status,
                routes=routes,
            )

    # -- submission ----------------------------------------------------

    def submit(self, req: SolveRequest, trace: Optional[str] = None) -> str:
        """Enqueue one tenant solve; returns the tenant id (the request's,
        or a generated ``t<n>``).  Raises while draining — a drain is a
        promise that nothing new enters the queue.  The queue put happens
        UNDER the same lock as the state check: put-after-release would
        let a concurrent drain observe an empty queue, declare a clean
        drain, and strand this tenant 'queued' forever.

        ``trace`` is the graftslo request trace id: generated fresh when
        absent, echoed in the ``/solve`` response and ``/result``, and
        ACCEPTED on resubmit — a retried request passing its original
        trace id keeps both attempts on one flow-linked timeline."""
        rid = str(trace) if trace else os.urandom(8).hex()
        now = time.monotonic()
        # graftmem serve admission (docs/serving.md): a tenant whose
        # BUCKET-PADDED solve cannot fit the device budget is refused at
        # the door with the breach named (MemoryBudgetExceeded is a
        # RuntimeError, so the HTTP path's structured-503 handler carries
        # it to the client with its ``mem`` block) — instead of entering
        # a batch that XLA will kill with RESOURCE_EXHAUSTED, taking its
        # co-batched tenants down with it.  Outside the lock: the model
        # is pure host math.
        if memguard.enabled:
            memguard.check(
                req.compiled, req.algo, req.params,
                context="serve", n_cycles=req.n_cycles,
                serve_bucket=True,
            )
        with self._lock:
            if self._state != "serving":
                raise RuntimeError(
                    f"server is {self._state}: not accepting tenants"
                )
            tenant = req.tenant or f"t{next(self._ids)}"
            if tenant in self._tenants:
                raise ValueError(f"tenant id {tenant!r} already known")
            req = req._replace(tenant=tenant)
            hold_s = self._chaos_hold_s(tenant)
            rec = {
                "status": "queued",
                "request": req,
                "algo": req.algo,
                "n_cycles": req.n_cycles,
                "submitted_s": now,
                # perf_counter twin of submitted_s: span timestamps must
                # live in the tracer's clock domain
                "submitted_pc": time.perf_counter(),
                "trace": rid,
            }
            if hold_s:
                rec["hold_until_s"] = now + hold_s
            if tracer.enabled:
                rec["flow_id"] = tracer.new_flow_id()
            self._tenants[tenant] = rec
            self._queue.put(tenant)
            depth = self._queue.qsize()
            if depth > self._queue_hwm:
                self._queue_hwm = depth
            hwm = self._queue_hwm
        if hold_s:
            _m_chaos_delays.inc()
            logger.info(
                "chaos delay: tenant %s held %.3fs before dispatch",
                tenant, hold_s,
            )
        if metrics_registry.enabled:
            _m_queue_depth.set(depth)
            _m_queue_hwm.set(hwm)
        if tracer.enabled:
            # the submit anchor of the request's flow: Perfetto draws the
            # arrow from here through the batch to result-ready
            tracer.flow_point(
                "s", "serve.submit", rec["flow_id"], cat="serve",
                flow_name="serve.request", tenant=tenant, trace=rid,
            )
        return tenant

    def _chaos_hold_s(self, tenant: str) -> float:
        """Seconds a chaos ``delay`` rule holds this tenant before it may
        enter a batch (0 = none).  Deterministic: the probabilistic rules
        decide by the schedule's keyed hash, never a shared PRNG — the
        same schedule delays the same tenants every run, which is what
        lets ``make slo-smoke`` assert bit-reproducible burn alerts."""
        sched = self.fault_schedule
        if sched is None or not getattr(sched, "rules", None):
            return 0.0
        from ..chaos.schedule import unit_draw

        total = 0.0
        for i, rule in enumerate(sched.rules):
            if rule.action != "delay":
                continue
            if not rule.matches("serve", tenant, "solve"):
                continue
            if rule.p < 1.0 and unit_draw(
                sched.seed, f"serve.delay|{i}|{tenant}", 0
            ) >= rule.p:
                continue
            total += rule.seconds
        return total

    def result(self, tenant: str) -> Dict[str, Any]:
        """One tenant's public record (what GET /result/<id> answers)."""
        with self._lock:
            rec = self._tenants.get(tenant)
            if rec is None:
                return {"tenant": tenant, "status": "unknown"}
            out = {
                "tenant": tenant,
                "status": rec["status"],
                "algo": rec["algo"],
            }
            for k in (
                "cost", "violations", "cycles", "best_cost",
                "cycles_to_best", "assignment", "error", "bucket",
                "batch_size", "queue_ms", "pulse", "trace", "phases",
                "batch_seq", "cold_compile",
            ):
                if k in rec:
                    out[k] = rec[k]
            return out

    def wait(self, tenant: str, timeout: float = 60.0) -> Dict[str, Any]:
        """Poll until the tenant reaches a terminal state."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rec = self.result(tenant)
            if rec["status"] in ("done", "failed", "killed", "unknown"):
                return rec
            time.sleep(0.005)
        return self.result(tenant)

    # -- status surface ------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            lat = sorted(self._latencies[-LATENCY_SAMPLES:])
            tenants = dict(
                list(self._tenants.items())[-STATUS_TENANTS:]
            )
            rows = {}
            for tid, rec in tenants.items():
                row = {
                    "status": rec["status"],
                    "algo": rec["algo"],
                }
                for k in (
                    "cost", "best_cost", "cycles", "cycles_to_best",
                    "bucket", "batch_size", "queue_ms", "error", "trace",
                ):
                    if k in rec:
                        row[k] = rec[k]
                if "pulse" in rec:
                    row["pulse"] = rec["pulse"]
                rows[tid] = row
            counts: Dict[str, int] = {}
            for rec in self._tenants.values():
                counts[rec["status"]] = counts.get(rec["status"], 0) + 1
            out = {
                "status": "serve",
                "mode": self.mode,
                "state": self._state,
                "queue_depth": self._queue.qsize(),
                "queue_depth_watermark": self._queue_hwm,
                "buckets": len(self._buckets_seen),
                "tenants": rows,
                "tenant_counts": counts,
                "batches": self.batches,
                "solves": self.solves,
                "dead_letters": self.dead_letters,
                "queue_ms": {
                    "p50": _percentile(lat, 0.50),
                    "p99": _percentile(lat, 0.99),
                },
                # graftmem: last live memory sample + guard config (the
                # fleet collector lifts the per-worker columns from here)
                "memory": memory_status(),
            }
        if self.slo is not None:
            # outside the server lock: the block reads the engine's own
            # state under the engine's lock
            out["slo"] = self.slo.status_block()
        return out

    def peers(self) -> List[str]:
        """Fellow workers' base URLs: the configured ``--peer`` list plus
        whatever sibling fleet manifests record under the shared state
        directory's parent (the graftdur service-registry idiom —
        ``fleet --manifest`` reads the same files).  Own endpoint
        excluded; best-effort, never raises."""
        own = (
            f"http://{self._host}:{self.http.port}"
            if self.http is not None
            else None
        )
        out: List[str] = []
        seen: set = set()
        for url in self._peers:
            if url != own and url not in seen:
                seen.add(url)
                out.append(url)
        if self.checkpoint_dir:
            import json as _json

            parent = os.path.dirname(
                os.path.abspath(self.checkpoint_dir)
            )
            try:
                entries = sorted(os.listdir(parent))
            except OSError:
                entries = []
            for entry in entries:
                path = os.path.join(parent, entry, "fleet-manifest.json")
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        doc = _json.load(f)
                except (OSError, ValueError):
                    continue
                url = str(doc.get("endpoint") or "").rstrip("/")
                if url and url != own and url not in seen:
                    seen.add(url)
                    out.append(url)
        return out

    # -- lifecycle -----------------------------------------------------

    def drain(self, timeout: float = 120.0) -> bool:
        """Graceful shutdown: stop accepting, finish every queued tenant,
        stop the worker, and (with ``checkpoint_dir``) write the fleet
        checkpoint.  True when the queue fully drained in time."""
        with self._lock:
            self._state = "draining"
        self._stop.set()
        ok = self._drained.wait(timeout)
        with self._lock:
            self._state = "drained" if ok else "drain-timeout"
        if self.slo is not None:
            # final evaluator tick AFTER the queue drained: requests that
            # finished between the last periodic tick and now still reach
            # the burn math before the engine stops
            self.slo.stop(final_tick=True)
        if self.checkpoint_dir:
            try:
                self.fleet_checkpoint_path = self._write_fleet_checkpoint()
            except OSError:
                logger.exception("fleet checkpoint write failed")
        return ok

    def _write_fleet_checkpoint(self) -> str:
        """The drain's durable record: one atomic JSON manifest with the
        full tenant census — terminal tenants keep their results
        (cost/assignment/cycles), non-terminal ones are listed so nothing
        a dying fleet owned goes unaccounted.  Same manifest format
        family as the solver checkpoints (``kind: fleet``); array-free,
        so it reads anywhere."""
        import os
        import time as _time

        from ..durability.manager import MANIFEST_FORMAT
        from ..utils.checkpoint import atomic_write_json

        with self._lock:
            tenants = {}
            for tid, rec in self._tenants.items():
                row = {"status": rec["status"], "algo": rec["algo"]}
                for k in (
                    "cost", "violations", "cycles", "best_cost",
                    "cycles_to_best", "assignment", "error", "bucket",
                    "batch_size", "n_cycles",
                ):
                    if k in rec:
                        row[k] = rec[k]
                tenants[tid] = row
            manifest = {
                "format": MANIFEST_FORMAT,
                "kind": "fleet",
                "wrote_unix_s": _time.time(),
                # graftfleet: the worker's scrape endpoint, so a fleet
                # that checkpoints into a shared state directory is its
                # own service registry (telemetry/federate.py reads
                # manifests as collector targets)
                "endpoint": (
                    f"http://{self._host}:{self.http.port}"
                    if self.http is not None else None
                ),
                "worker": (
                    f"{self._host}:{self.http.port}"
                    if self.http is not None else None
                ),
                "state": self._state,
                "mode": self.mode,
                "batches": self.batches,
                "solves": self.solves,
                "dead_letters": self.dead_letters,
                "tenants": tenants,
            }
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        path = os.path.join(self.checkpoint_dir, "fleet-manifest.json")
        atomic_write_json(
            path, manifest, indent=2, sort_keys=True, default=str
        )
        if metrics_registry.enabled:
            _m_fleet_ckpt.inc()
        logger.info(
            "fleet checkpoint: %d tenant(s) -> %s", len(tenants), path
        )
        return path

    def shutdown(self, drain: bool = True, timeout: float = 120.0) -> bool:
        ok = self.drain(timeout) if drain else True
        if not drain:
            self._stop.set()
            if self.slo is not None:
                self.slo.stop(final_tick=True)
        if self.http is not None:
            self.http.shutdown()
        return ok

    def wait_drained(self, timeout: float = 120.0) -> bool:
        """Block until a drain (started here or via POST /shutdown)
        finished emptying the queue."""
        return self._drained.wait(timeout)

    # -- HTTP routes (mounted on the shared metrics port) --------------

    def _http_solve(self, path: str, body: bytes):
        import json

        from ..dcop.yamldcop import load_dcop
        from ..compile.core import compile_dcop

        spec = json.loads(body.decode("utf-8"))
        dcop = load_dcop(spec["dcop_yaml"])
        req = SolveRequest(
            tenant=spec.get("tenant") or "",
            compiled=compile_dcop(dcop),
            algo=spec.get("algo", "dsa"),
            params=spec.get("params") or {},
            n_cycles=int(spec.get("n_cycles", 100)),
            seed=int(spec.get("seed", 0)),
        )
        # a resubmit carrying its original trace id keeps both attempts
        # flow-linked on one timeline (graftslo); generating the fresh id
        # HERE (not reading it back via result()) keeps POST /solve to
        # one server-lock acquisition
        rid = str(spec.get("trace") or "") or os.urandom(8).hex()
        try:
            tenant = self.submit(req, trace=rid)
        except RuntimeError as e:
            # structured rejection: a draining worker tells the client
            # WHERE to go (the manifest's peer list) and WHEN to come
            # back — failover without guessing (docs/serving.md)
            with self._lock:
                state = self._state
            retry_after = 2
            doc = {
                "error": str(e),
                "state": state,
                "retry_after_s": retry_after,
                "peers": self.peers(),
            }
            if isinstance(e, MemoryBudgetExceeded):
                # graftmem refusal: the breach block (predicted vs
                # capacity, dominant component) rides the structured 503
                # so routers/clients can tell "won't EVER fit here" from
                # "busy right now" (docs/serving.md)
                doc["mem"] = e.breach
            return (
                503,
                doc,
                {"Retry-After": str(retry_after)},
            )
        return 200, {"tenant": tenant, "trace": rid}

    def _http_result(self, path: str, body: bytes):
        tenant = path.rsplit("/", 1)[-1]
        rec = self.result(tenant)
        return (404 if rec["status"] == "unknown" else 200), rec

    def _http_healthz(self, path: str, body: bytes):
        """Readiness, not liveness: 200 only while ACCEPTING tenants.
        A draining worker is healthy but must answer not-ready, so
        routers exclude it from placement while the queue empties —
        before this endpoint a drain looked identical to busy from
        outside.  (Dead is the transport error the caller already
        gets.)"""
        with self._lock:
            state = self._state
            queue_depth = self._queue.qsize()
        return (
            (200 if state == "serving" else 503),
            {"state": state, "queue_depth": queue_depth},
        )

    def _http_window(self, path: str, body: bytes):
        """Live micro-batch window retune (graftha: the router widens
        windows when the fleet idles, narrows them under load).  Clamped
        to [0, 10s]; takes effect on the next batch collection."""
        import json

        spec = json.loads(body.decode("utf-8")) if body else {}
        try:
            window_ms = float(spec["window_ms"])
        except (KeyError, TypeError, ValueError):
            return 400, {"error": "expected {'window_ms': <float>}"}
        window_ms = min(10_000.0, max(0.0, window_ms))
        with self._lock:
            self.window_s = window_ms / 1e3
        return 200, {"window_ms": window_ms}

    def _http_slo(self, path: str, body: bytes):
        return 200, self.slo.report()

    def _http_shutdown(self, path: str, body: bytes):
        # answer first, drain in the background: the HTTP reply must not
        # wait behind the queue
        threading.Thread(
            target=self.shutdown, kwargs={"drain": True}, daemon=True
        ).start()
        return 200, {"state": "draining"}

    # -- the worker loop -----------------------------------------------

    def _next_ready(self, timeout: float) -> str:
        """Pop the next dispatchable tenant.  A tenant held back by a
        chaos ``delay`` rule is re-queued until its release time — the
        hold applies to that tenant alone, so co-batched neighbors are
        never slowed by someone else's injected stall."""
        if self.fault_schedule is None:
            return self._queue.get(timeout=timeout)
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining < 0:
                raise queue.Empty
            tid = self._queue.get(timeout=max(0.0, remaining))
            with self._lock:
                rec = self._tenants.get(tid)
                hold = rec.get("hold_until_s", 0.0) if rec else 0.0
            now = time.monotonic()
            if hold <= now:
                return tid
            self._queue.put(tid)
            time.sleep(min(0.005, hold - now))

    def _run(self) -> None:
        while True:
            try:
                first = self._next_ready(0.05)
            except queue.Empty:
                if self._stop.is_set() and not self._queue.qsize():
                    break
                continue
            batch = [first]
            # one torn read costs at most one oddly-sized window; the
            # retune endpoint's next value is picked up a batch later
            deadline = time.monotonic() + self.window_s  # graftlint: disable=lock-unguarded-read (atomic float read; stale window tolerated for one batch)
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0 and not self._stop.is_set():
                    break
                try:
                    batch.append(self._next_ready(max(0.0, remaining)))
                except queue.Empty:
                    break
            try:
                self._dispatch(batch)
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("serve batch dispatch failed")
                now = time.monotonic()
                finals = []
                with self._lock:
                    for tid in batch:
                        rec = self._tenants.get(tid)
                        if rec and rec["status"] in ("queued", "running"):
                            rec["status"] = "failed"
                            rec["error"] = "dispatch error (see log)"
                            rec["finished_s"] = now
                            self.dead_letters += 1
                            _m_dead_letters.inc()
                            finals.append(self._final_row(tid, rec))
                self._finish_requests(finals)
        self._drained.set()

    def _fired_kills(self) -> List[str]:
        """Patterns of chaos kills due by now, each fired exactly once."""
        if self.fault_schedule is None:
            return []
        elapsed = time.monotonic() - self._t0
        out = []
        for ev in self.fault_schedule.kills:
            key = (ev.agent, ev.at)
            if ev.at <= elapsed and key not in self._kills_fired:
                self._kills_fired.add(key)
                out.append(ev.agent)
        return out

    def _dispatch(self, tenant_ids: List[str]) -> None:
        now = time.monotonic()
        # request-lifecycle instrumentation (graftslo) is flag-gated at
        # the top: with telemetry off and no SLO engine the dispatch path
        # costs exactly these boolean checks
        observing = (
            tracer.enabled
            or metrics_registry.enabled
            or self.slo is not None
        )
        with self._lock:
            reqs = []
            for tid in tenant_ids:
                rec = self._tenants[tid]
                rec["status"] = "running"
                rec["started_s"] = now
                q_ms = (now - rec["submitted_s"]) * 1e3
                rec["queue_ms"] = round(q_ms, 3)
                self._latencies.append(q_ms)
                if len(self._latencies) > 2 * LATENCY_SAMPLES:
                    del self._latencies[:-LATENCY_SAMPLES]
                if metrics_registry.enabled:
                    _m_queue_seconds.observe(q_ms / 1e3)
                reqs.append(rec["request"])
        # chaos kills due before/while this batch runs: the victims'
        # solves still execute (the batch is one program), their RESULTS
        # are dropped — mid-batch death must degrade only the dead tenant
        kill_patterns = self._fired_kills()
        results = solve_batched(
            reqs, max_batch=self.max_batch, mode=self.mode,
            observer=self._on_batch_event if observing else None,
        )
        kill_patterns += self._fired_kills()  # due while the batch ran
        finals: List[Dict[str, Any]] = []
        with self._lock:
            for tid in tenant_ids:
                rec = self._tenants[tid]
                tr: Optional[TenantResult] = results.get(tid)
                killed = any(
                    fnmatch.fnmatchcase(tid, pat) for pat in kill_patterns
                )
                rec["finished_s"] = time.monotonic()
                if observing:
                    rec["finished_pc"] = time.perf_counter()
                # terminal records never re-dispatch: drop the request
                # (it pins the compiled problem + its cached device
                # arrays — the big share of a tenant's memory)
                rec.pop("request", None)
                if killed:
                    rec["status"] = "killed"
                    rec["error"] = "killed by chaos schedule"
                    self.dead_letters += 1
                    _m_dead_letters.inc()
                elif tr is None or tr.result is None:
                    rec["status"] = "failed"
                    rec["error"] = (tr.extras if tr else {}).get(
                        "error", "no result"
                    )
                    self.dead_letters += 1
                    _m_dead_letters.inc()
                else:
                    self._record_done(rec, tr)
                    self.solves += 1
                if observing:
                    finals.append(self._final_row(tid, rec))
            self.batches += 1
            self._evict_terminal()
            if metrics_registry.enabled:
                for state in TENANT_STATES:
                    _m_tenants.set(
                        sum(
                            1 for r in self._tenants.values()
                            if r["status"] == state
                        ),
                        state=state,
                    )
        self._finish_requests(finals)

    # -- request-lifecycle instrumentation (graftslo) ------------------

    def _on_batch_event(self, ev: Dict[str, Any]) -> None:
        """One dispatched group's phase boundaries (serve/batch.py
        observer): attribute them to every tenant that rode the batch —
        phase histograms (exemplar-linked to the tenants' trace ids),
        saturation gauges, the batch/phase span tree, and the flow point
        tying each tenant's submit to the batch it rode."""
        bucket = _bucket_str(ev["bucket"])
        seq = next(self._batch_seq)
        occupancy = 100.0 * ev["k_real"] / max(1, ev["k_pad"])
        t_solved = ev["t_solved"] or ev["t_dispatched"]
        segments = (
            ("assemble", ev["t_start"], ev["t_assembled"]),
            ("dispatch", ev["t_assembled"], ev["t_dispatched"]),
            ("solve", ev["t_dispatched"], t_solved),
            ("readback", t_solved, ev["t_done"]),
        )
        rows = []
        with self._lock:
            self._buckets_seen.add(bucket)
            n_buckets = len(self._buckets_seen)
            for tid in ev["tenants"]:
                rec = self._tenants.get(tid)
                if rec is None:
                    continue
                sub_pc = rec.get("submitted_pc")
                phases = {
                    name: max(0.0, b - a) for name, a, b in segments
                }
                phases["queue"] = (
                    max(0.0, ev["t_start"] - sub_pc)
                    if sub_pc is not None else 0.0
                )
                rec["phases"] = {
                    k: round(v, 6) for k, v in phases.items()
                }
                rec["batch_seq"] = seq
                rec.setdefault("bucket", bucket)
                if ev["fresh_compiles"]:
                    # the stall is attributed to the tenants that paid it:
                    # whoever rode the batch that compiled
                    rec["cold_compile"] = True
                rows.append(
                    (tid, rec.get("trace"), rec.get("flow_id"), sub_pc,
                     phases)
                )
        if metrics_registry.enabled:
            _m_occupancy.set(occupancy)
            _m_bucket_census.set(n_buckets)
            _m_queue_depth.set(self._queue.qsize())
            for tid, trace, _flow, _sub, phases in rows:
                for name, v in phases.items():
                    _m_phase_seconds.observe(
                        v, exemplar_=trace, phase=name, bucket=bucket
                    )
        if tracer.enabled:
            tenants = list(ev["tenants"])
            tracer.complete(
                "serve.batch", ev["t_start"],
                ev["t_done"] - ev["t_start"], cat="serve",
                batch=seq, bucket=bucket, k_real=ev["k_real"],
                k_pad=ev["k_pad"], occupancy_pct=round(occupancy, 1),
                fresh_compiles=ev["fresh_compiles"], tenants=tenants,
            )
            for name, a, b in segments:
                tracer.complete(
                    f"serve.{name}", a, b - a, cat="serve", batch=seq,
                    bucket=bucket, tenants=tenants,
                )
            if ev["fresh_compiles"]:
                # the cold-compile stall as its own slice, naming who
                # paid: the executable was built inside this dispatch
                tracer.complete(
                    "serve.cold_compile", ev["t_assembled"],
                    ev["t_dispatched"] - ev["t_assembled"], cat="serve",
                    batch=seq, bucket=bucket,
                    fresh_compiles=ev["fresh_compiles"],
                    paid_by=tenants,
                )
            for tid, trace, flow_id, sub_pc, _phases in rows:
                if sub_pc is not None:
                    tracer.complete(
                        "serve.queued", sub_pc,
                        max(0.0, ev["t_start"] - sub_pc), cat="serve",
                        tenant=tid, trace=trace, batch=seq,
                        bucket=bucket,
                    )
                if flow_id is not None:
                    tracer.flow_point(
                        "t", "serve.batch.enter", flow_id, cat="serve",
                        flow_name="serve.request", tenant=tid,
                        trace=trace, batch=seq, bucket=bucket,
                    )

    def _final_row(self, tid: str, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Terminal-transition snapshot for :meth:`_finish_requests`
        (caller holds the lock; the emission happens outside it)."""
        return {
            "tenant": tid,
            "trace": rec.get("trace"),
            "flow_id": rec.get("flow_id"),
            "submitted_s": rec.get("submitted_s", 0.0),
            "finished_s": rec.get("finished_s", 0.0),
            "submitted_pc": rec.get("submitted_pc"),
            "finished_pc": rec.get("finished_pc"),
            "status": rec["status"],
            "bucket": rec.get("bucket"),
            "batch_seq": rec.get("batch_seq"),
            "cold_compile": rec.get("cold_compile", False),
            "phases": rec.get("phases"),
        }

    def _finish_requests(self, rows: List[Dict[str, Any]]) -> None:
        """Result-ready side of the request lifecycle: the end-to-end
        latency histogram (exemplar = trace id), the ``serve.request``
        root span closing the tenant's span tree, the flow finish, and
        the SLO classification."""
        for r in rows:
            latency = max(0.0, r["finished_s"] - r["submitted_s"])
            dead = r["status"] in ("failed", "killed")
            if metrics_registry.enabled:
                _m_request_seconds.observe(latency, exemplar_=r["trace"])
            if tracer.enabled:
                if r["submitted_pc"] is not None and r["finished_pc"]:
                    tracer.complete(
                        "serve.request", r["submitted_pc"],
                        max(0.0, r["finished_pc"] - r["submitted_pc"]),
                        cat="serve", tenant=r["tenant"], trace=r["trace"],
                        status=r["status"], bucket=r["bucket"],
                        batch=r["batch_seq"],
                        cold_compile=r["cold_compile"],
                    )
                tracer.instant(
                    "serve.result_ready", cat="serve",
                    tenant=r["tenant"], trace=r["trace"],
                    status=r["status"],
                )
                if r["flow_id"] is not None:
                    tracer.flow_point(
                        "f", "serve.result", r["flow_id"], cat="serve",
                        flow_name="serve.request", tenant=r["tenant"],
                        trace=r["trace"], status=r["status"],
                    )
            if self.slo is not None:
                self.slo.record_request(
                    r["tenant"], r["status"], latency,
                    dead_letter=dead, trace=r["trace"],
                    phases=r["phases"],
                )

    def _evict_terminal(self) -> None:
        """Drop the oldest TERMINAL tenant records past TENANT_RETAIN
        (caller holds the lock) — the memory bound of a long-lived
        server; live tenants are never evicted."""
        excess = len(self._tenants) - TENANT_RETAIN  # graftlint: disable=lock-unguarded-read (caller _dispatch holds self._lock)
        if excess <= 0:
            return
        for tid in [
            t for t, r in self._tenants.items()  # graftlint: disable=lock-unguarded-read (caller holds self._lock)
            if r["status"] in ("done", "failed", "killed")
        ][:excess]:
            del self._tenants[tid]  # graftlint: disable=lock-unguarded-write (caller holds self._lock)

    def _record_done(self, rec: Dict[str, Any], tr: TenantResult) -> None:
        rec["status"] = "done"
        rec["cost"] = tr.result.cost
        rec["violations"] = tr.result.violations
        rec["cycles"] = tr.result.cycles
        rec["assignment"] = tr.result.assignment
        rec["best_cost"] = tr.extras.get("best_cost")
        rec["cycles_to_best"] = tr.extras.get("cycles_to_best")
        if "bucket" in tr.extras:
            rec["bucket"] = _bucket_str(tr.extras["bucket"])
        if "batch_size" in tr.extras:
            rec["batch_size"] = tr.extras["batch_size"]
        pulse_blk = tr.extras.get("pulse")
        if pulse_blk is not None and pulse_blk.get("health") is not None:
            a = analyze_pulse(pulse_blk["health"])
            rec["pulse"] = {
                "diagnosis": a.get("diagnosis_full", a.get("diagnosis")),
                "churn": round(float(a.get("churn_now", 0.0) or 0.0), 4),
                "residual": float(a.get("residual_now", 0.0) or 0.0),
                "violations": int(a.get("violations", 0) or 0),
                "cycles": a.get("cycles", 0),
            }
