"""The serving front-end: async request queue + micro-batching window.

``ServeServer`` is what ``pydcop_tpu serve`` runs: tenants submit solves
(programmatically or over POST /solve on the shared metrics port), a
single worker thread collects requests inside a micro-batching window
(``window_ms``), groups them by shape bucket and dispatches each group as
ONE vmapped device program (serve/batch.py).  Results, per-tenant
anytime-cost and graftpulse health rows stream over the existing
``/status`` + ``/metrics`` surface (infrastructure/ui.py), and shutdown
drains the queue — zero dead letters unless a chaos schedule killed a
tenant on purpose.

graftchaos composition: a ``FaultSchedule``'s timed kills match tenant
ids (fnmatch, like agent kills).  A tenant killed mid-batch has its
result DROPPED and dead-letter accounted — the co-batched tenants'
results are untouched, because the batch math never depended on which
tenants survive the readback.  ``telemetry_off()`` mid-flight only stops
the streams; the serve loop re-checks the singletons per dispatch, so
solving continues undisturbed.
"""

from __future__ import annotations

import fnmatch
import itertools
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from ..telemetry.metrics import metrics_registry
from ..telemetry.pulse import analyze as analyze_pulse
from .batch import SolveRequest, TenantResult, solve_batched

__all__ = ["ServeServer"]

logger = logging.getLogger("pydcop_tpu.serve.server")

#: tenant lifecycle states (docs/serving.md)
TENANT_STATES = ("queued", "running", "done", "failed", "killed")

#: cap on the /status tenants block: the newest rows win (a long-lived
#: server must not grow its status document without bound)
STATUS_TENANTS = 256

#: retention cap on TERMINAL tenant records (done/failed/killed): beyond
#: it the oldest terminal records — full assignments included — are
#: evicted and GET /result answers 'unknown' for them.  Queued/running
#: tenants are never evicted.  This bounds the server's memory, not just
#: its status document.
TENANT_RETAIN = 4096

#: queue-latency samples kept for the p50/p99 surface (matches the
#: status read window; older samples carry no extra information)
LATENCY_SAMPLES = 2048

_m_queue_seconds = metrics_registry.histogram(
    "serve.queue_seconds",
    "tenant queue latency (submit to batch dispatch start)",
)
_m_dead_letters = metrics_registry.counter(
    "serve.dead_letters",
    "tenant results dropped (chaos kills, failed solves)",
)
_m_tenants = metrics_registry.gauge(
    "serve.tenants", "tenants known to the serve loop, by state"
)
_m_fleet_ckpt = metrics_registry.counter(
    "serve.fleet_checkpoints",
    "fleet checkpoints written by graceful drains (graftdur)",
)


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class ServeServer:
    """Micro-batching solve server (one worker thread, one device)."""

    def __init__(
        self,
        port: Optional[int] = None,
        window_ms: float = 25.0,
        max_batch: int = 32,
        fault_schedule: Any = None,
        host: str = "127.0.0.1",
        mode: str = "vmap",
        checkpoint_dir: Optional[str] = None,
    ) -> None:
        if mode not in ("vmap", "fused"):
            raise ValueError(f"unknown serve batch mode {mode!r}")
        self.window_s = max(0.0, window_ms) / 1e3
        self.max_batch = max(1, int(max_batch))
        self.fault_schedule = fault_schedule
        #: graftdur: a graceful drain writes a fleet checkpoint here —
        #: the tenant census with terminal results, so a restarted
        #: server (or an operator) can account for every tenant the
        #: dying fleet owned (docs/durability.md)
        self.checkpoint_dir = checkpoint_dir
        self.fleet_checkpoint_path: Optional[str] = None
        #: "vmap" = bit-exact per-tenant trajectories + shared warm
        #: executables; "fused" = block-diagonal fleet fusion for maximal
        #: throughput (docs/serving.md)
        self.mode = mode
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._tenants: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._state = "serving"
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._ids = itertools.count()
        self._t0 = time.monotonic()
        self._kills_fired: set = set()
        self._latencies: List[float] = []
        self.batches = 0
        self.solves = 0
        self.dead_letters = 0
        self.http = None
        self._worker = threading.Thread(
            target=self._run, name="serve-worker", daemon=True
        )
        self._worker.start()
        if port is not None:
            from ..infrastructure.ui import MetricsHttpServer

            self.http = MetricsHttpServer(
                port=port,
                host=host,
                status_cb=self.status,
                routes={
                    ("POST", "/solve"): self._http_solve,
                    ("GET", "/result"): self._http_result,
                    ("POST", "/shutdown"): self._http_shutdown,
                },
            )

    # -- submission ----------------------------------------------------

    def submit(self, req: SolveRequest) -> str:
        """Enqueue one tenant solve; returns the tenant id (the request's,
        or a generated ``t<n>``).  Raises while draining — a drain is a
        promise that nothing new enters the queue.  The queue put happens
        UNDER the same lock as the state check: put-after-release would
        let a concurrent drain observe an empty queue, declare a clean
        drain, and strand this tenant 'queued' forever."""
        with self._lock:
            if self._state != "serving":
                raise RuntimeError(
                    f"server is {self._state}: not accepting tenants"
                )
            tenant = req.tenant or f"t{next(self._ids)}"
            if tenant in self._tenants:
                raise ValueError(f"tenant id {tenant!r} already known")
            req = req._replace(tenant=tenant)
            self._tenants[tenant] = {
                "status": "queued",
                "request": req,
                "algo": req.algo,
                "n_cycles": req.n_cycles,
                "submitted_s": time.monotonic(),
            }
            self._queue.put(tenant)
        return tenant

    def result(self, tenant: str) -> Dict[str, Any]:
        """One tenant's public record (what GET /result/<id> answers)."""
        with self._lock:
            rec = self._tenants.get(tenant)
            if rec is None:
                return {"tenant": tenant, "status": "unknown"}
            out = {
                "tenant": tenant,
                "status": rec["status"],
                "algo": rec["algo"],
            }
            for k in (
                "cost", "violations", "cycles", "best_cost",
                "cycles_to_best", "assignment", "error", "bucket",
                "batch_size", "queue_ms", "pulse",
            ):
                if k in rec:
                    out[k] = rec[k]
            return out

    def wait(self, tenant: str, timeout: float = 60.0) -> Dict[str, Any]:
        """Poll until the tenant reaches a terminal state."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rec = self.result(tenant)
            if rec["status"] in ("done", "failed", "killed", "unknown"):
                return rec
            time.sleep(0.005)
        return self.result(tenant)

    # -- status surface ------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            lat = sorted(self._latencies[-LATENCY_SAMPLES:])
            tenants = dict(
                list(self._tenants.items())[-STATUS_TENANTS:]
            )
            rows = {}
            for tid, rec in tenants.items():
                row = {
                    "status": rec["status"],
                    "algo": rec["algo"],
                }
                for k in (
                    "cost", "best_cost", "cycles", "cycles_to_best",
                    "bucket", "batch_size", "queue_ms", "error",
                ):
                    if k in rec:
                        row[k] = rec[k]
                if "pulse" in rec:
                    row["pulse"] = rec["pulse"]
                rows[tid] = row
            counts: Dict[str, int] = {}
            for rec in self._tenants.values():
                counts[rec["status"]] = counts.get(rec["status"], 0) + 1
            return {
                "status": "serve",
                "mode": self.mode,
                "state": self._state,
                "queue_depth": self._queue.qsize(),
                "tenants": rows,
                "tenant_counts": counts,
                "batches": self.batches,
                "solves": self.solves,
                "dead_letters": self.dead_letters,
                "queue_ms": {
                    "p50": _percentile(lat, 0.50),
                    "p99": _percentile(lat, 0.99),
                },
            }

    # -- lifecycle -----------------------------------------------------

    def drain(self, timeout: float = 120.0) -> bool:
        """Graceful shutdown: stop accepting, finish every queued tenant,
        stop the worker, and (with ``checkpoint_dir``) write the fleet
        checkpoint.  True when the queue fully drained in time."""
        with self._lock:
            self._state = "draining"
        self._stop.set()
        ok = self._drained.wait(timeout)
        with self._lock:
            self._state = "drained" if ok else "drain-timeout"
        if self.checkpoint_dir:
            try:
                self.fleet_checkpoint_path = self._write_fleet_checkpoint()
            except OSError:
                logger.exception("fleet checkpoint write failed")
        return ok

    def _write_fleet_checkpoint(self) -> str:
        """The drain's durable record: one atomic JSON manifest with the
        full tenant census — terminal tenants keep their results
        (cost/assignment/cycles), non-terminal ones are listed so nothing
        a dying fleet owned goes unaccounted.  Same manifest format
        family as the solver checkpoints (``kind: fleet``); array-free,
        so it reads anywhere."""
        import os
        import time as _time

        from ..durability.manager import MANIFEST_FORMAT
        from ..utils.checkpoint import atomic_write_json

        with self._lock:
            tenants = {}
            for tid, rec in self._tenants.items():
                row = {"status": rec["status"], "algo": rec["algo"]}
                for k in (
                    "cost", "violations", "cycles", "best_cost",
                    "cycles_to_best", "assignment", "error", "bucket",
                    "batch_size", "n_cycles",
                ):
                    if k in rec:
                        row[k] = rec[k]
                tenants[tid] = row
            manifest = {
                "format": MANIFEST_FORMAT,
                "kind": "fleet",
                "wrote_unix_s": _time.time(),
                "state": self._state,
                "mode": self.mode,
                "batches": self.batches,
                "solves": self.solves,
                "dead_letters": self.dead_letters,
                "tenants": tenants,
            }
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        path = os.path.join(self.checkpoint_dir, "fleet-manifest.json")
        atomic_write_json(
            path, manifest, indent=2, sort_keys=True, default=str
        )
        if metrics_registry.enabled:
            _m_fleet_ckpt.inc()
        logger.info(
            "fleet checkpoint: %d tenant(s) -> %s", len(tenants), path
        )
        return path

    def shutdown(self, drain: bool = True, timeout: float = 120.0) -> bool:
        ok = self.drain(timeout) if drain else True
        if not drain:
            self._stop.set()
        if self.http is not None:
            self.http.shutdown()
        return ok

    def wait_drained(self, timeout: float = 120.0) -> bool:
        """Block until a drain (started here or via POST /shutdown)
        finished emptying the queue."""
        return self._drained.wait(timeout)

    # -- HTTP routes (mounted on the shared metrics port) --------------

    def _http_solve(self, path: str, body: bytes):
        import json

        from ..dcop.yamldcop import load_dcop
        from ..compile.core import compile_dcop

        spec = json.loads(body.decode("utf-8"))
        dcop = load_dcop(spec["dcop_yaml"])
        req = SolveRequest(
            tenant=spec.get("tenant") or "",
            compiled=compile_dcop(dcop),
            algo=spec.get("algo", "dsa"),
            params=spec.get("params") or {},
            n_cycles=int(spec.get("n_cycles", 100)),
            seed=int(spec.get("seed", 0)),
        )
        try:
            tenant = self.submit(req)
        except RuntimeError as e:
            return 503, {"error": str(e)}
        return 200, {"tenant": tenant}

    def _http_result(self, path: str, body: bytes):
        tenant = path.rsplit("/", 1)[-1]
        rec = self.result(tenant)
        return (404 if rec["status"] == "unknown" else 200), rec

    def _http_shutdown(self, path: str, body: bytes):
        # answer first, drain in the background: the HTTP reply must not
        # wait behind the queue
        threading.Thread(
            target=self.shutdown, kwargs={"drain": True}, daemon=True
        ).start()
        return 200, {"state": "draining"}

    # -- the worker loop -----------------------------------------------

    def _run(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    break
                continue
            batch = [first]
            deadline = time.monotonic() + self.window_s
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0 and not self._stop.is_set():
                    break
                try:
                    batch.append(
                        self._queue.get(timeout=max(0.0, remaining))
                    )
                except queue.Empty:
                    break
            try:
                self._dispatch(batch)
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("serve batch dispatch failed")
                now = time.monotonic()
                with self._lock:
                    for tid in batch:
                        rec = self._tenants.get(tid)
                        if rec and rec["status"] in ("queued", "running"):
                            rec["status"] = "failed"
                            rec["error"] = "dispatch error (see log)"
                            rec["finished_s"] = now
                            self.dead_letters += 1
                            _m_dead_letters.inc()
        self._drained.set()

    def _fired_kills(self) -> List[str]:
        """Patterns of chaos kills due by now, each fired exactly once."""
        if self.fault_schedule is None:
            return []
        elapsed = time.monotonic() - self._t0
        out = []
        for ev in self.fault_schedule.kills:
            key = (ev.agent, ev.at)
            if ev.at <= elapsed and key not in self._kills_fired:
                self._kills_fired.add(key)
                out.append(ev.agent)
        return out

    def _dispatch(self, tenant_ids: List[str]) -> None:
        now = time.monotonic()
        with self._lock:
            reqs = []
            for tid in tenant_ids:
                rec = self._tenants[tid]
                rec["status"] = "running"
                rec["started_s"] = now
                q_ms = (now - rec["submitted_s"]) * 1e3
                rec["queue_ms"] = round(q_ms, 3)
                self._latencies.append(q_ms)
                if len(self._latencies) > 2 * LATENCY_SAMPLES:
                    del self._latencies[:-LATENCY_SAMPLES]
                if metrics_registry.enabled:
                    _m_queue_seconds.observe(q_ms / 1e3)
                reqs.append(rec["request"])
        # chaos kills due before/while this batch runs: the victims'
        # solves still execute (the batch is one program), their RESULTS
        # are dropped — mid-batch death must degrade only the dead tenant
        kill_patterns = self._fired_kills()
        results = solve_batched(
            reqs, max_batch=self.max_batch, mode=self.mode
        )
        kill_patterns += self._fired_kills()  # due while the batch ran
        with self._lock:
            for tid in tenant_ids:
                rec = self._tenants[tid]
                tr: Optional[TenantResult] = results.get(tid)
                killed = any(
                    fnmatch.fnmatchcase(tid, pat) for pat in kill_patterns
                )
                rec["finished_s"] = time.monotonic()
                # terminal records never re-dispatch: drop the request
                # (it pins the compiled problem + its cached device
                # arrays — the big share of a tenant's memory)
                rec.pop("request", None)
                if killed:
                    rec["status"] = "killed"
                    rec["error"] = "killed by chaos schedule"
                    self.dead_letters += 1
                    _m_dead_letters.inc()
                elif tr is None or tr.result is None:
                    rec["status"] = "failed"
                    rec["error"] = (tr.extras if tr else {}).get(
                        "error", "no result"
                    )
                    self.dead_letters += 1
                    _m_dead_letters.inc()
                else:
                    self._record_done(rec, tr)
                    self.solves += 1
            self.batches += 1
            self._evict_terminal()
            if metrics_registry.enabled:
                for state in TENANT_STATES:
                    _m_tenants.set(
                        sum(
                            1 for r in self._tenants.values()
                            if r["status"] == state
                        ),
                        state=state,
                    )

    def _evict_terminal(self) -> None:
        """Drop the oldest TERMINAL tenant records past TENANT_RETAIN
        (caller holds the lock) — the memory bound of a long-lived
        server; live tenants are never evicted."""
        excess = len(self._tenants) - TENANT_RETAIN  # graftlint: disable=lock-unguarded-read (caller _dispatch holds self._lock)
        if excess <= 0:
            return
        for tid in [
            t for t, r in self._tenants.items()  # graftlint: disable=lock-unguarded-read (caller holds self._lock)
            if r["status"] in ("done", "failed", "killed")
        ][:excess]:
            del self._tenants[tid]  # graftlint: disable=lock-unguarded-write (caller holds self._lock)

    def _record_done(self, rec: Dict[str, Any], tr: TenantResult) -> None:
        rec["status"] = "done"
        rec["cost"] = tr.result.cost
        rec["violations"] = tr.result.violations
        rec["cycles"] = tr.result.cycles
        rec["assignment"] = tr.result.assignment
        rec["best_cost"] = tr.extras.get("best_cost")
        rec["cycles_to_best"] = tr.extras.get("cycles_to_best")
        if "bucket" in tr.extras:
            key = tr.extras["bucket"]
            rec["bucket"] = (
                f"{key.algo}/v{key.dims.n_vars}e{key.dims.n_edges}"
                f"d{key.dims.max_domain}n{key.n_pad}"
            )
        if "batch_size" in tr.extras:
            rec["batch_size"] = tr.extras["batch_size"]
        pulse_blk = tr.extras.get("pulse")
        if pulse_blk is not None and pulse_blk.get("health") is not None:
            a = analyze_pulse(pulse_blk["health"])
            rec["pulse"] = {
                "diagnosis": a.get("diagnosis_full", a.get("diagnosis")),
                "churn": round(float(a.get("churn_now", 0.0) or 0.0), 4),
                "residual": float(a.get("residual_now", 0.0) or 0.0),
                "violations": int(a.get("violations", 0) or 0),
                "cycles": a.get("cycles", 0),
            }
