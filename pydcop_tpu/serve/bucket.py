"""Shape buckets: power-of-two-rounded padded dims shared by a tenant fleet.

A jit executable is keyed by the static shape of every operand, so two
problems share one compiled program exactly when their PADDED dims match.
``bucket_dims_of`` derives a problem's bucket by rounding every shardable
``DeviceDCOP`` dimension up to a power of two (<2x padding waste, and the
number of distinct buckets a fleet can populate grows only
logarithmically with problem size); ``pad_dev_to_bucket`` then pads the
instance to its bucket with the same cost-neutral dead-state rows
``parallel.mesh.pad_device_dcop`` uses for mesh sharding — padding is
dead state, not masked state, so solvers need no changes.

``pad_ell_classes`` does the same for the MaxSum ELL layout: each degree
class's variable count is rounded up to a power of two with dummy
variables (slots masked dead exactly like build_ell's intra-class
padding), so two graphs with the same padded span signature share the
ELL step executable too.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

from ..compile.kernels import DeviceDCOP, EllLayout
from ..parallel.mesh import pad_device_dcop_to

__all__ = [
    "BucketDims",
    "bucket_dims_of",
    "pad_dev_to_bucket",
    "pad_ell_classes",
    "padded_spans",
    "pow2",
]


def pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor)."""
    n = max(int(n), floor)
    return 1 << max(0, n - 1).bit_length()


class BucketDims(NamedTuple):
    """The padded DeviceDCOP dims identifying one shape bucket (all
    power-of-two-rounded; equality = same bucket = same executable once
    the algorithm statics match too)."""

    n_vars: int
    n_edges: int
    n_constraints: int
    max_domain: int
    #: (arity, padded constraint rows) per arity bucket
    bucket_sig: Tuple[Tuple[int, int], ...]
    dtype: str


def bucket_dims_of(compiled) -> BucketDims:
    """Bucket of a CompiledDCOP: every dim of the device representation
    rounded up to a power of two (variables and constraints reserve the
    one dead row the padding needs, exactly like pad_device_dcop)."""
    n_edges_dev = max(compiled.n_edges, 1)
    n_cons_dev = max(compiled.n_constraints, 1)
    sig = tuple(
        (b.arity, pow2(b.tables.shape[0])) for b in compiled.buckets
    )
    next_edge = n_edges_dev + sum(
        (rows - b.tables.shape[0]) * b.arity
        for (_, rows), b in zip(sig, compiled.buckets)
    )
    return BucketDims(
        n_vars=pow2(compiled.n_vars + 1),
        n_edges=pow2(next_edge),
        n_constraints=pow2(n_cons_dev + 1),
        max_domain=compiled.max_domain,
        bucket_sig=sig,
        dtype=np.dtype(compiled.float_dtype).name,
    )


def pad_dev_to_bucket(dev: DeviceDCOP, dims: BucketDims) -> DeviceDCOP:
    """Pad a device problem to its bucket's dims (cost-neutral dead
    rows; see parallel.mesh.pad_device_dcop_to)."""
    return pad_device_dcop_to(
        dev,
        dims.n_vars,
        dims.n_edges,
        dims.n_constraints,
        tuple(rows for _, rows in dims.bucket_sig),
    )


def padded_spans(
    spans: Tuple[Tuple[int, int], ...]
) -> Tuple[Tuple[int, int], ...]:
    """ELL span signature with each degree class's variable count rounded
    up to a power of two — the MaxSum component of the bucket key."""
    return tuple((pow2(nb), db) for nb, db in spans)


def pad_ell_classes(ell: EllLayout) -> EllLayout:
    """Pad a single-shard ELL layout so each degree class holds a
    power-of-two variable count (``padded_spans`` of the original).

    The pad columns are dummy variables of their class's degree: their
    slots carry all-zero tables, are masked out of every mean/min
    (``edge_valid_t`` False, ``real_row`` False) and are their own
    pair-permutation partner, exactly like build_ell's intra-class
    degree padding — both message planes stay exactly zero there every
    cycle, so fan-in sums, convergence checks and trajectories are
    slot-for-slot identical to the unpadded layout."""
    if ell.n_shards != 1:
        raise ValueError(
            "pad_ell_classes expects a single-shard layout "
            f"(got n_shards={ell.n_shards})"
        )
    target = padded_spans(ell.spans)
    d = ell.tabs_t.shape[0]
    # old slot / variable-column index per NEW position, -1 on class pads
    slot_parts = []
    var_parts = []
    off_e = off_v = 0
    for (nb, db), (tb, _) in zip(ell.spans, target):
        pad_n = tb - nb
        if db > 0:
            slot_parts.append(np.arange(off_e, off_e + nb * db))
            if pad_n:
                slot_parts.append(np.full(pad_n * db, -1, dtype=np.int64))
        var_parts.append(np.arange(off_v, off_v + nb))
        if pad_n:
            var_parts.append(np.full(pad_n, -1, dtype=np.int64))
        off_e += nb * db
        off_v += nb
    slot_map = (
        np.concatenate(slot_parts).astype(np.int64)
        if slot_parts else np.zeros(0, dtype=np.int64)
    )
    var_map = np.concatenate(var_parts).astype(np.int64)
    n_pad_new = len(slot_map)
    real_slot = slot_map >= 0
    new_of_old = np.empty(ell.n_pad, dtype=np.int64)
    new_of_old[slot_map[real_slot]] = np.flatnonzero(real_slot)

    edge_orig = np.full(n_pad_new, -1, dtype=ell.edge_orig.dtype)
    edge_orig[real_slot] = ell.edge_orig[slot_map[real_slot]]
    pair_perm = np.arange(n_pad_new, dtype=np.int32)
    pair_perm[real_slot] = new_of_old[
        ell.pair_perm[slot_map[real_slot]]
    ].astype(np.int32)
    tabs_t = np.zeros((d, d, n_pad_new), dtype=ell.tabs_t.dtype)
    tabs_t[:, :, real_slot] = ell.tabs_t[:, :, slot_map[real_slot]]
    edge_valid_t = np.zeros((d, n_pad_new), dtype=bool)
    edge_valid_t[:, real_slot] = ell.edge_valid_t[:, slot_map[real_slot]]
    dsize_edges = np.ones(n_pad_new, dtype=ell.dsize_edges.dtype)
    dsize_edges[real_slot] = ell.dsize_edges[slot_map[real_slot]]
    real_row = np.zeros((1, n_pad_new), dtype=bool)
    real_row[0, real_slot] = ell.real_row[0, slot_map[real_slot]]

    real_var = var_map >= 0
    var_perm = np.zeros(len(var_map), dtype=np.int32)
    var_perm[real_var] = ell.var_perm[var_map[real_var]]
    valid_ell = np.zeros((d, len(var_map)), dtype=bool)
    valid_ell[:, real_var] = ell.valid_ell_t[:, var_map[real_var]]
    valid_ell[0, ~real_var] = True  # pad columns: unread argmin lands on 0
    pos_of_var = np.empty(len(ell.pos_of_var), dtype=np.int32)
    new_var_pos = np.flatnonzero(real_var).astype(np.int32)
    pos_of_var[var_perm[real_var]] = new_var_pos[
        np.arange(real_var.sum())
    ]
    return EllLayout(
        spans=target,
        n_pad=n_pad_new,
        var_perm=var_perm,
        pos_of_var=pos_of_var,
        edge_orig=edge_orig,
        pair_perm=pair_perm,
        tabs_t=tabs_t,
        edge_valid_t=edge_valid_t,
        valid_ell_t=valid_ell,
        dsize_edges=dsize_edges,
        real_row=real_row,
        n_shards=1,
    )
