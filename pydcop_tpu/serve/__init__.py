"""graftserve: many-tenant batched solving behind one vmapped executable.

"Millions of users" means many DCOP instances in flight, not one big one
(ROADMAP item 3).  The reference serves one problem per orchestrator
process with a python thread per agent; the TPU-native answer is ONE
compiled program whose leading batch axis amortizes dispatch, compile and
readback across an entire fleet of tenant solves:

- ``serve.bucket`` — shape buckets: every padded ``DeviceDCOP`` dimension
  is rounded up to a power of two (reusing ``parallel.mesh``'s
  cost-neutral dead-state padding), so same-topology-class problems map
  to the same bucket and share an XLA executable.  The second tenant in a
  warm bucket compiles NOTHING (pinned via the ``profiled_jit`` census).
- ``serve.batch`` — the vmapped engine: a stacked ``DeviceDCOP`` pytree
  (leading axis = instance) solved as one dispatch by mapping
  ``algorithms.base._fused_core`` over the instance axis; per-tenant PRNG
  keys, noise levels and cycle budgets ride as traced operands.
  Batch-of-K results are BITWISE equal to K sequential solves through
  ``solve_one`` (same bucket padding) — pinned in tests/test_algorithms.
- ``serve.server`` — the serving front-end behind ``pydcop_tpu serve``:
  an async request queue with a micro-batching window, per-tenant
  anytime-cost + graftpulse rows on the existing ``/status``/``/metrics``
  surface, graceful drain, and graftchaos composition (a tenant killed
  mid-batch degrades that tenant only, dead-letter accounted).
- ``serve.router`` — graftha, the HA tier behind ``pydcop_tpu router``:
  N workers behind an SLO-driven router (bucket-affinity placement via
  ``distribution/tpu_part``, fast-burn admission control, chaos-killed
  workers' tenants failed over onto survivors — docs/serving.md "HA
  fleet").  Imported lazily: the router is host-only and must not pull
  the device stack.
"""

from .batch import (
    BatchPlan,
    ServeUnsupported,
    SolveRequest,
    TenantResult,
    bucket_key,
    solve_batched,
    solve_one,
)
from .bucket import BucketDims, bucket_dims_of, pad_dev_to_bucket
from .server import ServeServer

__all__ = [
    "BatchPlan",
    "BucketDims",
    "ServeServer",
    "ServeUnsupported",
    "SolveRequest",
    "TenantResult",
    "bucket_dims_of",
    "bucket_key",
    "pad_dev_to_bucket",
    "solve_batched",
    "solve_one",
]
