"""graftha: the HA serve fleet — an SLO-driven router over N workers.

PAPER.md's reference runtime treats agent death as a first-class event
(replication + repair); graftha is the serving-layer twin: N
:class:`~pydcop_tpu.serve.server.ServeServer` workers behind one router
so losing a worker is an SLO blip, not an outage (ROADMAP item 3,
"heavy traffic from millions of users").  Three responsibilities:

- **Placement** — tenants are routed by *bucket affinity*: requests
  hash to an :func:`affinity_key` (algorithm + power-of-two problem
  class, the cheap prefix of ``serve.batch.BucketKey``) and buckets are
  laid onto workers by the SAME placement engine that places
  computations on agents (``distribution/tpu_part`` — its third use,
  after agent distribution and mesh sharding).  Same-bucket tenants
  land on the same worker, so the fleet compiles each executable once
  instead of once per worker — warm-bucket hits beat round-robin on
  queue p99 (pinned in tests/test_router.py and the fleet-soak record).
  ``placement="round_robin"`` keeps the classic spray for A/B runs.
- **Admission control** — a fleet-SLO-fed control loop: when a
  fast-burn alert trips (on the federated worker objectives or on the
  router's own forward-outcome objectives), low-priority tenants are
  *shed* (structured 503 + ``Retry-After`` + live peer list) and
  normal-priority tenants are *deferred* (parked router-side, released
  when the burn clears or ``defer_max_s`` elapses); high priority is
  always admitted.  Every shed/defer decision is a structured event and
  a counter (``router.shed_total{reason,priority}``).  When queues sit
  idle and nothing burns, the loop *widens* the workers' micro-batch
  windows (``POST /window``) to trade latency headroom for batch
  occupancy, and narrows them back the moment queues build or an alert
  fires.
- **Failover** — a chaos-killed worker is detected by the
  ``fleet.worker_up`` flip (bounded scrape retry first — one dropped
  connection is not a death) or by a forward that exhausts its
  :class:`~pydcop_tpu.infrastructure.retry.RetryPolicy`.  The victim's
  non-terminal tenants are re-admitted onto surviving workers: terminal
  results left in the victim's graftdur ``fleet-manifest.json`` are
  ADOPTED (ownership transfer recorded à la graftucs — a tenant is
  never solved twice), everything else is re-solved from scratch with
  the original seed (``router.resolve_from_scratch``) — bit-identical
  to the uninterrupted solve under the vmap bit-identity contract.
  Per-tenant deadlines bound the whole recovery, so a flapping worker
  degrades to slow, not lost.

Host-only and stdlib+numpy: the router never touches a device backend —
it is safe to run next to a TPU fleet (docs/serving.md, "HA fleet").
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import threading
import time
from dataclasses import replace
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from ..infrastructure.retry import RetryPolicy
from ..telemetry.federate import (
    FleetCollector,
    FleetSlo,
    FleetTarget,
    _http_fetch,
)
from ..telemetry.metrics import metrics_registry
from ..telemetry.slo import (
    DEFAULT_FAST_BURN,
    DEFAULT_SLOW_BURN,
    Objective,
    SloEngine,
)

__all__ = ["Router", "affinity_key", "PRIORITIES"]

logger = logging.getLogger("pydcop_tpu.serve.router")

#: admission classes, most to least protected
PRIORITIES = ("high", "normal", "low")

#: structured router events kept for /status
EVENTS_CAP = 512

#: tenant rows included in /status
STATUS_TENANTS = 64

_TERMINAL = ("done", "failed", "killed")


def _pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor) (host twin of
    ``serve.bucket.pow2`` — that module imports the device stack)."""
    n = max(int(n), int(floor))
    p = 1
    while p < n:
        p <<= 1
    return p


def affinity_key(spec: Dict[str, Any]) -> str:
    """The routing bucket of one ``/solve`` request: algorithm plus the
    power-of-two class of the problem's variable and constraint counts —
    the cheap, compile-free prefix of ``serve.batch.BucketKey``.  Equal
    keys co-locate (and so share warm executables on their worker);
    unequal keys merely land in different buckets, exactly like the
    serve layer's own bucketing — correctness never depends on it.

    >>> affinity_key({"algo": "dsa", "dcop_yaml": "variables: {a: {domain: d}}"})
    'dsa/v2c1'
    """
    algo = str(spec.get("algo") or "dsa")
    try:
        import yaml

        doc = yaml.safe_load(spec.get("dcop_yaml") or "") or {}
        n_vars = len(doc.get("variables") or {})
        n_cons = len(doc.get("constraints") or {})
    except Exception:  # noqa: BLE001 — unparseable specs still route
        return f"{algo}/v0c0"
    return f"{algo}/v{_pow2(n_vars + 1)}c{_pow2(max(n_cons, 1))}"


def _http_post(
    url: str, doc: Dict[str, Any], timeout: float = 10.0
) -> Optional[Tuple[int, Dict[str, Any]]]:
    """POST ``doc`` as JSON; ``(status, body)`` for any HTTP answer
    (including 4xx/5xx — a structured rejection is data), None on
    transport failure (the worker is unreachable)."""
    import urllib.error
    import urllib.request

    data = json.dumps(doc, default=str).encode("utf-8")
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read().decode("utf-8")
            return resp.getcode(), (json.loads(body) if body else {})
    except urllib.error.HTTPError as e:
        try:
            body = e.read().decode("utf-8")
            return e.code, (json.loads(body) if body else {})
        except (OSError, ValueError):
            return e.code, {}
    except (urllib.error.URLError, OSError, ValueError, TimeoutError):
        return None


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_m_shed = metrics_registry.counter(
    "router.shed_total",
    "tenants shed by admission control, by reason and priority",
)
# graftmem: workers' memory-budget refusals as the ROUTER sees them — a
# mem-refused tenant must not be retried against the same worker (the
# breach is a property of the problem's bucket, not of load), so the
# refusal is surfaced per worker for placement decisions
_m_mem_refused = metrics_registry.counter(
    "router.mem_refusals_total",
    "forwards rejected by a worker's graftmem OOM guard, by worker",
)
_m_deferred = metrics_registry.counter(
    "router.deferred_total", "tenants deferred by admission control"
)
_m_released = metrics_registry.counter(
    "router.released_total", "deferred tenants released to a worker"
)
_m_forwards = metrics_registry.counter(
    "router.forwards_total", "tenant forwards accepted, per worker"
)
_m_fwd_retries = metrics_registry.counter(
    "router.forward_retries_total", "forward transport attempts retried"
)
_m_failovers = metrics_registry.counter(
    "router.failovers_total", "worker failovers handled, per worker"
)
_m_from_scratch = metrics_registry.counter(
    "router.resolve_from_scratch",
    "victim tenants re-solved from scratch on a surviving worker",
)
_m_adopted = metrics_registry.counter(
    "router.adopted_results",
    "victim tenant results adopted from durable fleet manifests",
)
_m_window_adj = metrics_registry.counter(
    "router.window_adjust_total",
    "micro-batch window retunes pushed to workers, by direction",
)
_g_admission = metrics_registry.gauge(
    "router.admission_open", "1 while no fast-burn alert gates admission"
)
_g_placeable = metrics_registry.gauge(
    "router.workers_placeable", "workers currently eligible for placement"
)
_g_tenants = metrics_registry.gauge(
    "router.tenants", "router tenant census by status"
)

#: sentinel: "use the module default scrape-retry policy"
_DEFAULT = object()


class Router:
    """SLO-driven router over a fleet of serve workers (module
    docstring).  All control-loop entry points (:meth:`tick`,
    :meth:`submit`) accept an explicit ``now`` and every transport is
    injectable, so unit tests drive the whole failure lifecycle
    deterministically with fake clocks and fake fleets."""

    def __init__(
        self,
        targets: Sequence[FleetTarget],
        port: Optional[int] = None,
        host: str = "127.0.0.1",
        placement: str = "affinity",
        interval_s: float = 0.5,
        stale_after_s: float = 10.0,
        objectives: Sequence[Objective] = (),
        router_objectives: Sequence[Objective] = (),
        fast_burn: float = DEFAULT_FAST_BURN,
        slow_burn: float = DEFAULT_SLOW_BURN,
        retry: Optional[RetryPolicy] = None,
        scrape_retry: Any = _DEFAULT,
        tenant_deadline_s: float = 120.0,
        defer_max_s: float = 15.0,
        window_base_ms: float = 25.0,
        window_max_factor: float = 4.0,
        idle_ticks_to_widen: int = 3,
        state_dir: Optional[str] = None,
        result_poll_batch: int = 64,
        clock: Callable[[], float] = time.monotonic,
        fetch: Optional[Callable[[str], Optional[Dict[str, Any]]]] = None,
        post: Optional[
            Callable[[str, Dict[str, Any]], Optional[Tuple[int, Dict[str, Any]]]]
        ] = None,
    ) -> None:
        if placement not in ("affinity", "round_robin"):
            raise ValueError(f"unknown placement strategy {placement!r}")
        self.placement = placement
        self.interval_s = max(0.05, float(interval_s))
        self.tenant_deadline_s = float(tenant_deadline_s)
        self.defer_max_s = float(defer_max_s)
        self.window_base_ms = float(window_base_ms)
        self.window_max_factor = max(1.0, float(window_max_factor))
        self.idle_ticks_to_widen = max(1, int(idle_ticks_to_widen))
        self.state_dir = state_dir
        self.result_poll_batch = max(1, int(result_poll_batch))
        #: forwards ride a RetryPolicy (infrastructure/retry.py) with the
        #: per-tenant deadline folded in — a flapping worker degrades to
        #: slow, not lost
        self.retry = retry or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.5, jitter="full"
        )
        self._clock = clock
        self._fetch = fetch or _http_fetch
        self._post = post or _http_post
        kwargs: Dict[str, Any] = {}
        if scrape_retry is not _DEFAULT:
            kwargs["scrape_retry"] = scrape_retry
        self.collector = FleetCollector(
            targets,
            interval_s=interval_s,
            stale_after_s=stale_after_s,
            clock=clock,
            fetch=fetch,
            **kwargs,
        )
        self._targets_by_name: Dict[str, FleetTarget] = {
            t.name: t for t in self.collector.targets
        }
        self.fleet_slo: Optional[FleetSlo] = (
            FleetSlo(
                self.collector,
                objectives,
                fast_burn=fast_burn,
                slow_burn=slow_burn,
                clock=clock,
            )
            if objectives
            else None
        )
        #: the router's OWN objectives, classified over forward outcomes
        #: (accepted = good; transport-exhausted / rejected / deadline-
        #: expired = bad) — the burn signal a worker kill produces even
        #: when the dead worker can no longer report its own slo.events
        self.engine: Optional[SloEngine] = (
            SloEngine(
                router_objectives,
                fast_burn=fast_burn,
                slow_burn=slow_burn,
                clock=clock,
                publish_metrics=True,
                # alert postmortems land next to the ownership manifest,
                # not in whatever directory the process happens to run in
                # (no state_dir -> None: the engine defaults into
                # $PYDCOP_TPU_STATE_DIR, never the cwd)
                postmortem_path=os.path.join(
                    state_dir, "router_slo_postmortem.json"
                )
                if state_dir
                else None,
            )
            if router_objectives
            else None
        )
        self._lock = threading.Lock()
        self._t0 = clock()
        self._ids = itertools.count()
        self._rr_seq = itertools.count()
        self._state = "serving"
        self._tenants: Dict[str, Dict[str, Any]] = {}
        self._events: Deque[Dict[str, Any]] = collections.deque(
            maxlen=EVENTS_CAP
        )
        self._bucket_counts: Dict[str, int] = {}
        self._bucket_map: Dict[str, str] = {}
        self._placed_for: Tuple[str, ...] = ()
        self._suspect: set = set()
        self._was_live: Dict[str, bool] = {}
        self._idle_ticks = 0
        self._window_factor = 1.0
        self._counts: Dict[str, int] = {
            "shed": 0,
            "deferred": 0,
            "released": 0,
            "failovers": 0,
            "adopted": 0,
            "from_scratch": 0,
            "deadline_expired": 0,
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.http = None
        if port is not None:
            from ..infrastructure.ui import MetricsHttpServer

            routes: Dict[Any, Callable] = {
                ("POST", "/solve"): self._http_solve,
                ("GET", "/result"): self._http_result,
                ("GET", "/healthz"): self._http_healthz,
                ("GET", "/fleet/status"): self._http_fleet_status,
                ("POST", "/shutdown"): self._http_shutdown,
            }
            if self.fleet_slo is not None:
                routes[("GET", "/fleet/slo")] = self._http_fleet_slo
            if self.engine is not None:
                routes[("GET", "/slo")] = self._http_slo
            self.http = MetricsHttpServer(
                port=port,
                host=host,
                status_cb=self.status,
                snapshot_cb=self.snapshot,
                routes=routes,
            )

    # -- worker liveness ----------------------------------------------

    def _target(self, worker: str) -> Optional[FleetTarget]:
        return self._targets_by_name.get(worker)

    def _live_workers(
        self,
        now: Optional[float] = None,
        rows: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> List[str]:
        """Workers eligible for placement: scraped up, not draining
        (satellite: a draining worker is healthy but must not receive
        new tenants), and not suspected dead by a failed forward."""
        if rows is None:
            rows = self.collector.status(now=now)["workers"]
        with self._lock:
            suspect = set(self._suspect)
        out = []
        for name in sorted(rows):
            row = rows[name]
            if not row.get("up") or name in suspect:
                continue
            state = row.get("state")
            if state is not None and state != "serving":
                continue
            out.append(name)
        return out

    def _live_urls(self, now: Optional[float] = None) -> List[str]:
        return [
            self._targets_by_name[w].url
            for w in self._live_workers(now)
            if w in self._targets_by_name
        ]

    # -- placement (tpu_part, third use) -------------------------------

    def _compute_placement(
        self,
        buckets: Sequence[str],
        counts: Dict[str, int],
        workers: Sequence[str],
    ) -> Dict[str, str]:
        """Lay affinity buckets onto workers through the multilevel
        partitioner: one ComputationNode per bucket (same-algorithm
        buckets chain-linked so related shapes co-locate when they
        must share), equal-capacity AgentDefs per live worker — the
        exact ``distribution/tpu_part`` path that places computations
        on agents, reused verbatim for tenants on workers."""
        workers = sorted(workers)
        buckets = sorted(buckets)
        if not buckets or not workers:
            return {}
        if len(workers) == 1:
            return {b: workers[0] for b in buckets}
        try:
            from ..computations_graph.objects import (
                ComputationGraph,
                ComputationNode,
                Link,
            )
            from ..dcop.objects import AgentDef
            from ..distribution import tpu_part

            links_of: Dict[str, List[Link]] = {b: [] for b in buckets}
            by_algo: Dict[str, List[str]] = {}
            for b in buckets:
                by_algo.setdefault(b.split("/", 1)[0], []).append(b)
            for group in by_algo.values():
                for a, b in zip(group, group[1:]):
                    link = Link((a, b))
                    links_of[a].append(link)
                    links_of[b].append(link)
            graph = ComputationGraph(
                nodes=[
                    ComputationNode(b, "bucket", links=links_of[b])
                    for b in buckets
                ]
            )
            agents = [AgentDef(w, capacity=100.0) for w in workers]

            def _load(_node: Any, _neigh: str) -> float:
                return 1.0

            dist = tpu_part.distribute(
                graph, agents, communication_load=_load
            )
            return {b: dist.agent_for(b) for b in buckets}
        except Exception:  # noqa: BLE001 — placement must never drop traffic
            logger.exception(
                "tpu_part placement failed; falling back to modulo spread"
            )
            return {
                b: workers[i % len(workers)] for i, b in enumerate(buckets)
            }

    def _pick_worker(
        self, akey: str, excluded: set, now: Optional[float] = None
    ) -> Optional[str]:
        live_all = self._live_workers(now)
        live = [w for w in live_all if w not in excluded]
        if not live:
            return None
        if self.placement == "round_robin":
            with self._lock:
                i = next(self._rr_seq)
            return live[i % len(live)]
        key = tuple(sorted(live_all))
        with self._lock:
            # recompute the sticky bucket->worker map whenever the live
            # worker set or the bucket census changed under it
            if key != self._placed_for or not (
                set(self._bucket_counts) <= set(self._bucket_map)
            ):
                self._bucket_map = self._compute_placement(
                    list(self._bucket_counts),
                    dict(self._bucket_counts),
                    list(key),
                )
                self._placed_for = key
            mapped = self._bucket_map.get(akey)
        if mapped in live:
            return mapped
        # the placed worker is excluded mid-forward: stable fallback
        return live[hash(akey) % len(live)]

    # -- admission ------------------------------------------------------

    def _alerts_fast(self) -> List[str]:
        """Fast-burn alerts currently firing, across the federated
        worker objectives and the router's own forward objectives."""
        out: List[str] = []
        if self.fleet_slo is not None:
            out += [
                f"fleet:{name}"
                for name, sev in self.fleet_slo.fleet_engine.alerts_active()
                if sev == "fast"
            ]
        if self.engine is not None:
            out += [
                f"router:{name}"
                for name, sev in self.engine.alerts_active()
                if sev == "fast"
            ]
        return sorted(out)

    def admission_mode(self) -> str:
        return "shedding" if self._alerts_fast() else "open"

    def submit(
        self, spec: Dict[str, Any], now: Optional[float] = None
    ) -> Tuple[int, Dict[str, Any], Optional[Dict[str, str]]]:
        """Admit one ``/solve`` request: ``(status, payload, headers)``.

        200 = forwarded to a worker, 202 = deferred (parked router-side,
        released by the control loop), 503 = shed (structured, with
        ``Retry-After`` and the live peer list so clients can fail over
        without guessing)."""
        now = self._clock() if now is None else now
        priority = str(spec.get("priority") or "normal")
        if priority not in PRIORITIES:
            return (
                400,
                {
                    "error": f"unknown priority {priority!r} "
                    f"(expected one of {PRIORITIES})"
                },
                None,
            )
        if not spec.get("dcop_yaml"):
            return 400, {"error": "missing dcop_yaml"}, None
        trace = str(spec.get("trace") or "") or os.urandom(8).hex()
        retry_after = max(1, int(round(self.defer_max_s / 2.0)))
        with self._lock:
            state = self._state
        if state != "serving":
            return (
                503,
                {
                    "error": f"router is {state}: not accepting tenants",
                    "state": state,
                    "retry_after_s": retry_after,
                    "peers": self._live_urls(now),
                },
                {"Retry-After": str(retry_after)},
            )
        akey = affinity_key(spec)
        alerts = self._alerts_fast()
        with self._lock:
            tid = str(spec.get("tenant") or "") or (
                f"r{next(self._ids)}-{os.urandom(3).hex()}"
            )
            if tid in self._tenants:
                return 409, {"error": f"tenant id {tid!r} already known"}, None
            if alerts and priority == "low":
                self._counts["shed"] += 1
            else:
                body = {
                    k: spec[k]
                    for k in ("dcop_yaml", "algo", "params", "n_cycles", "seed")
                    if k in spec
                }
                self._tenants[tid] = {
                    "spec": body,
                    "priority": priority,
                    "akey": akey,
                    "trace": trace,
                    "status": "deferred",
                    # claimed by the submitting thread: the control
                    # loop's flush must not race the synchronous
                    # placement below, or the same tenant gets POSTed
                    # to a worker twice
                    "placing": True,
                    "worker": None,
                    "force": False,
                    "submitted_s": now,
                    "deadline_s": now + self.tenant_deadline_s,
                    "history": [],
                }
                self._bucket_counts[akey] = (
                    self._bucket_counts.get(akey, 0) + 1
                )
        if alerts and priority == "low":
            _m_shed.inc(reason="fast-burn", priority=priority)
            self._event(
                now, "shed",
                tenant=tid, priority=priority, reason="fast-burn",
                alerts=alerts,
            )
            return (
                503,
                {
                    "error": "admission shed: fast-burn alert active",
                    "shed": True,
                    "tenant": tid,
                    "reason": "fast-burn",
                    "priority": priority,
                    "alerts": alerts,
                    "retry_after_s": retry_after,
                    "peers": self._live_urls(now),
                },
                {"Retry-After": str(retry_after)},
            )
        if alerts and priority == "normal":
            with self._lock:
                self._counts["deferred"] += 1
                self._tenants[tid]["placing"] = False
            _m_deferred.inc(reason="fast-burn", priority=priority)
            self._event(
                now, "defer",
                tenant=tid, priority=priority, reason="fast-burn",
                alerts=alerts,
            )
            return (
                202,
                {
                    "tenant": tid,
                    "trace": trace,
                    "deferred": True,
                    "reason": "fast-burn",
                },
                None,
            )
        placed = self._forward(tid, now)
        with self._lock:
            rec = self._tenants.get(tid)
            if rec is not None:
                rec["placing"] = False
        if placed:
            with self._lock:
                worker = self._tenants[tid].get("worker")
            return 200, {"tenant": tid, "trace": trace, "worker": worker}, None
        with self._lock:
            self._counts["deferred"] += 1
        _m_deferred.inc(reason="no-worker", priority=priority)
        self._event(
            now, "defer", tenant=tid, priority=priority, reason="no-worker"
        )
        return (
            202,
            {
                "tenant": tid,
                "trace": trace,
                "deferred": True,
                "reason": "no-worker",
            },
            None,
        )

    # -- forwarding -----------------------------------------------------

    def _forward(self, tid: str, now: float) -> bool:
        """Place + forward one parked tenant; False leaves it deferred
        (no live worker, or every candidate failed)."""
        excluded: set = set()
        for _ in range(len(self.collector.targets)):
            with self._lock:
                rec = self._tenants.get(tid)
                if rec is None or rec["status"] not in ("deferred",):
                    return rec is not None and rec["status"] == "forwarded"
                akey = rec["akey"]
            worker = self._pick_worker(akey, excluded, now)
            if worker is None:
                return False
            ok, answered = self._post_solve(worker, tid, now)
            if ok:
                return True
            excluded.add(worker)
            if not answered:
                # transport exhausted: treat the worker as down and
                # rescue whatever else it owned (failed forward is one
                # of the two failover triggers)
                self._note_suspect(worker, now, reason="failed-forward")
        return False

    def _post_solve(
        self, worker: str, tid: str, now: float
    ) -> Tuple[bool, bool]:
        """One worker's forward attempt loop under the RetryPolicy:
        ``(accepted, answered)``.  ``answered`` False means transport
        death (every attempt failed to reach the worker)."""
        target = self._target(worker)
        if target is None:
            return False, True
        with self._lock:
            rec = self._tenants.get(tid)
            if rec is None:
                return False, True
            body = dict(rec["spec"])
            body["tenant"] = tid
            body["trace"] = rec["trace"]
            deadline_left = rec["deadline_s"] - now
        if deadline_left <= 0:
            return False, True
        policy = replace(
            self.retry,
            deadline=(
                min(self.retry.deadline, deadline_left)
                if self.retry.deadline is not None
                else deadline_left
            ),
        )
        started = policy.start()
        t_fwd = self._clock()
        attempt = 0
        while True:
            res = self._post(target.url + "/solve", body)
            if res is not None:
                code, doc = res
                if code == 200:
                    with self._lock:
                        rec = self._tenants.get(tid)
                        if rec is not None:
                            rec["status"] = "forwarded"
                            rec["worker"] = worker
                            rec["history"].append(
                                {
                                    "t": round(now - self._t0, 3),
                                    "event": "forward",
                                    "worker": worker,
                                }
                            )
                    _m_forwards.inc(worker=worker)
                    self._slo_record(tid, "done", self._clock() - t_fwd)
                    return True, True
                # an ANSWERED rejection (draining worker's structured
                # 503, bad request): no point retrying the same worker
                self._slo_record(tid, "failed", self._clock() - t_fwd)
                mem = (doc or {}).get("mem")
                if mem:
                    # graftmem refusal: keep the breach on the tenant
                    # record (visible in /fleet/status detail) and count
                    # it per worker — the structured error distinguishes
                    # "will never fit this worker" from "busy"
                    _m_mem_refused.inc(worker=worker)
                    with self._lock:
                        rec = self._tenants.get(tid)
                        if rec is not None:
                            rec["mem_refusal"] = mem
                self._event(
                    now, "forward-rejected",
                    tenant=tid, worker=worker, code=code,
                    state=(doc or {}).get("state"),
                    **({"mem_reason": mem.get("reason")} if mem else {}),
                )
                return False, True
            attempt += 1
            _m_fwd_retries.inc(worker=worker)
            if not policy.sleep_before_retry(attempt - 1, started):
                break
        self._slo_record(tid, "failed", self._clock() - t_fwd)
        return False, False

    def _slo_record(self, tenant: str, status: str, latency_s: float) -> None:
        if self.engine is not None:
            self.engine.record_request(tenant, status, latency_s)

    # -- failover -------------------------------------------------------

    def _note_suspect(self, worker: str, now: float, reason: str) -> None:
        with self._lock:
            fresh = worker not in self._suspect
            self._suspect.add(worker)
        if fresh:
            self._event(now, "worker-suspect", worker=worker, reason=reason)
            self._failover(worker, now, reason=reason)

    def _check_workers(self, now: float) -> None:
        """Walk the collector's up/down view: clear suspicions the
        scrape refutes, fail over workers the scrape says died."""
        rows = self.collector.status(now=now)["workers"]
        downs: List[str] = []
        with self._lock:
            for name in sorted(rows):
                up = bool(rows[name].get("up"))
                if up and name in self._suspect:
                    self._suspect.discard(name)
                was = self._was_live.get(name)
                self._was_live[name] = up
                if was and not up:
                    downs.append(name)
        for name in downs:
            self._failover(name, now, reason="scrape-down")

    def _failover(self, victim: str, now: float, reason: str) -> None:
        """Re-admit the victim's non-terminal tenants onto survivors.
        Terminal results in the victim's durable fleet manifest are
        adopted (never re-run); the rest re-solve from scratch with
        their original seeds — bit-identical under the vmap contract."""
        with self._lock:
            victims = [
                tid
                for tid, rec in self._tenants.items()
                if rec["status"] == "forwarded" and rec.get("worker") == victim
            ]
            for tid in victims:
                # claim atomically: a concurrent failover of the same
                # worker (scrape flip + failed forward racing) must not
                # rescue a tenant twice
                self._tenants[tid]["status"] = "failing-over"
            if victims:
                self._counts["failovers"] += 1
        if not victims:
            return
        _m_failovers.inc(worker=victim)
        self._event(
            now, "failover", worker=victim, reason=reason,
            tenants=len(victims),
        )
        manifest = self._manifest_tenants(victim)
        rescued: List[str] = []
        for tid in sorted(victims):
            row = manifest.get(tid)
            with self._lock:
                rec = self._tenants.get(tid)
                if rec is None or rec["status"] != "failing-over":
                    continue
                if row and row.get("status") in _TERMINAL:
                    # ownership transfer recorded; the tenant is NOT
                    # solved twice — the manifest result IS the solve
                    rec["status"] = row["status"]
                    result = dict(row)
                    result["tenant"] = tid
                    result["result_source"] = "manifest"
                    result["owner"] = victim
                    rec["result"] = result
                    rec["history"].append(
                        {
                            "t": round(now - self._t0, 3),
                            "event": "adopt",
                            "from": victim,
                        }
                    )
                    self._counts["adopted"] += 1
                    adopted = True
                else:
                    rec["status"] = "deferred"
                    rec["worker"] = None
                    rec["force"] = True
                    rec["history"].append(
                        {
                            "t": round(now - self._t0, 3),
                            "event": "resolve-from-scratch",
                            "from": victim,
                        }
                    )
                    self._counts["from_scratch"] += 1
                    adopted = False
            if adopted:
                _m_adopted.inc()
                self._event(now, "adopt", tenant=tid, worker=victim)
            else:
                _m_from_scratch.inc()
                rescued.append(tid)
        for tid in rescued:
            self._forward(tid, now)
        self._write_manifest()

    def _manifest_tenants(self, worker: str) -> Dict[str, Any]:
        """The victim's freshest graftdur ``fleet-manifest.json`` tenant
        census (empty when no state dir / no matching manifest)."""
        if not self.state_dir:
            return {}
        target = self._target(worker)
        url = target.url.rstrip("/") if target is not None else None
        best_t = -1.0
        tenants: Dict[str, Any] = {}
        candidates = [os.path.join(self.state_dir, "fleet-manifest.json")]
        try:
            entries = sorted(os.listdir(self.state_dir))
        except OSError:
            entries = []
        candidates += [
            os.path.join(self.state_dir, e, "fleet-manifest.json")
            for e in entries
        ]
        for path in candidates:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if doc.get("kind") != "fleet":
                continue
            endpoint = str(doc.get("endpoint") or "").rstrip("/")
            if not (
                (url and endpoint == url) or doc.get("worker") == worker
            ):
                continue
            t = float(doc.get("wrote_unix_s") or 0.0)
            if t > best_t:
                best_t = t
                tenants = doc.get("tenants") or {}
        return tenants

    # -- the control loop ----------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        """One control-loop step: scrape, evaluate burn, react.
        Deterministic when driven with an explicit ``now``."""
        now = self._clock() if now is None else now
        self.collector.poll(now=now)
        if self.fleet_slo is not None:
            self.fleet_slo.evaluate(now)
        if self.engine is not None:
            self.engine.evaluate(now)
        self._check_workers(now)
        self._poll_results(now)
        self._expire_deadlines(now)
        self._flush_deferred(now)
        self._tune_windows(now)
        self._publish_gauges(now)

    def _poll_results(self, now: float) -> None:
        """Pull terminal results for forwarded tenants into the router's
        own cache (bounded batch per tick) — after this, a worker death
        cannot lose a result the fleet already produced."""
        with self._lock:
            pending = [
                (tid, rec["worker"])
                for tid, rec in self._tenants.items()
                if rec["status"] == "forwarded" and rec.get("worker")
            ]
        for tid, worker in pending[: self.result_poll_batch]:
            target = self._target(worker)
            if target is None:
                continue
            doc = self._fetch(f"{target.url}/result/{tid}")
            if not doc:
                continue
            st = doc.get("status")
            if st not in _TERMINAL:
                continue
            with self._lock:
                rec = self._tenants.get(tid)
                if rec is None or rec["status"] != "forwarded":
                    continue
                rec["status"] = st
                result = dict(doc)
                result.setdefault("result_source", "worker")
                rec["result"] = result
                rec["history"].append(
                    {
                        "t": round(now - self._t0, 3),
                        "event": "complete",
                        "worker": worker,
                        "status": st,
                    }
                )

    def _expire_deadlines(self, now: float) -> None:
        with self._lock:
            expired = [
                tid
                for tid, rec in self._tenants.items()
                if rec["status"] in ("deferred", "forwarded")
                and now >= rec["deadline_s"]
            ]
            for tid in expired:
                rec = self._tenants[tid]
                rec["status"] = "failed"
                rec["error"] = "deadline exceeded"
                rec["history"].append(
                    {"t": round(now - self._t0, 3), "event": "deadline"}
                )
                self._counts["deadline_expired"] += 1
        for tid in expired:
            self._event(now, "deadline-expired", tenant=tid)
            self._slo_record(tid, "failed", self.tenant_deadline_s)

    def _flush_deferred(self, now: float, force: bool = False) -> None:
        """Release parked tenants: always when admission is open or the
        tenant is high priority / a failover rescue; normal-priority
        holds are bounded by ``defer_max_s`` even under sustained burn
        (deferred means slow, never lost)."""
        mode = self.admission_mode()
        with self._lock:
            ready = []
            for tid, rec in self._tenants.items():
                if rec["status"] != "deferred" or rec.get("placing"):
                    continue
                if (
                    force
                    or rec.get("force")
                    or mode == "open"
                    or rec["priority"] == "high"
                    or (
                        rec["priority"] == "normal"
                        and now - rec["submitted_s"] >= self.defer_max_s
                    )
                ):
                    ready.append(tid)
        for tid in ready:
            if self._forward(tid, now):
                with self._lock:
                    self._counts["released"] += 1
                _m_released.inc()

    def _tune_windows(self, now: float) -> None:
        """Widen the workers' micro-batch windows when the fleet idles
        (batch occupancy for free), narrow back to base the moment
        queues build or an alert fires."""
        rows = self.collector.status(now=now)["workers"]
        live = self._live_workers(now, rows=rows)
        qsum = sum(int(rows[w].get("queue_depth") or 0) for w in live)
        alerting = bool(self._alerts_fast())
        direction = None
        with self._lock:
            if alerting or qsum > 0:
                self._idle_ticks = 0
                if self._window_factor > 1.0:
                    self._window_factor = 1.0
                    direction = "narrow"
            else:
                self._idle_ticks += 1
                if (
                    self._idle_ticks >= self.idle_ticks_to_widen
                    and self._window_factor < self.window_max_factor
                ):
                    self._window_factor = min(
                        self.window_max_factor, self._window_factor * 2.0
                    )
                    self._idle_ticks = 0
                    direction = "widen"
            window_ms = self.window_base_ms * self._window_factor
        if direction is None:
            return
        _m_window_adj.inc(direction=direction)
        self._event(
            now, "window-adjust",
            direction=direction, window_ms=round(window_ms, 2),
        )
        for w in live:
            target = self._target(w)
            if target is not None:
                self._post(target.url + "/window", {"window_ms": window_ms})

    def _publish_gauges(self, now: float) -> None:
        if not metrics_registry.enabled:
            return
        with self._lock:
            counts: Dict[str, int] = {}
            for rec in self._tenants.values():
                counts[rec["status"]] = counts.get(rec["status"], 0) + 1
        for st, n in counts.items():
            _g_tenants.set(float(n), status=st)
        _g_placeable.set(float(len(self._live_workers(now))))
        _g_admission.set(0.0 if self._alerts_fast() else 1.0)

    def _event(self, now: float, kind: str, **fields: Any) -> None:
        ev = {"t": round(now - self._t0, 3), "event": kind, **fields}
        with self._lock:
            self._events.append(ev)
        logger.warning(
            "router-event %s", json.dumps(ev, sort_keys=True, default=str)
        )

    # -- public read surface --------------------------------------------

    def result(self, tenant: str) -> Dict[str, Any]:
        """One tenant's record: the router's cached terminal result when
        it has one, a live proxy to the owning worker otherwise."""
        with self._lock:
            rec = self._tenants.get(tenant)
            if rec is None:
                return {"tenant": tenant, "status": "unknown"}
            if rec.get("result") is not None:
                out = dict(rec["result"])
                out["tenant"] = tenant
                out["status"] = rec["status"]
                out["priority"] = rec["priority"]
                out["history"] = list(rec["history"])
                return out
            st = rec["status"]
            worker = rec.get("worker")
            out = {
                "tenant": tenant,
                "status": st,
                "priority": rec["priority"],
            }
            if "error" in rec:
                out["error"] = rec["error"]
            if "mem_refusal" in rec:
                out["mem_refusal"] = rec["mem_refusal"]
        if st == "forwarded" and worker:
            target = self._target(worker)
            doc = (
                self._fetch(f"{target.url}/result/{tenant}")
                if target is not None
                else None
            )
            if doc:
                doc = dict(doc)
                doc["worker"] = worker
                return doc
            out["worker"] = worker
        return out

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self._clock() if now is None else now
        fleet = self.collector.status(now=now)
        alerts = self._alerts_fast()
        with self._lock:
            rows: Dict[str, Dict[str, Any]] = {}
            for tid, rec in list(self._tenants.items())[-STATUS_TENANTS:]:
                row = {
                    "status": rec["status"],
                    "priority": rec["priority"],
                    "bucket": rec["akey"],
                }
                if rec.get("worker"):
                    row["worker"] = rec["worker"]
                res = rec.get("result") or {}
                for k in ("cost", "best_cost", "cycles", "queue_ms"):
                    if k in res:
                        row[k] = res[k]
                if "error" in rec:
                    row["error"] = rec["error"]
                rows[tid] = row
            counts: Dict[str, int] = {}
            for rec in self._tenants.values():
                counts[rec["status"]] = counts.get(rec["status"], 0) + 1
            out: Dict[str, Any] = {
                "status": "router",
                "state": self._state,
                "placement": {
                    "strategy": self.placement,
                    "buckets": dict(self._bucket_map),
                    "bucket_counts": dict(self._bucket_counts),
                },
                "admission": {"mode": (
                    "shedding" if alerts else "open"
                ), "alerts": alerts, **dict(self._counts)},
                "window": {
                    "base_ms": self.window_base_ms,
                    "factor": self._window_factor,
                },
                "tenants": rows,
                "tenant_counts": counts,
                "events": list(self._events)[-32:],
            }
        out["workers"] = fleet["workers"]
        out["workers_total"] = fleet["workers_total"]
        out["workers_up"] = fleet["workers_up"]
        out["fleet"] = fleet["fleet"]
        if self.fleet_slo is not None:
            out["slo"] = self.fleet_slo.status_block()
        if self.engine is not None:
            out["router_slo"] = self.engine.status_block()
        return out

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The /metrics.json document: the federated worker registry
        plus the fleet SLO series plus the router's OWN local series
        (``router.*``, its forward-objective ``slo.*``) re-labeled
        ``worker="router"`` so nothing collides with a worker series."""
        snap = self.collector.snapshot(now=now)
        if self.fleet_slo is not None:
            snap["metrics"].update(self.fleet_slo.metrics_block())
        local = metrics_registry.snapshot().get("metrics", {})
        for name, m in sorted(local.items()):
            dst = snap["metrics"].setdefault(
                name,
                {"kind": m.get("kind"), "help": m.get("help", ""), "values": []},
            )
            if dst.get("kind") != m.get("kind"):
                continue
            for entry in m.get("values", []):
                labels = dict(entry.get("labels") or {})
                labels["worker"] = "router"
                dst["values"].append(
                    {"labels": labels, "value": entry.get("value")}
                )
        return snap

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Spawn the background control loop (idempotent)."""
        self._stop.clear()
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="router-loop", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("router tick failed")
            self._stop.wait(self.interval_s)

    def stop_loop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def drain(self, timeout: float = 60.0) -> bool:
        """Graceful shutdown: stop admitting, flush every parked tenant,
        wait for the in-flight ones to reach a terminal state, record
        the ownership manifest.  True when nothing was left pending."""
        with self._lock:
            self._state = "draining"
        self._event(self._clock(), "drain-start")
        self.stop_loop()
        deadline = time.monotonic() + timeout
        pending = 0
        while time.monotonic() < deadline:
            try:
                self.tick()
                self._flush_deferred(self._clock(), force=True)
            except Exception:  # noqa: BLE001
                logger.exception("drain tick failed")
            with self._lock:
                pending = sum(
                    1
                    for rec in self._tenants.values()
                    if rec["status"]
                    in ("deferred", "forwarded", "failing-over")
                )
            if pending == 0:
                break
            time.sleep(min(self.interval_s, 0.25))
        ok = pending == 0
        with self._lock:
            self._state = "drained" if ok else "drain-timeout"
        self._event(self._clock(), "drain-done", drained=ok, pending=pending)
        self._write_manifest()
        return ok

    def shutdown(self, drain: bool = True, timeout: float = 60.0) -> bool:
        ok = self.drain(timeout) if drain else True
        if not drain:
            self.stop_loop()
        self.collector.stop()
        if self.http is not None:
            self.http.shutdown()
        return ok

    def _write_manifest(self) -> None:
        """The router's durable ownership record (``kind: router``):
        every tenant's status, owner and transfer history — the graftucs
        idiom, so an operator can always answer 'who solved tenant X'."""
        if not self.state_dir:
            return
        from ..durability.manager import MANIFEST_FORMAT
        from ..utils.checkpoint import atomic_write_json

        with self._lock:
            tenants = {
                tid: {
                    "status": rec["status"],
                    "priority": rec["priority"],
                    "bucket": rec["akey"],
                    "worker": rec.get("worker"),
                    "history": list(rec["history"]),
                }
                for tid, rec in self._tenants.items()
            }
            doc = {
                "format": MANIFEST_FORMAT,
                "kind": "router",
                "wrote_unix_s": time.time(),
                "state": self._state,
                "placement": {
                    "strategy": self.placement,
                    "buckets": dict(self._bucket_map),
                },
                "admission": dict(self._counts),
                "tenants": tenants,
            }
        try:
            os.makedirs(self.state_dir, exist_ok=True)
            atomic_write_json(
                os.path.join(self.state_dir, "router-manifest.json"),
                doc, indent=2, sort_keys=True, default=str,
            )
        except OSError:
            logger.exception("router manifest write failed")

    # -- HTTP routes ----------------------------------------------------

    def _http_solve(self, path: str, body: bytes):
        spec = json.loads(body.decode("utf-8"))
        code, payload, headers = self.submit(spec)
        if headers:
            return code, payload, headers
        return code, payload

    def _http_result(self, path: str, body: bytes):
        tenant = path.rsplit("/", 1)[-1]
        rec = self.result(tenant)
        return (404 if rec.get("status") == "unknown" else 200), rec

    def _http_healthz(self, path: str, body: bytes):
        with self._lock:
            state = self._state
        return (200 if state == "serving" else 503), {"state": state}

    def _http_fleet_status(self, path: str, body: bytes):
        return 200, self.status()

    def _http_fleet_slo(self, path: str, body: bytes):
        return 200, self.fleet_slo.status_block()

    def _http_slo(self, path: str, body: bytes):
        return 200, self.engine.report()

    def _http_shutdown(self, path: str, body: bytes):
        threading.Thread(
            target=self.shutdown, kwargs={"drain": True}, daemon=True
        ).start()
        return 200, {"state": "draining"}
