"""The vmapped batch engine: K tenant solves as ONE device dispatch.

``algorithms.base._fused_core`` is the whole solve (noise, init, every
cycle, anytime-best, convergence early-exit) as a pure traced function;
this module maps it over a leading instance axis with ``jax.vmap`` so a
stacked ``DeviceDCOP`` pytree — one bucket's worth of tenants — runs as
one compiled program and one packed readback.  Per-tenant PRNG keys,
noise levels, cycle budgets (``n_limit``) and real row counts
(``n_real``) are traced operands, so a warm bucket never recompiles.

Bit-identity contract (pinned in tests/test_algorithms.py): a batch of K
instances produces assignments, costs and cycle counts BITWISE equal to
the K sequential solves of :func:`solve_one` — the same plan, the same
bucket padding, the same noise draw shape, run through the regular
``run_cycles`` fused path one at a time.  vmap turns the masked scan's
``lax.cond`` into a select, which executes both branches but selects the
identical values, so trajectories cannot diverge.

Algorithm support: any module in ``pydcop_tpu.algorithms`` exporting
``batch_plan(compiled, dev, params) -> BatchPlan`` and
``bucket_extra(compiled, params)`` (dsa, mgm, mgm2, maxsum today).
Batch sizes are rounded up to a power of two — pad instances replicate
the last tenant with a zero cycle budget, so executables are keyed by
K's power-of-two class, not by K.
"""

from __future__ import annotations

import logging
import time
from functools import lru_cache, partial
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

logger = logging.getLogger("pydcop_tpu.serve.batch")

from ..algorithms import SolveResult, load_algorithm_module
from ..telemetry.metrics import metrics_registry
from ..telemetry.profiling import profiled_jit
from ..telemetry.pulse import HEALTH_WIDTH, pulse
from ..telemetry.tracing import tracer
from .bucket import BucketDims, bucket_dims_of, pad_dev_to_bucket, pow2

__all__ = [
    "BatchPlan",
    "BucketKey",
    "ServeUnsupported",
    "SolveRequest",
    "TenantResult",
    "bucket_key",
    "build_instance",
    "solve_batched",
    "solve_one",
]


class ServeUnsupported(ValueError):
    """The algorithm/problem combination has no batch plan (e.g. maxsum
    over non-binary constraints).  Callers fall back to a sequential
    solve or reject the request; this never crashes a co-batched
    tenant."""


class BatchPlan(NamedTuple):
    """Everything the engine needs to run one instance of a solve —
    static callables MUST be stable objects (module-level / lru-cached
    factories) shared by every instance of a bucket, per-instance arrays
    ride in ``consts`` (padded to the bucket's shapes)."""

    init: Callable
    step: Callable
    extract: Callable
    consts: Tuple
    convergence: Optional[Callable]
    same_count: int
    noise: float  # tie-breaking noise level (traced operand)
    return_final: bool
    health: Optional[Callable]
    #: per-cycle message model: (count, bytes) — the reference-parity
    #: msg accounting finalize() reports
    msg_per_cycle: Tuple[int, int]
    #: stop_cycle-style override of the requested cycle budget (0 = none)
    n_cycles_override: int = 0


class SolveRequest(NamedTuple):
    """One tenant's solve."""

    tenant: str
    compiled: Any  # CompiledDCOP
    algo: str
    params: Dict[str, Any]
    n_cycles: int = 100
    seed: int = 0


class TenantResult(NamedTuple):
    tenant: str
    result: SolveResult
    extras: Dict[str, Any]


class BucketKey(NamedTuple):
    """Full executable-sharing key: the shape bucket plus everything that
    becomes a jit static (algorithm + params select the step/init
    function objects; ``extra`` carries algorithm shape statics like the
    padded ELL span signature; ``n_pad`` is the scan-length bucket)."""

    algo: str
    params: Tuple[Tuple[str, Any], ...]
    dims: BucketDims
    extra: Tuple
    n_pad: int
    has_noise: bool


# -- serving metrics (module-level get-or-create, like base.py) ----------
_m_batches = metrics_registry.counter(
    "serve.batches", "vmapped batch dispatches"
)
_m_solves = metrics_registry.counter(
    "serve.solves", "tenant solves completed through the batch engine"
)
_m_batch_size = metrics_registry.histogram(
    "serve.batch_size", "real tenants per batch dispatch",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
)
_m_pad_instances = metrics_registry.counter(
    "serve.pad_instances",
    "replicated pad instances added to round batches to powers of two",
)


@lru_cache(maxsize=None)
def _algo_module(algo: str):
    mod = load_algorithm_module(algo)
    if not hasattr(mod, "batch_plan"):
        raise ServeUnsupported(
            f"algorithm {algo!r} has no batch_plan — serve it "
            "sequentially or add one (docs/serving.md)"
        )
    return mod


@lru_cache(maxsize=1024)
def _prepared_cached(algo: str, items: Tuple) -> Dict[str, Any]:
    from ..algorithms import prepare_algo_params

    return prepare_algo_params(dict(items), _algo_module(algo).algo_params)


def _prepared(mod, params: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    # hot per-request path (every bucket_key/_fused_key call): cache by
    # the raw items so a 32-tenant dispatch validates params 1x, not 64x
    return dict(
        _prepared_cached(
            mod.__name__.rsplit(".", 1)[-1],
            tuple(sorted((params or {}).items())),
        )
    )


def _scan_pad(n_cycles: int) -> int:
    # same power-of-two scan-length bucket as run_cycles' fused path
    return max(8, 1 << max(0, int(n_cycles) - 1).bit_length())


@lru_cache(maxsize=4096)
def _host_key(seed: int) -> np.ndarray:
    """Host copy of PRNGKey(seed) — batch key stacks stay off-device."""
    from ..algorithms.base import _cached_key

    return np.asarray(_cached_key(seed))


def _effective_cycles(plan: BatchPlan, n_cycles: int) -> int:
    return plan.n_cycles_override or int(n_cycles)


def bucket_key(req: SolveRequest) -> BucketKey:
    """The executable-sharing key of one request.  Two requests with equal
    keys are co-batchable AND share the compiled program; two requests
    with different keys simply land in different buckets — correctness
    never depends on a key collision."""
    mod = _algo_module(req.algo)
    params = _prepared(mod, req.params)
    dims = bucket_dims_of(req.compiled)
    extra = tuple(mod.bucket_extra(req.compiled, params))
    n_cycles = int(params.get("stop_cycle") or req.n_cycles)
    return BucketKey(
        algo=req.algo,
        params=tuple(sorted(params.items())),
        dims=dims,
        extra=extra,
        n_pad=_scan_pad(n_cycles),
        has_noise=bool(float(params.get("noise", 0.0) or 0.0)),
    )


def build_instance(req: SolveRequest, dims: BucketDims):
    """(bucket-padded DeviceDCOP, BatchPlan, host dev leaves, host
    consts) for one request, cached on the compiled problem so warm
    tenants upload nothing and the batch path stacks straight from host
    memory (pulling leaves back off the device per dispatch was the
    single largest host cost of a small-problem batch)."""
    import jax

    from ..algorithms.base import cached_const
    from ..compile.kernels import to_device

    mod = _algo_module(req.algo)
    params = _prepared(mod, req.params)

    def build():
        dev = pad_dev_to_bucket(to_device(req.compiled), dims)
        plan = mod.batch_plan(req.compiled, dev, params)
        host_dev = jax.tree_util.tree_map(np.asarray, dev)
        host_consts = tuple(np.asarray(c) for c in plan.consts)
        return dev, plan, host_dev, host_consts

    return cached_const(
        req.compiled,
        ("serve_instance", req.algo, dims, tuple(sorted(params.items()))),
        build,
    )


def solve_one(req: SolveRequest) -> TenantResult:
    """Sequential reference solve through the SAME bucket padding, plan
    and noise draw shape the batch path uses — the bit-identity baseline,
    and the serving layer's fallback for unbatchable requests."""
    from ..algorithms.base import finalize, run_cycles

    dims = bucket_dims_of(req.compiled)
    dev, plan, _host_dev, _host_consts = build_instance(req, dims)
    n_cycles = _effective_cycles(plan, req.n_cycles)
    values, curve, extras = run_cycles(
        req.compiled,
        plan.init,
        plan.step,
        plan.extract,
        n_cycles=n_cycles,
        seed=req.seed,
        dev=dev,
        consts=plan.consts,
        noise=plan.noise,
        convergence=plan.convergence,
        same_count=plan.same_count,
        return_final=plan.return_final,
        health=plan.health,
        noise_draw=dims.n_vars,
    )
    cycles = extras["cycles"]
    mc, ms = plan.msg_per_cycle
    result = finalize(
        req.compiled, values, cycles, mc * cycles, ms * cycles, curve,
        status="TIMEOUT" if extras["timed_out"] else "FINISHED",
    )
    return TenantResult(req.tenant, result, extras)


# graftflow: batchable
@partial(
    profiled_jit,
    name="serve._solve_batch",
    static_argnames=(
        "init", "step", "extract", "convergence", "n_pad", "same_count",
        "has_noise", "health", "n_draw",
    ),
)
def _solve_fused_batch(
    devs,
    keys,
    consts,
    n_limits,
    noises,
    n_reals,
    init: Callable,
    step: Callable,
    extract: Callable,
    convergence: Optional[Callable],
    n_pad: int,
    same_count: int,
    has_noise: bool,
    health: Optional[Callable],
    n_draw: int,
):
    """K whole solves as ONE dispatch: ``jax.vmap`` over the leading
    instance axis of the stacked DeviceDCOP, keys, consts and the traced
    per-instance scalars, everything host-bound packed into one byte
    array for exactly one readback (the batched analogue of
    ``_solve_fused``'s pack; section order
    ``[values | best_cost | cycles | best_cycle | health? | flips?]``,
    every per-instance section int32/float32 so the host can size them
    without device metadata)."""
    import jax
    import jax.numpy as jnp

    from ..algorithms.base import _as_bytes, _fused_core, _pack_layout

    def one(dev, key, c, n_limit, noise, n_real):
        return _fused_core(
            dev, key, c, n_limit, noise, n_real, init, step, extract,
            convergence, n_pad, same_count, False, has_noise, health,
            n_draw,
        )

    (
        _state, final_vals, best_vals, best_cost, best_cycle, cycles,
        _curve, pc, health_rows,
    ) = jax.vmap(one)(devs, keys, consts, n_limits, noises, n_reals)
    vals_dtype, scal_dtype, _ = _pack_layout(devs.max_domain, n_pad)
    packed_vals = jnp.stack([final_vals, best_vals], axis=1).astype(
        vals_dtype
    )  # [K, 2, n_vars]
    parts = [
        _as_bytes(packed_vals),
        _as_bytes(best_cost.astype(scal_dtype)),
        _as_bytes(cycles.astype(jnp.int32)),
        _as_bytes(best_cycle.astype(jnp.int32)),
    ]
    if health is not None:
        parts.append(_as_bytes(health_rows.astype(jnp.float32)))
        parts.append(_as_bytes(pc.flips))
    return jnp.concatenate(parts)


def _unpack_batch(
    buf: np.ndarray,
    k: int,
    n_vars: int,
    n_pad: int,
    max_domain: int,
    with_health: bool,
):
    """Host decode of the batched packed readback (the vectorized twin of
    run_cycles' sequential decode; same fail-loud layout check)."""
    from ..algorithms.base import _pack_layout

    vals_j, scal_j, _ = _pack_layout(max_domain, n_pad)
    vals_np, scal_np = np.dtype(vals_j), np.dtype(scal_j)
    vals_nbytes = k * 2 * n_vars * vals_np.itemsize
    scal_nbytes = k * scal_np.itemsize
    pulse_nbytes = (
        k * (n_pad * HEALTH_WIDTH + n_vars) * 4 if with_health else 0
    )
    expect = vals_nbytes + scal_nbytes + 2 * 4 * k + pulse_nbytes
    if buf.size != expect:
        raise AssertionError(
            f"batched readback layout drift: {buf.size} bytes total, "
            f"expected {expect} for k={k}, n_vars={n_vars}, n_pad={n_pad}"
        )
    final_plane, best_plane = np.swapaxes(
        buf[:vals_nbytes].view(vals_np).reshape(k, 2, n_vars), 0, 1
    ).astype(np.int32)
    off = vals_nbytes
    best_cost = buf[off:off + scal_nbytes].view(scal_np).copy()
    off += scal_nbytes
    cycles = buf[off:off + 4 * k].view(np.int32).copy()
    off += 4 * k
    best_cycle = buf[off:off + 4 * k].view(np.int32).copy()
    off += 4 * k
    health = flips = None
    if with_health:
        hb = k * n_pad * HEALTH_WIDTH * 4
        health = (
            buf[off:off + hb].view(np.float32)
            .reshape(k, n_pad, HEALTH_WIDTH).copy()
        )
        off += hb
        flips = buf[off:].view(np.int32).reshape(k, n_vars).copy()
    return final_plane, best_plane, best_cost, cycles, best_cycle, health, flips


def _jit_compiles_total() -> float:
    """Current sum of the ``compile.jit_compiles`` counter — the
    before/after delta around a batch dispatch is how a cold-compile
    stall gets ATTRIBUTED to the batch (and tenants) that paid it
    (graftslo request tracing; only read when telemetry is on)."""
    m = metrics_registry.get("compile.jit_compiles")
    if m is None:
        return 0.0
    return sum(v["value"] for v in m.snapshot()["values"])


def _dispatch_group(
    key: BucketKey,
    reqs: List[SolveRequest],
    observer: Optional[Callable[[Dict[str, Any]], None]] = None,
) -> List[TenantResult]:
    """Solve one bucket's worth of requests as a single vmapped dispatch.

    ``observer`` (the serve loop's request-lifecycle instrumentation)
    receives one event per dispatched group with the phase boundary
    timestamps (assemble / dispatch / device-solve / readback), the
    batch occupancy and the fresh-compile count; when it is None and
    telemetry is off, the dispatch path is byte-identical to the
    uninstrumented one (flag checks only)."""
    import jax
    import jax.numpy as jnp

    from ..algorithms.base import (
        _phase_of,
        _record_readback,
        _record_window,
        finalize,
        to_host,
    )

    t_start = time.perf_counter() if observer else 0.0
    instances = [build_instance(r, key.dims) for r in reqs]
    plan0 = instances[0][1]
    for _, plan, _h, _hc in instances[1:]:
        if plan.step is not plan0.step or plan.init is not plan0.init:
            # same BucketKey must mean same statics: a drift here would
            # silently retrace per instance instead of batching
            raise AssertionError(
                "bucket key collision with mismatched plan statics"
            )
    k_real = len(reqs)
    k_pad = pow2(k_real)
    # pad instances replicate the last tenant with a zero cycle budget —
    # the masked scan never steps them, their results are discarded
    pad_n = k_pad - k_real
    devs_list = [h for _, _, h, _ in instances] + (
        [instances[-1][2]] * pad_n
    )
    consts_list = [hc for _, _, _, hc in instances] + (
        [instances[-1][3]] * pad_n
    )
    budgets = [
        _effective_cycles(plan, r.n_cycles)
        for r, (_, plan, _h, _hc) in zip(reqs, instances)
    ] + [0] * pad_n
    seeds = [r.seed for r in reqs] + [reqs[-1].seed] * pad_n
    n_reals = [r.compiled.n_vars for r in reqs] + [
        reqs[-1].compiled.n_vars
    ] * pad_n
    noises = [
        float(p.noise or 0.0) for _, p, _h, _hc in instances
    ] + [0.0] * pad_n

    def stack(*xs):
        # np.stack over the cached HOST leaves + one upload per leaf:
        # an eager jnp.stack of K device arrays costs one dispatch per
        # leaf per call and was the single largest host cost of a
        # small-problem batch
        return jnp.asarray(np.stack(xs))

    devs = jax.tree_util.tree_map(stack, *devs_list)
    consts = tuple(
        stack(*parts) for parts in zip(*consts_list)
    ) if consts_list[0] else ()
    keys = jnp.asarray(np.stack([_host_key(int(s)) for s in seeds]))
    hook = (
        plan0.health
        if (plan0.health is not None and pulse.enabled) else None
    )
    telem = tracer.enabled or metrics_registry.enabled
    phase = _phase_of(plan0.step) if telem else "serve"
    compiles_before = (
        _jit_compiles_total()
        if observer and metrics_registry.enabled else 0.0
    )
    t0 = time.perf_counter() if telem or observer else 0.0
    packed = _solve_fused_batch(
        devs,
        keys,
        consts,
        jnp.asarray(budgets, jnp.int32),
        jnp.asarray(noises, jnp.float32),
        jnp.asarray(n_reals, jnp.int32),
        plan0.init,
        plan0.step,
        plan0.extract,
        plan0.convergence,
        key.n_pad,
        plan0.same_count,
        key.has_noise,
        hook,
        key.dims.n_vars,
    )
    t_rb = time.perf_counter() if telem or observer else 0.0
    t_solved = 0.0
    if observer:
        # split device execution from the host copy: the jit call above
        # returned an async future, so t_rb is dispatch-done, not
        # solve-done.  The extra sync costs nothing — to_host would
        # block on the same completion anyway.
        jax.block_until_ready(packed)
        t_solved = time.perf_counter()
    buf = to_host(packed)
    t_end = time.perf_counter() if telem or observer else 0.0
    (
        final_plane, best_plane, best_cost, cycles, best_cycle, health,
        flips,
    ) = _unpack_batch(
        buf, k_pad, key.dims.n_vars, key.n_pad, key.dims.max_domain,
        hook is not None,
    )
    if telem:
        _record_readback(int(buf.nbytes), t_rb, t_end)
        _record_window(
            "batch", phase, 0, int(cycles[:k_real].sum()), t0, t_end
        )
        _m_batches.inc()
        _m_solves.inc(k_real)
        _m_batch_size.observe(float(k_real))
        if pad_n:
            _m_pad_instances.inc(pad_n)
    if observer:
        fresh = (
            _jit_compiles_total() - compiles_before
            if metrics_registry.enabled else 0.0
        )
        observer(
            {
                "kind": "vmap",
                "bucket": key,
                "tenants": [r.tenant for r in reqs],
                "k_real": k_real,
                "k_pad": k_pad,
                "t_start": t_start,
                "t_assembled": t0,
                "t_dispatched": t_rb,
                "t_solved": t_solved,
                "t_done": time.perf_counter(),
                "fresh_compiles": int(fresh),
            }
        )
    out: List[TenantResult] = []
    for i, (req, (_, plan, _h, _hc)) in enumerate(zip(reqs, instances)):
        values = final_plane[i] if plan.return_final else best_plane[i]
        cyc = int(cycles[i])
        mc, ms = plan.msg_per_cycle
        result = finalize(
            req.compiled, values, cyc, mc * cyc, ms * cyc, None,
            status="FINISHED",
        )
        extras: Dict[str, Any] = {
            "best_values": best_plane[i],
            "best_cost": float(best_cost[i]),
            "cycles": cyc,
            "cycles_to_best": int(best_cycle[i]),
            "timed_out": False,
            "bucket": key,
            "batch_size": k_real,
        }
        if hook is not None:
            extras["pulse"] = {
                "health": health[i][:cyc],
                "flip_count": flips[i][:req.compiled.n_vars],
            }
        out.append(TenantResult(req.tenant, result, extras))
    return out


# -- fleet fusion (mode="fused"): K problems as ONE union solve ----------

#: (parts, union, blocks, dev, plan) per batch composition, keyed by the
#: tenants' compiled-object identities — warm resubmissions (bench
#: loops, periodic tenants) skip the union rebuild and re-upload.  The
#: cached value HOLDS the parts list on purpose: the id() keys are only
#: valid while the compiled objects are alive, so the cache must keep
#: them alive itself (a GC'd-and-reused address would otherwise serve a
#: stale union for a fresh problem)
_union_cache: "Dict[Tuple, Tuple]" = {}
_UNION_CACHE_CAP = 32


def _fused_key(req: SolveRequest):
    mod = _algo_module(req.algo)
    params = _prepared(mod, req.params)
    n_cycles = int(params.get("stop_cycle") or req.n_cycles)
    return (
        req.algo,
        tuple(sorted(params.items())),
        req.compiled.max_domain,
        np.dtype(req.compiled.float_dtype).name,
        req.compiled.objective,
        # budget class: a fused group runs to its LARGEST member budget,
        # so grouping by the power-of-two class bounds the inflation a
        # small-budget tenant can see to <2x (see _dispatch_fused)
        _scan_pad(n_cycles),
    )


def _dispatch_fused(
    reqs: List[SolveRequest],
    observer: Optional[Callable[[Dict[str, Any]], None]] = None,
) -> List[TenantResult]:
    """One union solve for a fused group (see serve/union.py): the K
    problems concatenate block-diagonally and run the ordinary
    sequential fused path at K x the size — every kernel in its
    efficient unbatched form.  Per-tenant results are exact (sliced and
    re-costed through each tenant's own compiled problem); trajectories
    are NOT seed-reproducible against solo runs (one fleet key), and the
    group runs to its LARGEST member budget — a tenant may receive (and
    its ``cycles`` truthfully reports) up to 2x its requested cycles
    (the fused grouping key includes the power-of-two budget class)."""
    from ..algorithms.base import finalize, run_cycles
    from ..compile.kernels import to_device
    from .union import fleet_seed, union_compiled

    mod = _algo_module(reqs[0].algo)
    params = _prepared(mod, reqs[0].params)
    t_start = time.perf_counter() if observer else 0.0
    compiles_before = (
        _jit_compiles_total()
        if observer and metrics_registry.enabled else 0.0
    )
    parts = [r.compiled for r in reqs]
    cache_key = (_fused_key(reqs[0]), tuple(id(c) for c in parts))
    hit = _union_cache.pop(cache_key, None)
    if hit is None:
        union, blocks = union_compiled(parts)
        dev = to_device(union)
        plan = mod.batch_plan(union, dev, params)
        # `parts` rides in the entry to pin the id() keys (see above)
        hit = (parts, union, blocks, dev, plan)
    _union_cache[cache_key] = hit  # re-insert: LRU order
    while len(_union_cache) > _UNION_CACHE_CAP:
        _union_cache.pop(next(iter(_union_cache)))
    _parts, union, blocks, dev, plan = hit
    t_assembled = time.perf_counter() if observer else 0.0
    n_cycles = max(
        _effective_cycles(plan, r.n_cycles) for r in reqs
    )
    values, _curve, extras = run_cycles(
        union,
        plan.init,
        plan.step,
        plan.extract,
        n_cycles=n_cycles,
        seed=fleet_seed([r.seed for r in reqs]),
        dev=dev,
        consts=plan.consts,
        noise=plan.noise,
        convergence=plan.convergence,
        same_count=plan.same_count,
        return_final=True,
        health=None,  # union-global health rows are not per-tenant
    )
    best = np.asarray(extras["best_values"])
    final = np.asarray(values)
    cycles = extras["cycles"]
    out: List[TenantResult] = []
    for req, (lo, hi) in zip(reqs, blocks):
        # each tenant's own message model (the union plan's would split
        # the fleet total evenly, misreporting unequal tenants)
        mc, ms = mod.msg_per_cycle(req.compiled)
        res_final = finalize(
            req.compiled, final[lo:hi], cycles, mc * cycles,
            ms * cycles, None, status="FINISHED",
        )
        result = res_final
        if not plan.return_final and not np.array_equal(
            final[lo:hi], best[lo:hi]
        ):
            # anytime semantics per tenant: the union-best slice can beat
            # the final slice (and vice versa — the union best is global)
            res_best = finalize(
                req.compiled, best[lo:hi], cycles, mc * cycles,
                ms * cycles, None, status="FINISHED",
            )
            if res_best.cost < res_final.cost:
                result = res_best
        out.append(
            TenantResult(
                req.tenant,
                result,
                {
                    "best_cost": result.cost,
                    "cycles": cycles,
                    "cycles_to_best": extras.get("cycles_to_best"),
                    "timed_out": extras.get("timed_out", False),
                    "batch_size": len(reqs),
                    "mode": "fused",
                },
            )
        )
    if metrics_registry.enabled:
        _m_batches.inc()
        _m_solves.inc(len(reqs))
        _m_batch_size.observe(float(len(reqs)))
    if observer:
        fresh = (
            _jit_compiles_total() - compiles_before
            if metrics_registry.enabled else 0.0
        )
        t_done = time.perf_counter()
        observer(
            {
                "kind": "fused",
                "bucket": f"fused/{reqs[0].algo}",
                "tenants": [r.tenant for r in reqs],
                "k_real": len(reqs),
                "k_pad": len(reqs),
                "t_start": t_start,
                "t_assembled": t_assembled,
                # the union solve is synchronous through run_cycles:
                # dispatch/device-solve/readback collapse into one
                # segment the observer reports as the solve phase
                "t_dispatched": t_assembled,
                "t_solved": t_done,
                "t_done": t_done,
                "fresh_compiles": int(fresh),
            }
        )
    return out


def solve_batched(
    requests: List[SolveRequest],
    max_batch: Optional[int] = None,
    mode: str = "vmap",
    observer: Optional[Callable[[Dict[str, Any]], None]] = None,
) -> Dict[str, TenantResult]:
    """Solve many tenants, one device dispatch per group.

    ``mode="vmap"`` (default): requests group by :func:`bucket_key` and
    each bucket dispatches as one ``jax.vmap`` batch — every tenant's
    trajectory is BITWISE the solo :func:`solve_one` trajectory, and
    warm buckets share one executable.

    ``mode="fused"``: requests group by (algo, params, domain, dtype)
    and each group solves as ONE block-diagonal union problem
    (serve/union.py) — maximal throughput on serial backends, same
    per-variable randomness distribution, but trajectories are not
    seed-reproducible against solo runs.

    Either way, a group whose batch dispatch fails degrades to
    per-tenant sequential solves, and a tenant that still fails is
    returned with a ``None`` result and the error in its extras — one
    bad tenant never takes down the co-batched rest."""
    if mode not in ("vmap", "fused"):
        raise ValueError(f"unknown serve batch mode {mode!r}")
    groups: Dict[Any, List[SolveRequest]] = {}
    order: List[Any] = []
    out: Dict[str, TenantResult] = {}
    for req in requests:
        try:
            key = (
                bucket_key(req) if mode == "vmap" else _fused_key(req)
            )
        except (ServeUnsupported, ValueError, TypeError) as exc:
            # TypeError covers unhashable param values hitting the key
            # caches — one malformed tenant must fail alone, never the
            # whole call
            out[req.tenant] = _failed(req, exc)
            continue
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(req)
    for key in order:
        reqs = groups[key]
        cap = max_batch or len(reqs)
        for lo in range(0, len(reqs), cap):
            chunk = reqs[lo:lo + cap]
            try:
                if mode == "vmap":
                    results = _dispatch_group(key, chunk, observer)
                else:
                    results = _dispatch_fused(chunk, observer)
                for tr in results:
                    out[tr.tenant] = tr
            except ServeUnsupported as exc:
                for req in chunk:
                    out[req.tenant] = _failed(req, exc)
            except Exception:
                # batch-level failure: isolate per tenant so one poisoned
                # instance cannot sink its co-batched neighbors.  LOUD:
                # the per-tenant results are still correct, so a silent
                # fallback would hide an engine bug behind identical
                # answers at sequential throughput
                logger.exception(
                    "batch dispatch failed for %d tenant(s) in mode=%s; "
                    "degrading to sequential solves", len(chunk), mode,
                )
                for req in chunk:
                    try:
                        out[req.tenant] = solve_one(req)
                    except Exception as exc:  # noqa: BLE001
                        out[req.tenant] = _failed(req, exc)
    return out


def _failed(req: SolveRequest, exc: Exception) -> TenantResult:
    return TenantResult(
        req.tenant, None,
        {"error": f"{type(exc).__name__}: {exc}", "timed_out": False},
    )
