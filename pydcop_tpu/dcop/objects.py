"""DCOP model objects: domains, variables, agents.

Role parity with /root/reference/pydcop/dcop/objects.py (Domain:46,
Variable:175, BinaryVariable:335, VariableWithCostDict:410,
VariableWithCostFunc:464, VariableNoisyCostFunc:547, ExternalVariable:618,
AgentDef:669, create_variables:258, create_agents:879).

TPU-first notes: these are host-side, immutable *definitions*.  The solver
never touches them in its hot path — `pydcop_tpu.compile` lowers them once to
index arrays and padded cost tables.  Unary costs are therefore represented so
they can be tabulated over the whole domain in one shot (`cost_vector`).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

from ..utils.expressions import ExpressionFunction
from ..utils.simple_repr import SimpleRepr

__all__ = [
    "Domain",
    "VariableDomain",
    "binary_domain",
    "Variable",
    "BinaryVariable",
    "VariableWithCostDict",
    "VariableWithCostFunc",
    "VariableNoisyCostFunc",
    "ExternalVariable",
    "AgentDef",
    "create_variables",
    "create_binary_variables",
    "create_agents",
]


class Domain(SimpleRepr):
    """A named, ordered, finite set of values.

    >>> d = Domain('colors', 'color', ['R', 'G', 'B'])
    >>> len(d), d.index('G'), d[2]
    (3, 1, 'B')
    """

    _repr_fields = ("name", "domain_type", "values")

    def __init__(self, name: str, domain_type: str, values: Iterable) -> None:
        self._name = name
        self._domain_type = domain_type
        self._values = tuple(values)
        self._index = {v: i for i, v in enumerate(self._values)}

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._domain_type

    @property
    def domain_type(self) -> str:
        return self._domain_type

    @property
    def values(self) -> Tuple:
        return self._values

    def index(self, value) -> int:
        try:
            return self._index[value]
        except KeyError:
            raise ValueError(f"{value!r} is not in domain {self._name}")

    def to_domain_value(self, token: str):
        """Map a string token (e.g. from YAML extensional tables) back to the
        typed domain value."""
        for v in self._values:
            if v == token or str(v) == str(token):
                return v
        raise ValueError(f"{token!r} does not match any value of {self._name}")

    def __iter__(self):
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __getitem__(self, i: int):
        return self._values[i]

    def __contains__(self, v) -> bool:
        return v in self._index

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Domain)
            and other.name == self.name
            and other.values == self.values
            and other.type == self.type
        )

    def __hash__(self) -> int:
        return hash((self._name, self._domain_type, self._values))

    def __repr__(self) -> str:
        return f"Domain({self._name}, {self._domain_type}, {self._values})"


# Alias kept for familiarity with the reference API.
VariableDomain = Domain


def binary_domain(name: str = "binary") -> Domain:
    return Domain(name, "binary", (0, 1))


class Variable(SimpleRepr):
    """A decision variable with a domain and optional initial value."""

    _repr_fields = ("name", "domain", "initial_value")

    def __init__(
        self,
        name: str,
        domain: Union[Domain, Iterable],
        initial_value: Any = None,
    ) -> None:
        self._name = name
        if not isinstance(domain, Domain):
            domain = Domain(f"d_{name}", "unknown", tuple(domain))
        self._domain = domain
        if initial_value is not None and initial_value not in domain:
            raise ValueError(
                f"initial value {initial_value!r} not in domain of {name}"
            )
        self._initial_value = initial_value

    @property
    def name(self) -> str:
        return self._name

    @property
    def domain(self) -> Domain:
        return self._domain

    @property
    def initial_value(self):
        return self._initial_value

    @property
    def has_cost(self) -> bool:
        return False

    def cost_for_val(self, val) -> float:
        return 0.0

    def cost_vector(self) -> List[float]:
        """Unary cost for every domain value, in domain order (compile-time
        tabulation target)."""
        return [self.cost_for_val(v) for v in self._domain]

    def clone(self) -> "Variable":
        return Variable(self._name, self._domain, self._initial_value)

    def __eq__(self, other) -> bool:
        return (
            type(other) is type(self)
            and other.name == self.name
            and other.domain == self.domain
            and other.initial_value == self.initial_value
            # unary costs are part of the variable's identity: two defs of the
            # same variable with different costs must NOT compare equal, or
            # DCOP.add_variable's redefinition guard would silently keep one
            and other.cost_vector() == self.cost_vector()
        )

    def __hash__(self) -> int:
        # initial_value is part of identity, like the reference
        # (tests/unit/test_dcop_variables.py:153); eq already compares it
        return hash(
            (type(self).__name__, self._name, self._domain,
             self._initial_value)
        )

    def __repr__(self) -> str:
        return f"Variable({self._name}, {self._domain.name})"


class BinaryVariable(Variable):
    """A 0/1 variable (used by the repair DCOPs, reference objects.py:335)."""

    def __init__(self, name: str, initial_value: int = 0) -> None:
        super().__init__(name, binary_domain(), initial_value)

    def clone(self) -> "BinaryVariable":
        return BinaryVariable(self._name, self._initial_value)

    @classmethod
    def _from_repr(cls, name, domain=None, initial_value=0):
        return cls(name, initial_value if initial_value is not None else 0)


class VariableWithCostDict(Variable):
    """Variable with a per-value unary cost given as a dict."""

    _repr_fields = ("name", "domain", "costs", "initial_value")

    def __init__(
        self,
        name: str,
        domain: Union[Domain, Iterable],
        costs: Dict[Any, float],
        initial_value: Any = None,
    ) -> None:
        super().__init__(name, domain, initial_value)
        self._costs = dict(costs)

    @property
    def costs(self) -> Dict[Any, float]:
        return dict(self._costs)

    @property
    def has_cost(self) -> bool:
        return True

    def cost_for_val(self, val) -> float:
        return float(self._costs.get(val, 0.0))

    def clone(self) -> "VariableWithCostDict":
        return VariableWithCostDict(
            self._name, self._domain, self._costs, self._initial_value
        )


class VariableWithCostFunc(Variable):
    """Variable whose unary cost is a function (or expression) of its value."""

    def __init__(
        self,
        name: str,
        domain: Union[Domain, Iterable],
        cost_func: Union[Callable, ExpressionFunction],
        initial_value: Any = None,
    ) -> None:
        super().__init__(name, domain, initial_value)
        if isinstance(cost_func, ExpressionFunction):
            if cost_func.variable_names != frozenset({name}):
                raise ValueError(
                    f"cost function of {name} must depend only on {name}, "
                    f"got {set(cost_func.variable_names)}"
                )
        self._cost_func = cost_func

    @property
    def cost_func(self):
        return self._cost_func

    @property
    def has_cost(self) -> bool:
        return True

    def cost_for_val(self, val) -> float:
        if isinstance(self._cost_func, ExpressionFunction):
            return float(self._cost_func(**{self._name: val}))
        return float(self._cost_func(val))

    def clone(self) -> "VariableWithCostFunc":
        return VariableWithCostFunc(
            self._name, self._domain, self._cost_func, self._initial_value
        )

    def _simple_repr(self):
        r = {
            "__qualname__": type(self).__qualname__,
            "__module__": type(self).__module__,
            "name": self._name,
            "domain": self._domain._simple_repr(),
            "initial_value": self._initial_value,
        }
        if isinstance(self._cost_func, ExpressionFunction):
            r["cost_func"] = self._cost_func.expression
        else:
            raise TypeError(
                "only expression-based cost functions are serializable"
            )
        return r

    @classmethod
    def _from_repr(cls, name, domain, cost_func, initial_value=None):
        from ..utils.simple_repr import from_repr as _fr

        return cls(name, _fr(domain), ExpressionFunction(cost_func), initial_value)


class VariableNoisyCostFunc(VariableWithCostFunc):
    """Cost-function variable with bounded uniform noise added per value.

    Mirrors the reference's noise semantics (objects.py:547): at construction a
    noise sample in [0, noise_level) is drawn per domain value and added to the
    cost.  Unlike the reference we accept an explicit ``seed`` so runs are
    reproducible.
    """

    def __init__(
        self,
        name: str,
        domain: Union[Domain, Iterable],
        cost_func: Union[Callable, ExpressionFunction],
        initial_value: Any = None,
        noise_level: float = 0.02,
        seed: Optional[int] = None,
    ) -> None:
        super().__init__(name, domain, cost_func, initial_value)
        self._noise_level = noise_level
        # default seed must be stable across processes (hash() is randomized)
        import zlib

        self._seed = (
            seed if seed is not None else zlib.crc32(name.encode()) & 0xFFFF
        )
        rng = random.Random(self._seed)
        self._noise = {v: rng.uniform(0, noise_level) for v in self._domain}

    @property
    def noise_level(self) -> float:
        return self._noise_level

    def cost_for_val(self, val) -> float:
        return super().cost_for_val(val) + self._noise[val]

    def clone(self) -> "VariableNoisyCostFunc":
        c = VariableNoisyCostFunc(
            self._name,
            self._domain,
            self._cost_func,
            self._initial_value,
            self._noise_level,
            seed=self._seed,
        )
        c._noise = dict(self._noise)
        return c

    def _simple_repr(self):
        r = super()._simple_repr()
        r["__qualname__"] = type(self).__qualname__
        r["noise_level"] = self._noise_level
        r["seed"] = self._seed
        return r

    @classmethod
    def _from_repr(
        cls, name, domain, cost_func, initial_value=None, noise_level=0.02, seed=None
    ):
        from ..utils.simple_repr import from_repr as _fr

        return cls(
            name,
            _fr(domain),
            ExpressionFunction(cost_func),
            initial_value,
            noise_level=noise_level,
            seed=seed,
        )


class ExternalVariable(Variable):
    """A read-only input variable (sensor); supports value-change callbacks
    (reference objects.py:618-664)."""

    def __init__(
        self,
        name: str,
        domain: Union[Domain, Iterable],
        value: Any = None,
    ) -> None:
        super().__init__(name, domain, value)
        self._value = value if value is not None else self._domain[0]
        self._subscribers: List[Callable[[Any], None]] = []

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        if v == self._value:
            return
        if v not in self._domain:
            raise ValueError(f"{v!r} not in domain of external var {self._name}")
        self._value = v
        for cb in self._subscribers:
            cb(v)

    def subscribe(self, callback: Callable[[Any], None]) -> None:
        self._subscribers.append(callback)

    def unsubscribe(self, callback: Callable[[Any], None]) -> None:
        self._subscribers.remove(callback)

    def clone(self) -> "ExternalVariable":
        return ExternalVariable(self._name, self._domain, self._value)


def _name_range(name_or_indexes) -> List[str]:
    if isinstance(name_or_indexes, str):
        return [name_or_indexes]
    return [str(i) for i in name_or_indexes]


def create_variables(
    prefix: str,
    indexes,
    domain: Domain,
    separator: str = "_",
) -> Dict:
    """Mass-create variables named ``prefix + index`` (reference
    objects.py:258).  ``indexes`` may be an iterable or a tuple of iterables
    (cartesian product, keyed by tuples)."""
    variables = {}
    if isinstance(indexes, tuple) and all(
        not isinstance(i, (str, int)) for i in indexes
    ):
        import itertools

        for combo in itertools.product(*indexes):
            key = tuple(str(c) for c in combo)
            name = prefix + separator.join(key)
            variables[key] = Variable(name, domain)
    else:
        for i in indexes:
            name = f"{prefix}{i}"
            variables[str(i)] = Variable(name, domain)
    return variables


def create_binary_variables(
    prefix: str, indexes, separator: str = "_"
) -> Dict:
    variables = {}
    if isinstance(indexes, tuple) and all(
        not isinstance(i, (str, int)) for i in indexes
    ):
        import itertools

        for combo in itertools.product(*indexes):
            key = tuple(str(c) for c in combo)
            name = prefix + separator.join(key)
            variables[key] = BinaryVariable(name)
    else:
        for i in indexes:
            variables[str(i)] = BinaryVariable(f"{prefix}{i}")
    return variables


class AgentDef(SimpleRepr):
    """An agent definition: name, capacity, routes, hosting costs, plus any
    extra attributes (reference objects.py:669-841).

    >>> a = AgentDef('a1', capacity=100, foo='bar')
    >>> a.name, a.capacity, a.foo
    ('a1', 100, 'bar')
    >>> a.route('a2')
    1
    >>> a.hosting_cost('c1')
    0
    """

    def __init__(
        self,
        name: str,
        capacity: float = 100,
        default_route: float = 1,
        routes: Optional[Dict[str, float]] = None,
        default_hosting_cost: float = 0,
        hosting_costs: Optional[Dict[str, float]] = None,
        **extra: Any,
    ) -> None:
        self._name = name
        self._capacity = capacity
        self._default_route = default_route
        self._routes = dict(routes) if routes else {}
        self._default_hosting_cost = default_hosting_cost
        self._hosting_costs = dict(hosting_costs) if hosting_costs else {}
        self._extra = dict(extra)

    @property
    def name(self) -> str:
        return self._name

    @property
    def capacity(self):
        return self._capacity

    @property
    def default_route(self) -> float:
        return self._default_route

    @property
    def routes(self) -> Dict[str, float]:
        return dict(self._routes)

    @property
    def default_hosting_cost(self) -> float:
        return self._default_hosting_cost

    @property
    def hosting_costs(self) -> Dict[str, float]:
        return dict(self._hosting_costs)

    @property
    def extra_attrs(self) -> Dict[str, Any]:
        return dict(self._extra)

    def route(self, other_agent: str) -> float:
        if other_agent == self._name:
            return 0
        return self._routes.get(other_agent, self._default_route)

    def hosting_cost(self, computation: str) -> float:
        return self._hosting_costs.get(computation, self._default_hosting_cost)

    def __getattr__(self, item):
        # only called when normal lookup fails: expose extra attrs
        extra = self.__dict__.get("_extra", {})
        if item in extra:
            return extra[item]
        raise AttributeError(f"AgentDef has no attribute {item!r}")

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, AgentDef)
            and other.name == self.name
            and other.capacity == self.capacity
            and other._routes == self._routes
            and other._hosting_costs == self._hosting_costs
            and other._default_route == self._default_route
            and other._default_hosting_cost == self._default_hosting_cost
            and other._extra == self._extra
        )

    def __hash__(self) -> int:
        return hash(("AgentDef", self._name))

    def __repr__(self) -> str:
        return f"AgentDef({self._name})"

    def _simple_repr(self):
        r = {
            "__qualname__": "AgentDef",
            "__module__": type(self).__module__,
            "name": self._name,
            "capacity": self._capacity,
            "default_route": self._default_route,
            "routes": dict(self._routes),
            "default_hosting_cost": self._default_hosting_cost,
            "hosting_costs": dict(self._hosting_costs),
        }
        r.update(self._extra)
        return r


def create_agents(
    prefix: str,
    indexes,
    default_route: float = 1,
    routes: Optional[Dict[str, float]] = None,
    default_hosting_costs: float = 0,
    hosting_costs: Optional[Dict[str, float]] = None,
    **kwargs: Any,
) -> Dict[str, AgentDef]:
    """Mass-create agents ``prefix + index`` (reference objects.py:879)."""
    agents = {}
    for i in indexes:
        name = f"{prefix}{i}"
        agents[str(i)] = AgentDef(
            name,
            default_route=default_route,
            routes=routes or {},
            default_hosting_cost=default_hosting_costs,
            hosting_costs=hosting_costs or {},
            **kwargs,
        )
    return agents
