"""The DCOP problem container.

Role parity with /root/reference/pydcop/dcop/dcop.py (DCOP:41,
solution_cost:308, filter_dcop:370).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from .objects import AgentDef, Domain, ExternalVariable, Variable
from .relations import Constraint, RelationProtocol

__all__ = ["DCOP", "solution_cost", "filter_dcop"]

from ..constants import INFINITY as DEFAULT_INFINITY  # noqa: E402


class DCOP:
    """A Distributed Constraint Optimization Problem.

    Aggregates domains, variables, constraints and agents; evaluates global
    solution cost.  Constraints can be added with ``add_constraint`` or the
    ``+=`` sugar, which auto-registers their variables and domains.

    >>> from pydcop_tpu.dcop.objects import Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = DCOP('demo')
    >>> x = Variable('x', [0, 1]); y = Variable('y', [0, 1])
    >>> d += constraint_from_str('c1', 'x + y', [x, y])
    >>> sorted(d.variables)
    ['x', 'y']
    """

    def __init__(
        self,
        name: str = "dcop",
        objective: str = "min",
        description: str = "",
        domains: Optional[Dict[str, Domain]] = None,
        variables: Optional[Dict[str, Variable]] = None,
        constraints: Optional[Dict[str, Constraint]] = None,
        agents: Optional[Dict[str, AgentDef]] = None,
    ) -> None:
        if objective not in ("min", "max"):
            raise ValueError("objective must be 'min' or 'max'")
        self.name = name
        self.description = description
        self.objective = objective
        self.domains: Dict[str, Domain] = dict(domains or {})
        self.variables: Dict[str, Variable] = {}
        self.external_variables: Dict[str, ExternalVariable] = {}
        self.constraints: Dict[str, Constraint] = {}
        self._agents_def: Dict[str, AgentDef] = dict(agents or {})
        self.dist_hints = None
        for v in (variables or {}).values():
            self.add_variable(v)
        for c in (constraints or {}).values():
            self.add_constraint(c)

    # -- variables ---------------------------------------------------------

    def add_variable(self, v: Variable) -> None:
        if isinstance(v, ExternalVariable):
            self.external_variables[v.name] = v
        else:
            existing = self.variables.get(v.name)
            if existing is not None and existing != v:
                raise ValueError(
                    f"inconsistent redefinition of variable {v.name}"
                )
            self.variables[v.name] = v
        self.domains.setdefault(v.domain.name, v.domain)

    def variable(self, name: str) -> Variable:
        return self.variables[name]

    def get_variables(self) -> List[Variable]:
        return list(self.variables.values())

    @property
    def all_variables(self) -> List[Variable]:
        return list(self.variables.values()) + list(
            self.external_variables.values()
        )

    # -- constraints -------------------------------------------------------

    def add_constraint(self, c: Constraint) -> None:
        if c.name in self.constraints:
            raise ValueError(f"duplicate constraint name {c.name}")
        self.constraints[c.name] = c
        for v in c.dimensions:
            if (
                v.name not in self.variables
                and v.name not in self.external_variables
            ):
                self.add_variable(v)

    def __iadd__(self, c: Constraint) -> "DCOP":
        self.add_constraint(c)
        return self

    def constraint(self, name: str) -> Constraint:
        return self.constraints[name]

    # -- agents ------------------------------------------------------------

    def add_agents(self, agents: Union[Iterable[AgentDef], Dict[str, AgentDef]]):
        if isinstance(agents, dict):
            agents = agents.values()
        for a in agents:
            self._agents_def[a.name] = a

    @property
    def agents(self) -> Dict[str, AgentDef]:
        return dict(self._agents_def)

    def agent(self, name: str) -> AgentDef:
        return self._agents_def[name]

    # -- evaluation --------------------------------------------------------

    def solution_cost(
        self, assignment: Dict[str, Any], infinity: float = DEFAULT_INFINITY
    ) -> Tuple[float, int]:
        """(cost, violation_count) of a full assignment.

        A constraint whose cost is >= ``infinity`` (or infinite) counts as a
        violation and its cost is not accumulated (reference dcop.py:308).
        """
        cost, violations = 0.0, 0
        full = dict(assignment)
        for n, ev in self.external_variables.items():
            full.setdefault(n, ev.value)
        missing = set(self.variables) - set(full)
        if missing:
            raise ValueError(f"assignment misses variables {sorted(missing)}")
        for c in self.constraints.values():
            val = c.get_value_for_assignment(
                {n: full[n] for n in c.scope_names}
            )
            if val >= infinity or val == float("inf"):
                violations += 1
            else:
                cost += val
        for v in self.variables.values():
            if v.has_cost:
                cost += v.cost_for_val(full[v.name])
        return cost, violations

    def __repr__(self) -> str:
        return (
            f"DCOP({self.name}: {len(self.variables)} vars, "
            f"{len(self.constraints)} constraints, "
            f"{len(self._agents_def)} agents)"
        )


def solution_cost(
    dcop: DCOP, assignment: Dict[str, Any], infinity: float = DEFAULT_INFINITY
) -> Tuple[float, int]:
    return dcop.solution_cost(assignment, infinity)


def filter_dcop(
    dcop: DCOP, min_arity: int = 2, remove_var_costs: bool = True
) -> DCOP:
    """Strip constraints below ``min_arity`` (and optionally variable costs) —
    used before building computation graphs that only handle binary+
    constraints (reference dcop.py:370)."""
    filtered = DCOP(dcop.name, dcop.objective, dcop.description)
    filtered.add_agents(dcop.agents)
    for c in dcop.constraints.values():
        if c.arity >= min_arity:
            filtered.add_constraint(c)
    for v in dcop.variables.values():
        if v.name not in filtered.variables:
            filtered.add_variable(
                Variable(v.name, v.domain, v.initial_value)
                if remove_var_costs
                else v
            )
        elif remove_var_costs and v.has_cost:
            filtered.variables[v.name] = Variable(
                v.name, v.domain, v.initial_value
            )
        elif not remove_var_costs:
            filtered.variables[v.name] = v
    return filtered
