"""YAML (de)serialization of DCOPs, agents, distributions and scenarios.

Format-compatible with the reference's on-disk format
(/root/reference/pydcop/dcop/yamldcop.py:63-560 and the spec at
/root/reference/docs/usage/file_formats/dcop_format.yml): domains (extensive
values or ``[1 .. 10]`` ranges), variables with ``cost_function`` /
``noise_level``, external variables, intentional constraints (expression,
multi-line function body, external ``source`` file, ``partial`` application),
extensional constraints (``values: {cost: "v1 v2 | v1 v3"}`` tables with
``default``), agents with capacity/extras, symmetric ``routes``,
``hosting_costs`` and ``distribution_hints``.  Multi-file merge is supported
by concatenating documents.
"""

from __future__ import annotations

import os
import re
import shlex
from typing import Any, Dict, Iterable, List, Optional, Union

import yaml

from ..utils.expressions import ExpressionFunction, load_source_module
from .dcop import DCOP
from .objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostFunc,
)
from .relations import (
    Constraint,
    NAryFunctionRelation,
    NAryMatrixRelation,
    assignment_matrix,
    constraint_from_external_definition,
    constraint_from_str,
)
from .scenario import DcopEvent, EventAction, Scenario

__all__ = [
    "load_dcop",
    "load_dcop_from_file",
    "dcop_yaml",
    "yaml_agents",
    "load_agents_from_file",
    "load_scenario",
    "load_scenario_from_file",
    "yaml_scenario",
    "DcopInvalidFormatError",
]

_RANGE_RE = re.compile(r"^\s*(-?\d+)\s*\.\.\s*(-?\d+)\s*$")


class DcopInvalidFormatError(Exception):
    pass


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_dcop_from_file(filenames: Union[str, Iterable[str]]) -> DCOP:
    """Load a DCOP from one file or a list of files merged in order.

    Sections (domains, variables, constraints, agents, ...) from later files
    are merged entry-wise into earlier ones — NOT by yaml duplicate-key
    semantics, which would silently drop whole sections.
    """
    if isinstance(filenames, str):
        filenames = [filenames]
    filenames = list(filenames)
    merged: Dict[str, Any] = {}
    for f in filenames:
        with open(f, encoding="utf-8") as fh:
            data = yaml.safe_load(fh.read())
        if not isinstance(data, dict):
            raise DcopInvalidFormatError(f"{f}: dcop yaml must be a mapping")
        for key, section in data.items():
            if (
                key in merged
                and isinstance(merged[key], dict)
                and isinstance(section, dict)
            ):
                merged[key].update(section)
            else:
                merged[key] = section
    main_dir = os.path.dirname(os.path.abspath(filenames[0]))
    return _load_dcop_data(merged, main_dir=main_dir)


def load_dcop(dcop_str: str, main_dir: str = ".") -> DCOP:
    data = yaml.safe_load(dcop_str)
    if not isinstance(data, dict):
        raise DcopInvalidFormatError("dcop yaml must be a mapping")
    return _load_dcop_data(data, main_dir)


def _load_dcop_data(data: Dict[str, Any], main_dir: str = ".") -> DCOP:
    if "name" not in data:
        raise DcopInvalidFormatError("missing 'name' in dcop yaml")
    if "objective" not in data:
        # reference format requires it (yamldcop.py raises KeyError there;
        # tests/unit/test_dcop_serialization.py:115 pins the behavior)
        raise DcopInvalidFormatError("missing 'objective' in dcop yaml")
    dcop = DCOP(
        data["name"],
        data["objective"],
        data.get("description", ""),
    )

    domains = _build_domains(data.get("domains", {}))
    dcop.domains.update(domains)

    for v in _build_variables(data.get("variables", {}), domains).values():
        dcop.add_variable(v)
    for v in _build_external_variables(
        data.get("external_variables", {}), domains
    ).values():
        dcop.add_variable(v)

    for c in _build_constraints(
        data.get("constraints", {}), dcop, main_dir
    ).values():
        dcop.add_constraint(c)

    agents = _build_agents(
        data.get("agents", {}),
        data.get("routes", {}) or {},
        data.get("hosting_costs", {}) or {},
    )
    dcop.add_agents(agents)

    hints = data.get("distribution_hints")
    if hints:
        from ..distribution.objects import DistributionHints

        # validate references like the reference loader
        # (ref tests/unit/test_dcop_serialization.py:889-903)
        must_host = hints.get("must_host", {}) or {}
        agent_names = {a.name for a in agents}
        known = set(dcop.variables) | set(dcop.constraints)
        for agent, comps in must_host.items():
            if agent not in agent_names:
                raise ValueError(
                    f"distribution_hints.must_host: unknown agent {agent!r}"
                )
            for comp in comps:
                if comp not in known:
                    raise ValueError(
                        f"distribution_hints.must_host: unknown "
                        f"computation {comp!r} for agent {agent!r}"
                    )
        dcop.dist_hints = DistributionHints(
            must_host=must_host,
            host_with=hints.get("host_with", {}),
        )
    return dcop


def _expand_values(raw_values) -> List[Any]:
    # range written without brackets arrives as a bare string ('1 .. 10')
    if isinstance(raw_values, str):
        m = _RANGE_RE.match(raw_values)
        if not m:
            raise DcopInvalidFormatError(
                f"domain values must be a list or a range, got {raw_values!r}"
            )
        lo, hi = map(int, m.groups())
        return list(range(lo, hi + 1))
    if (
        len(raw_values) == 1
        and isinstance(raw_values[0], str)
        and _RANGE_RE.match(raw_values[0])
    ):
        lo, hi = map(int, _RANGE_RE.match(raw_values[0]).groups())
        return list(range(lo, hi + 1))
    return list(raw_values)


def _build_domains(raw: Dict[str, Any]) -> Dict[str, Domain]:
    domains = {}
    for name, d in (raw or {}).items():
        if "values" not in d:
            raise DcopInvalidFormatError(f"domain {name} has no values")
        values = _expand_values(d["values"])
        domains[name] = Domain(name, d.get("type", ""), values)
    return domains


def _build_variables(
    raw: Dict[str, Any], domains: Dict[str, Domain]
) -> Dict[str, Variable]:
    variables = {}
    for name, v in (raw or {}).items():
        v = v or {}
        try:
            domain = domains[v["domain"]]
        except KeyError:
            raise DcopInvalidFormatError(
                f"variable {name}: missing or unknown domain"
            )
        initial = v.get("initial_value")
        if initial is not None and initial not in domain:
            raise DcopInvalidFormatError(
                f"variable {name}: initial value {initial!r} not in domain"
            )
        if "cost_function" in v:
            try:
                cost_fn = ExpressionFunction(str(v["cost_function"]))
            except SyntaxError as e:
                raise DcopInvalidFormatError(
                    f"variable {name}: invalid cost_function "
                    f"{v['cost_function']!r}: {e}"
                ) from e
            if "noise_level" in v:
                variables[name] = VariableNoisyCostFunc(
                    name,
                    domain,
                    cost_fn,
                    initial,
                    noise_level=float(v["noise_level"]),
                )
            else:
                variables[name] = VariableWithCostFunc(
                    name, domain, cost_fn, initial
                )
        else:
            variables[name] = Variable(name, domain, initial)
    return variables


def _build_external_variables(
    raw: Dict[str, Any], domains: Dict[str, Domain]
) -> Dict[str, ExternalVariable]:
    out = {}
    for name, v in (raw or {}).items():
        domain = domains[v["domain"]]
        if "initial_value" not in v:
            raise DcopInvalidFormatError(
                f"external variable {name} requires an initial_value"
            )
        out[name] = ExternalVariable(name, domain, v["initial_value"])
    return out


def _build_constraints(
    raw: Dict[str, Any], dcop: DCOP, main_dir: str
) -> Dict[str, Constraint]:
    constraints: Dict[str, Constraint] = {}
    all_vars = dcop.all_variables
    for name, c in (raw or {}).items():
        ctype = c.get("type")
        if ctype == "intention":
            if "source" in c:
                src = c["source"]
                if not os.path.isabs(src):
                    src = os.path.join(main_dir, src)
                rel = constraint_from_external_definition(
                    name, src, str(c["function"]), all_vars
                )
            else:
                try:
                    rel = constraint_from_str(
                        name, str(c["function"]), all_vars
                    )
                except SyntaxError as e:
                    # a bare SyntaxError would not say WHICH constraint
                    raise DcopInvalidFormatError(
                        f"constraint {name}: invalid expression "
                        f"{c['function']!r}: {e}"
                    ) from e
            if "partial" in c:
                f = rel.function.partial(**c["partial"])
                by_name = {v.name: v for v in all_vars}
                scope = [by_name[n] for n in sorted(f.variable_names)]
                rel = NAryFunctionRelation(f, scope, name=name)
            constraints[name] = rel
        elif ctype == "extensional":
            constraints[name] = _build_extensional(name, c, dcop)
        else:
            raise DcopInvalidFormatError(
                f"constraint {name}: unknown type {ctype!r}"
            )
    return constraints


def _build_extensional(name: str, c: Dict[str, Any], dcop: DCOP) -> Constraint:
    var_names = c["variables"]
    if isinstance(var_names, str):
        var_names = [var_names]
    variables = []
    for vn in var_names:
        if vn in dcop.variables:
            variables.append(dcop.variables[vn])
        elif vn in dcop.external_variables:
            variables.append(dcop.external_variables[vn])
        else:
            raise DcopInvalidFormatError(
                f"extensional constraint {name}: unknown variable {vn}"
            )
    default = float(c.get("default", 0))
    matrix = assignment_matrix(variables, default)
    for value, assignments in (c.get("values") or {}).items():
        value = float(value)
        for assignment in str(assignments).split("|"):
            tokens = shlex.split(assignment.strip())
            if len(tokens) != len(variables):
                raise DcopInvalidFormatError(
                    f"extensional constraint {name}: assignment "
                    f"{assignment!r} does not match scope arity"
                )
            idx = tuple(
                v.domain.index(v.domain.to_domain_value(t))
                for v, t in zip(variables, tokens)
            )
            matrix[idx] = value
    return NAryMatrixRelation(variables, matrix, name=name)


def _build_agents(
    raw, routes: Dict[str, Any], hosting_costs: Dict[str, Any]
) -> List[AgentDef]:
    default_route = float(routes.get("default", 1))
    default_hosting = hosting_costs.get("default", 0)

    # route symmetry: collect pair costs, error on conflicting redefinition
    pair_routes: Dict[str, Dict[str, float]] = {}
    seen = set()
    for a, peers in routes.items():
        if a == "default":
            continue
        for b, cost in (peers or {}).items():
            key = tuple(sorted((a, b)))
            if key in seen:
                if pair_routes[a].get(b) != float(cost):
                    raise DcopInvalidFormatError(
                        f"route ({a}, {b}) defined twice with different costs"
                    )
                continue
            seen.add(key)
            pair_routes.setdefault(a, {})[b] = float(cost)
            pair_routes.setdefault(b, {})[a] = float(cost)

    agents = []
    if isinstance(raw, list):
        raw = {a: {} for a in raw}
    for name, props in (raw or {}).items():
        props = dict(props or {})
        capacity = props.pop("capacity", 100)
        hc = hosting_costs.get(name, {}) or {}
        agents.append(
            AgentDef(
                name,
                capacity=capacity,
                default_route=default_route,
                routes=pair_routes.get(name, {}),
                default_hosting_cost=hc.get("default", default_hosting),
                hosting_costs=hc.get("computations", {}),
                **props,
            )
        )
    return agents


def load_agents_from_file(filename: str) -> List[AgentDef]:
    with open(filename, encoding="utf-8") as fh:
        data = yaml.safe_load(fh.read())
    return _build_agents(
        data.get("agents", {}),
        data.get("routes", {}) or {},
        data.get("hosting_costs", {}) or {},
    )


# ---------------------------------------------------------------------------
# dumping
# ---------------------------------------------------------------------------


def dcop_yaml(dcop: DCOP) -> str:
    data: Dict[str, Any] = {
        "name": dcop.name,
        "objective": dcop.objective,
    }
    if dcop.description:
        data["description"] = dcop.description

    data["domains"] = {
        d.name: {"values": list(d.values), **({"type": d.type} if d.type else {})}
        for d in dcop.domains.values()
    }

    from .objects import VariableWithCostDict

    variables = {}
    for v in dcop.variables.values():
        entry: Dict[str, Any] = {"domain": v.domain.name}
        if v.initial_value is not None:
            entry["initial_value"] = v.initial_value
        if isinstance(v, VariableNoisyCostFunc):
            entry["cost_function"] = v.cost_func.expression
            entry["noise_level"] = v.noise_level
        elif isinstance(v, VariableWithCostFunc):
            entry["cost_function"] = v.cost_func.expression
        elif isinstance(v, VariableWithCostDict):
            # no dict-cost syntax in the yaml format: encode the cost table as
            # a dict-literal indexing expression, semantics-preserving
            entry["cost_function"] = f"{v.costs!r}[{v.name}]"
        variables[v.name] = entry
    data["variables"] = variables

    if dcop.external_variables:
        data["external_variables"] = {
            v.name: {"domain": v.domain.name, "initial_value": v.value}
            for v in dcop.external_variables.values()
        }

    constraints = {}
    for c in dcop.constraints.values():
        if isinstance(c, NAryMatrixRelation):
            constraints[c.name] = _dump_extensional(c)
        elif (
            isinstance(c, NAryFunctionRelation)
            and c.expression is not None
            and getattr(c.function, "source_module", None) is None
        ):
            constraints[c.name] = {
                "type": "intention",
                "function": c.expression,
            }
        else:
            # source-file constraints (and opaque callables): the source path
            # is not recoverable, dump the tabulated cost table instead
            constraints[c.name] = _dump_extensional(c.tabulate())
    data["constraints"] = constraints

    if dcop.agents:
        data["agents"] = {
            a.name: {
                "capacity": a.capacity,
                **a.extra_attrs,
            }
            for a in dcop.agents.values()
        }
        routes: Dict[str, Any] = {}
        dumped = set()
        for a in dcop.agents.values():
            if a.default_route != 1:
                routes["default"] = a.default_route
            for b, cost in a.routes.items():
                key = tuple(sorted((a.name, b)))
                if key in dumped:
                    continue
                dumped.add(key)
                routes.setdefault(key[0], {})[key[1]] = cost
        if routes:
            data["routes"] = routes
        hosting: Dict[str, Any] = {}
        for a in dcop.agents.values():
            entry = {}
            if a.default_hosting_cost:
                entry["default"] = a.default_hosting_cost
            if a.hosting_costs:
                entry["computations"] = a.hosting_costs
            if entry:
                hosting[a.name] = entry
        if hosting:
            data["hosting_costs"] = hosting

    return yaml.safe_dump(data, default_flow_style=False, sort_keys=False)


def _dump_extensional(c: NAryMatrixRelation) -> Dict[str, Any]:
    import numpy as np

    values: Dict[float, List[str]] = {}
    m = c.matrix
    flat_counts: Dict[float, int] = {}
    for idx in np.ndindex(*m.shape):
        val = float(m[idx])
        flat_counts[val] = flat_counts.get(val, 0) + 1
    default = max(flat_counts, key=flat_counts.get) if flat_counts else 0.0
    for idx in np.ndindex(*m.shape):
        val = float(m[idx])
        if val == default:
            continue
        tokens = " ".join(
            _dump_token(v.domain[i]) for v, i in zip(c.dimensions, idx)
        )
        values.setdefault(val, []).append(tokens)
    out: Dict[str, Any] = {
        "type": "extensional",
        "variables": c.scope_names,
        "default": default,
    }
    if values:
        out["values"] = {k: " | ".join(v) for k, v in values.items()}
    return out


def _dump_token(v) -> str:
    s = str(v)
    if " " in s:
        return f"'{s}'"
    return s


def yaml_agents(agents: Iterable[AgentDef]) -> str:
    data = {
        "agents": {
            a.name: {"capacity": a.capacity, **a.extra_attrs} for a in agents
        }
    }
    return yaml.safe_dump(data, default_flow_style=False, sort_keys=False)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def load_scenario_from_file(filename: str) -> Scenario:
    with open(filename, encoding="utf-8") as fh:
        return load_scenario(fh.read())


def load_scenario(scenario_str: str) -> Scenario:
    data = yaml.safe_load(scenario_str)
    events = []
    for i, e in enumerate(data.get("events", [])):
        eid = e.get("id", f"e{i}")
        if "delay" in e:
            events.append(DcopEvent(eid, delay=float(e["delay"])))
        else:
            actions = []
            for a in e.get("actions", []):
                a = dict(a)
                atype = a.pop("type")
                actions.append(EventAction(atype, **a))
            events.append(DcopEvent(eid, actions=actions))
    return Scenario(events)


def yaml_scenario(scenario: Scenario) -> str:
    events = []
    for e in scenario.events:
        if e.is_delay:
            events.append({"id": e.id, "delay": e.delay})
        else:
            events.append(
                {
                    "id": e.id,
                    "actions": [
                        {"type": a.type, **a.args} for a in e.actions or []
                    ],
                }
            )
    return yaml.safe_dump(
        {"events": events}, default_flow_style=False, sort_keys=False
    )
