"""Constraint algebra: relations over variables, join/projection, helpers.

Role parity with /root/reference/pydcop/dcop/relations.py (RelationProtocol:48,
NAryFunctionRelation:456, NAryMatrixRelation:672, constraint_from_str:1275,
join:1672, projection:1717, assignment helpers :1452-1660).

TPU-first redesign: ``NAryMatrixRelation`` (a dense cost hypercube over the
constraint scope) is the *primary* representation — every other constraint kind
lowers to it via ``tabulate`` at compile time.  ``join`` is a numpy
broadcast-add over the aligned union scope and ``projection`` an axis
min/max-reduce, instead of the reference's python iteration over all
assignments (relations.py:1672-1756).  DPOP's whole UTIL phase is these two
ops, so they are written to move to jax.numpy untouched.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..utils.expressions import ExpressionFunction, load_source_module
from ..utils.simple_repr import SimpleRepr, from_repr
from .objects import Domain, Variable

__all__ = [
    "Constraint",
    "RelationProtocol",
    "ZeroAryRelation",
    "UnaryFunctionRelation",
    "UnaryBooleanRelation",
    "NAryFunctionRelation",
    "NAryMatrixRelation",
    "ConditionalRelation",
    "AsNAryFunctionRelation",
    "relation_from_str",
    "constraint_from_str",
    "constraint_from_external_definition",
    "assignment_matrix",
    "generate_assignment",
    "generate_assignment_as_dict",
    "assignment_cost",
    "find_arg_optimal",
    "find_optimal",
    "optimal_cost_value",
    "find_optimum",
    "join",
    "projection",
    "add_var_to_rel",
    "count_var_match",
    "is_compatible",
    "filter_assignment_dict",
    "find_dependent_relations",
    "DEFAULT_TYPE",
]

DEFAULT_TYPE = np.float64


class Constraint(SimpleRepr):
    """Base class for all relations (cost functions over variables)."""

    def __init__(self, name: str, variables: Sequence[Variable]) -> None:
        self._name = name
        self._variables = tuple(variables)

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return "generic"

    @property
    def dimensions(self) -> List[Variable]:
        return list(self._variables)

    @property
    def arity(self) -> int:
        return len(self._variables)

    @property
    def scope_names(self) -> List[str]:
        return [v.name for v in self._variables]

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(len(v.domain) for v in self._variables)

    def __call__(self, *args, **kwargs) -> float:
        if args and not kwargs:
            if len(args) != self.arity:
                raise ValueError(
                    f"{self.name} expects {self.arity} positional values"
                )
            kwargs = dict(zip(self.scope_names, args))
        return self.get_value_for_assignment(kwargs)

    def get_value_for_assignment(self, assignment: Dict[str, Any]) -> float:
        raise NotImplementedError

    def has_variable(self, variable: Union[str, Variable]) -> bool:
        name = variable if isinstance(variable, str) else variable.name
        return name in self.scope_names

    def slice(self, partial: Dict[str, Any]) -> "Constraint":
        """Constraint over the remaining scope with some variables fixed."""
        return self.tabulate().slice(partial)

    def tabulate(self) -> "NAryMatrixRelation":
        """Lower to a dense cost hypercube (the compile-time path to TPU)."""
        m = NAryMatrixRelation(self._variables, name=self._name)
        arr = np.empty(m.shape, dtype=DEFAULT_TYPE)
        names = self.scope_names
        domains = [v.domain.values for v in self._variables]
        for idx in np.ndindex(*m.shape) if m.shape else [()]:
            assignment = {n: domains[i][idx[i]] for i, n in enumerate(names)}
            arr[idx] = self.get_value_for_assignment(assignment)
        return NAryMatrixRelation(self._variables, arr, name=self._name)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._name}, {self.scope_names})"


# Alias for familiarity with the reference naming.
RelationProtocol = Constraint


class ZeroAryRelation(Constraint):
    """A constant relation (reference relations.py:218)."""

    _repr_fields = ("name", "value")

    def __init__(self, name: str, value: float) -> None:
        super().__init__(name, ())
        self.value = value

    def get_value_for_assignment(self, assignment: Dict[str, Any]) -> float:
        return self.value

    def __eq__(self, other):
        return (
            isinstance(other, ZeroAryRelation)
            and other.name == self.name
            and other.value == self.value
        )

    def __hash__(self):
        return hash((self._name, self.value))


class UnaryFunctionRelation(Constraint):
    """A unary relation from a python callable or expression."""

    def __init__(
        self,
        name: str,
        variable: Variable,
        rel_function: Union[Callable, ExpressionFunction],
    ) -> None:
        super().__init__(name, (variable,))
        self._fn = rel_function

    @property
    def expression(self) -> Optional[str]:
        if isinstance(self._fn, ExpressionFunction):
            return self._fn.expression
        return None

    def get_value_for_assignment(self, assignment: Dict[str, Any]) -> float:
        val = assignment[self._variables[0].name]
        if isinstance(self._fn, ExpressionFunction):
            return self._fn(**{self._variables[0].name: val})
        return self._fn(val)

    def __eq__(self, other):
        return (
            isinstance(other, UnaryFunctionRelation)
            and other.name == self.name
            and other.dimensions == self.dimensions
            and getattr(other, "_fn", None) == self._fn
        )

    def __hash__(self):
        return hash((self._name, self._variables))


class UnaryBooleanRelation(UnaryFunctionRelation):
    """Truthiness of the variable value as 0/1 (reference relations.py:392)."""

    def __init__(self, name: str, variable: Variable) -> None:
        super().__init__(name, variable, lambda v: 1 if v else 0)


class NAryFunctionRelation(Constraint):
    """An n-ary relation given by a python function.

    If ``f`` is an ``ExpressionFunction`` the scope can be inferred from its
    free variables.
    """

    def __init__(
        self,
        f: Union[Callable, ExpressionFunction],
        variables: Sequence[Variable],
        name: Optional[str] = None,
        f_kwargs: bool = True,
    ) -> None:
        super().__init__(name or getattr(f, "__name__", "rel"), variables)
        self._fn = f
        self._f_kwargs = f_kwargs or isinstance(f, ExpressionFunction)

    @property
    def function(self):
        return self._fn

    @property
    def expression(self) -> Optional[str]:
        if isinstance(self._fn, ExpressionFunction):
            return self._fn.expression
        return None

    def get_value_for_assignment(self, assignment: Dict[str, Any]) -> float:
        kwargs = {n: assignment[n] for n in self.scope_names}
        if self._f_kwargs:
            return self._fn(**kwargs)
        return self._fn(*[kwargs[n] for n in self.scope_names])

    def __eq__(self, other):
        return (
            isinstance(other, NAryFunctionRelation)
            and other.name == self.name
            and other.dimensions == self.dimensions
            and other._fn == self._fn
        )

    def __hash__(self):
        return hash((self._name, self._variables))

    def _simple_repr(self):
        if not isinstance(self._fn, ExpressionFunction):
            raise TypeError(
                "only expression-based n-ary relations are serializable; "
                "tabulate() first"
            )
        return {
            "__qualname__": "NAryFunctionRelation",
            "__module__": type(self).__module__,
            "name": self._name,
            "expression": self._fn.expression,
            "variables": [v._simple_repr() for v in self._variables],
        }

    @classmethod
    def _from_repr(cls, name, expression, variables):
        vs = [from_repr(v) for v in variables]
        return cls(ExpressionFunction(expression), vs, name=name)


def AsNAryFunctionRelation(*variables: Variable):
    """Decorator: lift a plain python function to an NAryFunctionRelation
    (reference relations.py:616).

    >>> x = Variable('x', [0, 1]); y = Variable('y', [0, 1])
    >>> @AsNAryFunctionRelation(x, y)
    ... def add(x, y):
    ...     return x + y
    >>> add(1, 1)
    2
    """

    def decorate(fn: Callable) -> NAryFunctionRelation:
        return NAryFunctionRelation(
            fn, variables, name=fn.__name__, f_kwargs=False
        )

    return decorate


class NAryMatrixRelation(Constraint):
    """Dense cost hypercube over the scope — the TPU-native constraint form.

    Axis ``i`` of the array indexes the domain of ``variables[i]`` in domain
    order.  (Reference: relations.py:672-906, but here the array ops are
    vectorized.)

    >>> x = Variable('x', ['a', 'b']); y = Variable('y', ['a', 'b'])
    >>> r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4.]]))
    >>> r(x='b', y='a')
    3.0
    """

    def __init__(
        self,
        variables: Sequence[Variable],
        matrix: Optional[np.ndarray] = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__(name or "rel", variables)
        shape = tuple(len(v.domain) for v in variables)
        if matrix is None:
            matrix = np.zeros(shape, dtype=DEFAULT_TYPE)
        else:
            matrix = np.asarray(matrix, dtype=DEFAULT_TYPE)
            if matrix.shape != shape:
                raise ValueError(
                    f"matrix shape {matrix.shape} does not match the scope's "
                    f"domain sizes {shape} (axis i must index variables[i])"
                )
        self._m = matrix

    @property
    def matrix(self) -> np.ndarray:
        return self._m

    @property
    def type(self) -> str:
        return "matrix"

    def _indices(self, assignment: Dict[str, Any]) -> Tuple[int, ...]:
        return tuple(
            v.domain.index(assignment[v.name]) for v in self._variables
        )

    def get_value_for_assignment(
        self, assignment: Union[Dict[str, Any], List]
    ) -> float:
        if isinstance(assignment, list):
            assignment = dict(zip(self.scope_names, assignment))
        if self.arity == 0:
            return float(self._m.reshape(()))
        return float(self._m[self._indices(assignment)])

    def set_value_for_assignment(
        self, assignment: Dict[str, Any], value: float
    ) -> "NAryMatrixRelation":
        """Return a new relation with one cell changed (immutable update)."""
        m = self._m.copy()
        m[self._indices(assignment)] = value
        return NAryMatrixRelation(self._variables, m, name=self._name)

    def slice(self, partial: Dict[str, Any]) -> "NAryMatrixRelation":
        """Fix some variables: index their axes, keep the rest."""
        unknown = set(partial) - set(self.scope_names)
        if unknown:
            raise ValueError(f"slice variables {unknown} not in scope")
        index: List[Any] = []
        remaining: List[Variable] = []
        for v in self._variables:
            if v.name in partial:
                index.append(v.domain.index(partial[v.name]))
            else:
                index.append(slice(None))
                remaining.append(v)
        return NAryMatrixRelation(
            remaining, self._m[tuple(index)], name=self._name
        )

    def tabulate(self) -> "NAryMatrixRelation":
        return self

    def __eq__(self, other):
        return (
            isinstance(other, NAryMatrixRelation)
            and other.name == self.name
            and other.dimensions == self.dimensions
            and np.array_equal(other._m, self._m)
        )

    def __hash__(self):
        return hash((self._name, self._variables))

    def _simple_repr(self):
        return {
            "__qualname__": "NAryMatrixRelation",
            "__module__": type(self).__module__,
            "name": self._name,
            "variables": [v._simple_repr() for v in self._variables],
            "matrix": self._m.tolist(),
        }

    @classmethod
    def _from_repr(cls, name, variables, matrix):
        vs = [from_repr(v) for v in variables]
        return cls(vs, np.array(matrix), name=name)

    @classmethod
    def from_func_relation(cls, rel: Constraint) -> "NAryMatrixRelation":
        return rel.tabulate()


class ConditionalRelation(Constraint):
    """``if condition(assignment): consequence(assignment)`` (reference
    relations.py:948)."""

    def __init__(
        self,
        condition: Constraint,
        consequence: Constraint,
        name: Optional[str] = None,
        return_value_if_false: float = 0,
    ) -> None:
        scope: List[Variable] = list(condition.dimensions)
        for v in consequence.dimensions:
            if v not in scope:
                scope.append(v)
        super().__init__(name or f"if_{condition.name}", scope)
        self._condition = condition
        self._consequence = consequence
        self._if_false = return_value_if_false

    def get_value_for_assignment(self, assignment: Dict[str, Any]) -> float:
        cond = self._condition.get_value_for_assignment(
            {n: assignment[n] for n in self._condition.scope_names}
        )
        if cond:
            return self._consequence.get_value_for_assignment(
                {n: assignment[n] for n in self._consequence.scope_names}
            )
        return self._if_false


def relation_from_str(
    name: str, expression: str, all_variables: Iterable[Variable]
) -> NAryFunctionRelation:
    """Build an intentional constraint from a python expression; the scope is
    the expression's free variables (reference relations.py:1275)."""
    f = ExpressionFunction(expression)
    by_name = {v.name: v for v in all_variables}
    scope = []
    for vname in sorted(f.variable_names):
        if vname not in by_name:
            raise ValueError(
                f"variable {vname!r} of constraint {name} is not defined"
            )
        scope.append(by_name[vname])
    return NAryFunctionRelation(f, scope, name=name)


constraint_from_str = relation_from_str


def constraint_from_external_definition(
    name: str,
    source_file: str,
    expression: str,
    all_variables: Iterable[Variable],
) -> NAryFunctionRelation:
    """Intentional constraint whose expression calls functions from an external
    python file via ``source.``  (reference relations.py:1314)."""
    module = load_source_module(source_file)
    f = ExpressionFunction(expression, source_module=module)
    by_name = {v.name: v for v in all_variables}
    scope = [by_name[v] for v in sorted(f.variable_names)]
    return NAryFunctionRelation(f, scope, name=name)


# ---------------------------------------------------------------------------
# assignment helpers
# ---------------------------------------------------------------------------


def assignment_matrix(variables: Sequence[Variable], default: float = 0):
    """Dense array over the joint domain, filled with ``default``."""
    shape = tuple(len(v.domain) for v in variables)
    return np.full(shape, default, dtype=DEFAULT_TYPE)


def generate_assignment(variables: Sequence[Variable]):
    """Iterate all assignments as value lists, last variable fastest."""
    for combo in itertools.product(*[v.domain.values for v in variables]):
        yield list(combo)


def generate_assignment_as_dict(variables: Sequence[Variable]):
    names = [v.name for v in variables]
    for combo in itertools.product(*[v.domain.values for v in variables]):
        yield dict(zip(names, combo))


def assignment_cost(
    assignment: Dict[str, Any],
    constraints: Iterable[Constraint],
    infinity: float = float("inf"),
) -> float:
    """Total cost of an assignment over the given constraints."""
    cost = 0.0
    for c in constraints:
        cost += c.get_value_for_assignment(
            {n: assignment[n] for n in c.scope_names}
        )
    return cost


def find_arg_optimal(
    variable: Variable, relation: Constraint, mode: str = "min"
) -> Tuple[List[Any], float]:
    """Values of ``variable`` optimizing a unary relation over it.

    Returns (list of optimal values, optimal cost) — vectorized over the
    tabulated relation.
    """
    if relation.arity != 1 or relation.dimensions[0].name != variable.name:
        raise ValueError(
            f"find_arg_optimal needs a unary relation on {variable.name}"
        )
    table = relation.tabulate().matrix
    opt = table.min() if mode == "min" else table.max()
    idx = np.nonzero(np.isclose(table, opt))[0]
    return [variable.domain[int(i)] for i in idx], float(opt)


def find_optimal(
    relation: Constraint, partial: Dict[str, Any], mode: str = "min"
) -> Tuple[List[Dict[str, Any]], float]:
    """All optimal assignments of the relation's free variables, given a
    partial assignment."""
    sliced = relation.tabulate().slice(partial) if partial else relation.tabulate()
    table = sliced.matrix
    opt = table.min() if mode == "min" else table.max()
    free = sliced.dimensions
    out = []
    for idx in zip(*np.nonzero(np.isclose(table, opt))):
        out.append(
            {v.name: v.domain[int(i)] for v, i in zip(free, idx)}
        )
    if not free and table.shape == ():
        out = [{}]
    return out, float(opt)


def optimal_cost_value(
    variable: Variable, mode: str = "min"
) -> Tuple[Any, float]:
    """Best value and cost w.r.t. the variable's own unary cost."""
    costs = np.array(variable.cost_vector(), dtype=DEFAULT_TYPE)
    i = int(np.argmin(costs) if mode == "min" else np.argmax(costs))
    return variable.domain[i], float(costs[i])


def find_optimum(relation: Constraint, mode: str = "min") -> float:
    """Global optimum of a relation over its whole joint domain."""
    table = relation.tabulate().matrix
    return float(table.min() if mode == "min" else table.max())


# ---------------------------------------------------------------------------
# join / projection — DPOP's math, as broadcast ops
# ---------------------------------------------------------------------------


def _aligned(
    rel: NAryMatrixRelation, scope: Sequence[Variable]
) -> np.ndarray:
    """View of rel's matrix expanded/transposed to the given union scope."""
    names = [v.name for v in scope]
    # transpose rel's axes into union order, then insert broadcast axes for
    # union variables absent from rel's scope
    order_in_union = [n for n in names if n in rel.scope_names]
    perm = [rel.scope_names.index(n) for n in order_in_union]
    m = np.transpose(rel.matrix, perm)
    out_index = tuple(
        slice(None) if n in rel.scope_names else None for n in names
    )
    return m[out_index]


def join(u1: Constraint, u2: Constraint) -> NAryMatrixRelation:
    """Pointwise sum over the union of scopes (reference relations.py:1672) —
    implemented as one numpy broadcast-add."""
    m1 = u1.tabulate()
    m2 = u2.tabulate()
    scope: List[Variable] = list(m1.dimensions)
    for v in m2.dimensions:
        if v.name not in [s.name for s in scope]:
            scope.append(v)
    a = _aligned(m1, scope)
    b = _aligned(m2, scope)
    return NAryMatrixRelation(
        scope, a + b, name=f"joined_{u1.name}_{u2.name}"
    )


def projection(
    rel: Constraint, variable: Variable, mode: str = "min"
) -> NAryMatrixRelation:
    """Optimize one variable out: reduce its axis (reference
    relations.py:1717)."""
    m = rel.tabulate()
    if variable.name not in m.scope_names:
        raise ValueError(
            f"cannot project {variable.name}: not in scope of {rel.name}"
        )
    axis = m.scope_names.index(variable.name)
    reduced = m.matrix.min(axis=axis) if mode == "min" else m.matrix.max(axis=axis)
    remaining = [v for v in m.dimensions if v.name != variable.name]
    return NAryMatrixRelation(
        remaining, reduced, name=f"{rel.name}_proj_{variable.name}"
    )


def add_var_to_rel(
    name: str,
    original_relation: Constraint,
    variable: Variable,
    f: Callable,
) -> NAryFunctionRelation:
    """Extend a relation with one extra variable combined via ``f(original
    cost, var value)`` (reference relations.py:1131)."""

    def extended(**kwargs):
        val = kwargs.pop(variable.name)
        return f(original_relation.get_value_for_assignment(kwargs), val)

    return NAryFunctionRelation(
        extended,
        list(original_relation.dimensions) + [variable],
        name=name,
    )


def count_var_match(var_names, relation: Constraint) -> int:
    """Number of the relation's dimensions whose names appear in
    ``var_names`` (reference relations.py:1139) — used by distribution
    heuristics to score agent/constraint affinity."""
    return sum(1 for v in relation.dimensions if v.name in var_names)


def is_compatible(
    assignment1: Dict[str, Any], assignment2: Dict[str, Any]
) -> bool:
    """True when two (potentially partial) assignments agree on every
    variable they share (reference relations.py:1257)."""
    return all(
        assignment1[k] == assignment2[k]
        for k in assignment1.keys() & assignment2.keys()
    )


def filter_assignment_dict(
    assignment: Dict[str, Any], target_vars: Sequence[Variable]
) -> Dict[str, Any]:
    """Restrict an assignment to the given variables (reference
    relations.py:1535)."""
    names = {v.name for v in target_vars}
    return {k: v for k, v in assignment.items() if k in names}


def find_dependent_relations(
    variable: Variable,
    constraints: Sequence[Constraint],
    ext_var_assignment: Optional[Dict[str, Any]] = None,
) -> List[Constraint]:
    """Constraints whose scope contains ``variable`` (reference
    relations.py:1219).  With ``ext_var_assignment``, a constraint only
    counts if it still has dimensions after slicing those (external)
    variables out — a ConditionalRelation whose condition variable is
    assigned may collapse to a constant and stop depending on anything."""
    out: List[Constraint] = []
    for r in constraints:
        if not any(v.name == variable.name for v in r.dimensions):
            continue
        if ext_var_assignment:
            sliced = r.slice(
                filter_assignment_dict(ext_var_assignment, r.dimensions)
            )
            if not sliced.dimensions:
                continue
        out.append(r)
    return out
