"""Dynamic-DCOP scenarios: timed event streams.

Role parity with /root/reference/pydcop/dcop/scenario.py (EventAction:37,
DcopEvent:55, Scenario:95).  Events either wait (``delay``) or perform actions
(``add_agent``, ``remove_agent``, external variable changes).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from ..utils.simple_repr import SimpleRepr

__all__ = ["EventAction", "DcopEvent", "Scenario"]


class EventAction(SimpleRepr):
    """A single action: type + free-form args (e.g. agent name)."""

    _repr_fields = ("type", "args")

    def __init__(self, type: str, **args: Any) -> None:  # noqa: A002
        self._type = type
        self._args = dict(args)

    @property
    def type(self) -> str:
        return self._type

    @property
    def args(self) -> Dict[str, Any]:
        return dict(self._args)

    @classmethod
    def _from_repr(cls, type, args):  # noqa: A002
        return cls(type, **args)

    def __eq__(self, other):
        return (
            isinstance(other, EventAction)
            and other._type == self._type
            and other._args == self._args
        )

    def __repr__(self) -> str:
        return f"EventAction({self._type}, {self._args})"


class DcopEvent(SimpleRepr):
    """An event: either a delay (seconds) or a list of actions."""

    _repr_fields = ("id", "delay", "actions")

    def __init__(
        self,
        id: str,  # noqa: A002
        delay: Optional[float] = None,
        actions: Optional[List[EventAction]] = None,
    ) -> None:
        self._id = id
        self._delay = delay
        self._actions = list(actions) if actions else None

    @property
    def id(self) -> str:
        return self._id

    @property
    def delay(self) -> Optional[float]:
        return self._delay

    @property
    def actions(self) -> Optional[List[EventAction]]:
        return list(self._actions) if self._actions is not None else None

    @property
    def is_delay(self) -> bool:
        return self._delay is not None

    @classmethod
    def _from_repr(cls, id, delay=None, actions=None):  # noqa: A002
        return cls(id, delay, actions)

    def __eq__(self, other):
        return (
            isinstance(other, DcopEvent)
            and other._id == self._id
            and other._delay == self._delay
            and other._actions == self._actions
        )

    def __repr__(self) -> str:
        kind = f"delay {self._delay}" if self.is_delay else self._actions
        return f"DcopEvent({self._id}, {kind})"


class Scenario(SimpleRepr):
    """An ordered list of events injected during a dynamic run."""

    _repr_fields = ("events",)

    def __init__(self, events: Optional[Iterable[DcopEvent]] = None) -> None:
        self._events = list(events) if events else []

    @property
    def events(self) -> List[DcopEvent]:
        return list(self._events)

    def add_event(self, event: DcopEvent) -> None:
        self._events.append(event)

    def __iter__(self):
        return iter(self._events)

    def __len__(self) -> int:
        return len(self._events)

    @classmethod
    def _from_repr(cls, events):
        return cls(events)

    def __eq__(self, other):
        return isinstance(other, Scenario) and other._events == self._events
