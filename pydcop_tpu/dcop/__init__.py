from .objects import (
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableDomain,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    create_agents,
    create_binary_variables,
    create_variables,
)
from .dcop import DCOP, filter_dcop, solution_cost
from .relations import (
    AsNAryFunctionRelation,
    Constraint,
    NAryFunctionRelation,
    NAryMatrixRelation,
    UnaryFunctionRelation,
    constraint_from_str,
    join,
    projection,
    relation_from_str,
)
from .scenario import DcopEvent, EventAction, Scenario
from .yamldcop import dcop_yaml, load_dcop, load_dcop_from_file
