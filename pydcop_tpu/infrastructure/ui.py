"""UI server: streams runtime events to GUI clients over WebSocket, plus
the graftwatch live metrics surface.

Role parity with /root/reference/pydcop/infrastructure/ui.py (UiServer:43): a
computation named ``_ui_<agent>`` running a per-agent WebSocket server that
(a) answers agent/computation state queries and (b) pushes cycle / value /
message events from the event bus to connected clients.

The reference depends on the ``websockets`` package; this build ships a
minimal RFC-6455 server on the stdlib (handshake + unfragmented text frames)
so the GUI protocol works without extra dependencies.

``MetricsHttpServer`` is the orchestrator's scrape endpoint (graftwatch):
``/metrics`` serves the live registry in Prometheus text format (the same
formatter ``pydcop_tpu telemetry --prom`` applies to snapshots),
``/metrics.json`` the raw snapshot, and ``/status`` the orchestrator's run
status for the ``pydcop_tpu watch`` terminal view.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import socket
import struct
import threading
from typing import Any, Callable, Dict, List, Optional

from .computations import MessagePassingComputation
from .events import event_bus

__all__ = ["UiServer", "MetricsHttpServer"]

logger = logging.getLogger("pydcop_tpu.infrastructure.ui")

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def _ws_accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _WS_MAGIC).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def _ws_encode_text(payload: str) -> bytes:
    data = payload.encode("utf-8")
    header = b"\x81"  # FIN + text opcode
    n = len(data)
    if n < 126:
        header += struct.pack("!B", n)
    elif n < 2 ** 16:
        header += struct.pack("!BH", 126, n)
    else:
        header += struct.pack("!BQ", 127, n)
    return header + data


def _ws_read_frame(conn: socket.socket) -> Optional[str]:
    """Read one text frame; None on close/error.  Client frames are masked."""
    try:
        head = conn.recv(2)
        if len(head) < 2:
            return None
        opcode = head[0] & 0x0F
        masked = head[1] & 0x80
        n = head[1] & 0x7F
        if n == 126:
            n = struct.unpack("!H", conn.recv(2))[0]
        elif n == 127:
            n = struct.unpack("!Q", conn.recv(8))[0]
        mask = conn.recv(4) if masked else b"\x00" * 4
        data = b""
        while len(data) < n:
            chunk = conn.recv(n - len(data))
            if not chunk:
                return None
            data += chunk
        if opcode == 0x8:  # close
            return None
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        return payload.decode("utf-8", errors="replace")
    except OSError:
        return None


class UiServer(MessagePassingComputation):
    """WebSocket event streamer + state query endpoint for one agent."""

    def __init__(self, agent, port: int) -> None:
        super().__init__(f"_ui_{agent.name}")
        self.agent = agent
        self.port = port
        self._clients: List[socket.socket] = []
        self._lock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def on_start(self) -> None:
        self._bus_was_enabled = event_bus.enabled
        event_bus.enabled = True
        event_bus.subscribe("computations.cycle.*", self._on_bus_event)
        event_bus.subscribe("computations.value.*", self._on_bus_event)
        event_bus.subscribe("computations.message_snd.*", self._on_bus_event)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", self.port))
        self._server.listen(4)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"ui-{self.agent.name}",
            daemon=True,
        )
        self._accept_thread.start()
        logger.info(
            "ui server for %s on ws://127.0.0.1:%s", self.agent.name,
            self.port,
        )

    def on_stop(self) -> None:
        event_bus.enabled = getattr(self, "_bus_was_enabled", False)
        event_bus.unsubscribe("computations.cycle.*", self._on_bus_event)
        event_bus.unsubscribe("computations.value.*", self._on_bus_event)
        event_bus.unsubscribe(
            "computations.message_snd.*", self._on_bus_event
        )
        with self._lock:
            for c in self._clients:
                try:
                    c.close()
                except OSError:
                    pass
            self._clients.clear()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    # -- websocket plumbing -------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True
            ).start()

    def _handshake(self, conn: socket.socket) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(1024)
            if not chunk:
                return False
            data += chunk
        headers: Dict[str, str] = {}
        for line in data.decode("latin1").split("\r\n")[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        key = headers.get("sec-websocket-key")
        if key is None:
            return False
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {_ws_accept_key(key)}\r\n\r\n"
        )
        conn.sendall(resp.encode("latin1"))
        return True

    def _client_loop(self, conn: socket.socket) -> None:
        if not self._handshake(conn):
            conn.close()
            return
        with self._lock:
            self._clients.append(conn)
        while True:
            text = _ws_read_frame(conn)
            if text is None:
                break
            try:
                req = json.loads(text)
            except json.JSONDecodeError:
                continue
            reply = self._answer(req)
            try:
                conn.sendall(_ws_encode_text(json.dumps(reply)))
            except OSError:
                break
        with self._lock:
            if conn in self._clients:
                self._clients.remove(conn)
        conn.close()

    # -- protocol ------------------------------------------------------

    def _answer(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """State queries (reference ui.py:106-134)."""
        cmd = req.get("cmd")
        if cmd == "agent":
            return {
                "cmd": "agent",
                "agent": self.agent.name,
                "computations": [
                    c.name for c in self.agent.computations
                ],
                "is_running": self.agent.is_running,
            }
        if cmd == "computations":
            return {
                "cmd": "computations",
                "computations": [
                    {
                        "name": c.name,
                        "running": c.is_running,
                        "value": getattr(c, "current_value", None),
                    }
                    for c in self.agent.computations
                ],
            }
        return {"error": f"unknown command {cmd!r}"}

    def _on_bus_event(self, topic: str, evt: Any) -> None:
        msg = json.dumps({"topic": topic, "event": repr(evt)})
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            try:
                c.sendall(_ws_encode_text(msg))
            except OSError:
                pass


class MetricsHttpServer:
    """Orchestrator scrape endpoint: ``/metrics`` (Prometheus text 0.0.4),
    ``/metrics.json`` (registry snapshot) and ``/status`` (run status from
    the orchestrator's callback).  ``port=0`` binds an ephemeral port —
    read it back from ``.port``.  The built-in routes are read-only by
    construction: every one answers GET from the registry/callback,
    nothing mutates run state.

    ``routes`` mounts extra endpoints on the same port — how graftserve
    puts its submit/result/shutdown surface next to the live metrics
    (serve/server.py): a dict mapping ``(method, path_prefix)`` to
    ``callback(path, body_bytes) -> (http_status, json_payload)``.  The
    longest matching prefix wins; built-in GET routes take precedence.

    ``snapshot_cb`` re-points ``/metrics`` + ``/metrics.json`` at a
    different snapshot source (same document shape as
    ``MetricsRegistry.snapshot()``) — how the graftfleet ``fleet`` verb
    serves the FEDERATED registry instead of this process's own
    (telemetry/federate.py); format negotiation (classic/OpenMetrics)
    is unchanged."""

    def __init__(
        self,
        port: int = 0,
        status_cb: Optional[Callable[[], Dict[str, Any]]] = None,
        host: str = "127.0.0.1",
        routes: Optional[Dict[Any, Callable]] = None,
        snapshot_cb: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.status_cb = status_cb
        self.snapshot_cb = snapshot_cb
        self.routes = dict(routes or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _dispatch_route(self, method: str, path: str) -> bool:
                """Serve from ``outer.routes``; True when a route matched
                (any outcome, including its error answer)."""
                best = None
                for (m, prefix), cb in outer.routes.items():
                    if m != method:
                        continue
                    if path == prefix or path.startswith(prefix + "/"):
                        if best is None or len(prefix) > len(best[0]):
                            best = (prefix, cb)
                if best is None:
                    return False
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                headers: Dict[str, Any] = {}
                try:
                    answer = best[1](path, body)
                    # routes answer (code, payload) or, when they need
                    # response headers (Retry-After on a structured
                    # 503), (code, payload, headers)
                    if len(answer) == 3:
                        code, payload, headers = answer
                    else:
                        code, payload = answer
                except Exception as e:  # noqa: BLE001
                    logger.exception("route %s %s failed", method, path)
                    code, payload, headers = 500, {"error": str(e)}, {}
                data = json.dumps(payload, default=str).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(str(k), str(v))
                self.end_headers()
                self.wfile.write(data)
                return True

            def do_POST(self) -> None:
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if not self._dispatch_route("POST", path):
                    self.send_response(404)
                    self.end_headers()

            def do_GET(self) -> None:
                query = (
                    self.path.split("?", 1)[1] if "?" in self.path else ""
                )
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        # OpenMetrics by content negotiation (what a
                        # Prometheus scraper requesting exemplars sends)
                        # or the explicit ?format=openmetrics; classic
                        # text 0.0.4 stays the default (graftslo)
                        from ..telemetry.prom import (
                            OPENMETRICS_CONTENT_TYPE,
                            PROMETHEUS_CONTENT_TYPE,
                        )

                        om = (
                            "format=openmetrics" in query
                            or "application/openmetrics-text"
                            in (self.headers.get("Accept") or "")
                        )
                        body = outer._metrics_text(openmetrics=om)
                        ctype = (
                            OPENMETRICS_CONTENT_TYPE if om
                            else PROMETHEUS_CONTENT_TYPE
                        )
                    elif path == "/metrics.json":
                        body = outer._metrics_json()
                        ctype = "application/json"
                    elif path in ("/status", "/"):
                        body = outer._status_json()
                        ctype = "application/json"
                    elif self._dispatch_route("GET", path):
                        return
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                except Exception as e:  # a broken callback must answer 500,
                    logger.exception("metrics endpoint %s failed", path)
                    self.send_response(500)  # not kill the server thread
                    self.end_headers()
                    self.wfile.write(str(e).encode("utf-8", "replace"))
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args) -> None:  # silence stderr
                logger.debug("metrics http: " + fmt, *args)

        class Server(ThreadingHTTPServer):
            # a serve-loop tenant fleet connects in bursts: the stdlib
            # default backlog of 5 resets concurrent submitters
            request_queue_size = 128
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"metrics-http-{self.port}",
            daemon=True,
        )
        self._thread.start()
        logger.info("metrics endpoint on http://%s:%s/metrics", host, self.port)

    def _snapshot(self) -> Dict[str, Any]:
        if self.snapshot_cb is not None:
            return self.snapshot_cb()
        from ..telemetry.metrics import metrics_registry

        return metrics_registry.snapshot()

    def _metrics_text(self, openmetrics: bool = False) -> str:
        from ..telemetry.prom import render_prometheus

        return render_prometheus(
            self._snapshot(), openmetrics=openmetrics
        )

    def _metrics_json(self) -> str:
        return json.dumps(self._snapshot(), indent=2, sort_keys=True)

    def _status_json(self) -> str:
        status = self.status_cb() if self.status_cb is not None else {}
        return json.dumps(status, default=str)

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
