"""Dormant per-step trace logging.

Role parity with /root/reference/pydcop/infrastructure/stats.py (:47-103):
a CSV trace of per-computation steps — duration, message counts/sizes and
operation counts (``op_count`` / ``nc_op_count``, the DCOP literature's
logical-time metric) — switched off unless a stats file is set.

TPU addition: the solver loop can log one row per *readback window* (k device
cycles) with the op count computed analytically from the compiled graph
(edges x domain work per cycle), since per-step python bookkeeping does not
exist on the compiled path.  ``jax.profiler`` traces (see api/bench) cover the
hardware-level view.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, TextIO

from ..telemetry.metrics import metrics_registry

__all__ = [
    "columns",
    "set_stats_file",
    "trace_computation",
    "stats_enabled",
    "trace_active",
]

# Registry twins of the CSV columns (handles created once at import; every
# write is flag-gated).  A row is routed to BOTH sinks independently: the
# CSV needs set_stats_file, the metrics need metrics_registry.enabled.
_m_steps = metrics_registry.counter(
    "stats.steps", "computation steps traced, by computation"
)
_m_step_seconds = metrics_registry.histogram(
    "stats.step_seconds", "per-step handler duration, by computation"
)
_m_msg_count = metrics_registry.counter(
    "stats.msg_count", "messages handled in traced steps, by computation"
)
_m_msg_size = metrics_registry.counter(
    "stats.msg_size", "message bytes handled in traced steps, by computation"
)
_m_op_count = metrics_registry.counter(
    "stats.op_count", "constraint-check operations, by computation"
)
_m_nc_op_count = metrics_registry.counter(
    "stats.nc_op_count", "non-concurrent operations, by computation"
)

columns: List[str] = [
    "time",
    "computation",
    "cycle",
    "duration",
    "msg_count",
    "msg_size",
    "op_count",
    "nc_op_count",
]

_lock = threading.Lock()
_file: Optional[TextIO] = None
logging_enabled = False


def stats_enabled() -> bool:
    return logging_enabled


def trace_active() -> bool:
    """True when a trace_computation row would reach ANY sink — callers use
    this to decide whether to pay for per-step timing."""
    return logging_enabled or metrics_registry.enabled


def set_stats_file(path: Optional[str]) -> None:
    """Open ``path`` for trace rows (CSV, header written once); ``None``
    disables tracing."""
    global _file, logging_enabled
    with _lock:
        if _file is not None:
            _file.close()
            _file = None
        if path is None:
            logging_enabled = False
            return
        _file = open(path, "w", encoding="utf-8")
        _file.write(",".join(columns) + "\n")
        logging_enabled = True


def trace_computation(
    computation: str,
    cycle: int,
    duration: float,
    msg_count: int = 0,
    msg_size: int = 0,
    op_count: int = 0,
    nc_op_count: int = 0,
) -> None:
    if metrics_registry.enabled:
        _m_steps.inc(computation=computation)
        _m_step_seconds.observe(duration, computation=computation)
        if msg_count:
            _m_msg_count.inc(msg_count, computation=computation)
        if msg_size:
            _m_msg_size.inc(msg_size, computation=computation)
        if op_count:
            _m_op_count.inc(op_count, computation=computation)
        if nc_op_count:
            _m_nc_op_count.inc(nc_op_count, computation=computation)
    if not logging_enabled:
        return
    row = [
        f"{time.time():.6f}",
        computation,
        str(cycle),
        f"{duration:.6f}",
        str(msg_count),
        str(msg_size),
        str(op_count),
        str(nc_op_count),
    ]
    with _lock:
        if _file is not None:
            _file.write(",".join(row) + "\n")
            _file.flush()
