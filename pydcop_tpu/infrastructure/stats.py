"""Dormant per-step trace logging.

Role parity with /root/reference/pydcop/infrastructure/stats.py (:47-103):
a CSV trace of per-computation steps — duration, message counts/sizes and
operation counts (``op_count`` / ``nc_op_count``, the DCOP literature's
logical-time metric) — switched off unless a stats file is set.

TPU addition: the solver loop can log one row per *readback window* (k device
cycles) with the op count computed analytically from the compiled graph
(edges x domain work per cycle), since per-step python bookkeeping does not
exist on the compiled path.  ``jax.profiler`` traces (see api/bench) cover the
hardware-level view.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, TextIO

__all__ = [
    "columns",
    "set_stats_file",
    "trace_computation",
    "stats_enabled",
]

columns: List[str] = [
    "time",
    "computation",
    "cycle",
    "duration",
    "msg_count",
    "msg_size",
    "op_count",
    "nc_op_count",
]

_lock = threading.Lock()
_file: Optional[TextIO] = None
logging_enabled = False


def stats_enabled() -> bool:
    return logging_enabled


def set_stats_file(path: Optional[str]) -> None:
    """Open ``path`` for trace rows (CSV, header written once); ``None``
    disables tracing."""
    global _file, logging_enabled
    with _lock:
        if _file is not None:
            _file.close()
            _file = None
        if path is None:
            logging_enabled = False
            return
        _file = open(path, "w", encoding="utf-8")
        _file.write(",".join(columns) + "\n")
        logging_enabled = True


def trace_computation(
    computation: str,
    cycle: int,
    duration: float,
    msg_count: int = 0,
    msg_size: int = 0,
    op_count: int = 0,
    nc_op_count: int = 0,
) -> None:
    if not logging_enabled:
        return
    row = [
        f"{time.time():.6f}",
        computation,
        str(cycle),
        f"{duration:.6f}",
        str(msg_count),
        str(msg_size),
        str(op_count),
        str(nc_op_count),
    ]
    with _lock:
        if _file is not None:
            _file.write(",".join(row) + "\n")
            _file.flush()
