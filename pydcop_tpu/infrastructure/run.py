"""Deployment topologies: one-call solve, threads, processes.

Role parity with /root/reference/pydcop/infrastructure/run.py: ``solve``
(:52 — one call from DCOP + algorithm name to a solved assignment through the
full runtime), ``run_local_thread_dcop`` (:145 — orchestrator + in-process
agents) and ``run_local_process_dcop`` (:225 — HTTP communication, one OS
process per agent).

TPU-first note: in every topology the *device solve* runs under the
orchestrator (one compiled scan for the whole DCOP — see orchestrator.py);
what the topology changes is where the control-plane agents live.  Thread
mode wires InProcessCommunicationLayer agents; process mode spawns one
python process per agent talking HTTP/JSON — the same management protocol
end-to-end, so it exercises serialization and transport exactly like a
multi-machine run (commands/agent.py + commands/orchestrator.py reuse these
pieces)."""

from __future__ import annotations

import logging
import multiprocessing
import time
from typing import Any, Dict, List, Optional, Union

from ..algorithms import AlgorithmDef, load_algorithm_module
from ..dcop.dcop import DCOP
from ..dcop.objects import AgentDef
from ..utils.simple_repr import from_repr, simple_repr
from .communication import HttpCommunicationLayer, InProcessCommunicationLayer
from .orchestratedagents import OrchestratedAgent
from .orchestrator import Orchestrator

__all__ = [
    "solve",
    "run_local_thread_dcop",
    "run_local_process_dcop",
    "INFINITY",
]

logger = logging.getLogger("pydcop_tpu.run")

# re-export: the default threshold lives jax-free in constants.py
from ..constants import INFINITY  # noqa: E402


def _build(dcop: DCOP, algo_def, distribution):
    """Graph + distribution from names (reference run.py:99-122)."""
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, mode=dcop.objective
        )
    algo_module = load_algorithm_module(algo_def.algo)
    import importlib

    graph_module = importlib.import_module(
        f"pydcop_tpu.computations_graph.{algo_module.GRAPH_TYPE}"
    )
    cg = graph_module.build_computation_graph(dcop)
    if isinstance(distribution, str):
        dist_module = importlib.import_module(
            f"pydcop_tpu.distribution.{distribution}"
        )
        distribution = dist_module.distribute(
            cg,
            list(dcop.agents.values()),
            hints=getattr(dcop, "dist_hints", None),
            computation_memory=getattr(
                algo_module, "computation_memory", None
            ),
            communication_load=getattr(
                algo_module, "communication_load", None
            ),
        )
    return algo_def, cg, distribution


def run_local_thread_dcop(
    algo_def: Union[str, AlgorithmDef],
    dcop: DCOP,
    distribution: Union[str, Any] = "oneagent",
    n_cycles: int = 100,
    seed: int = 0,
    collector=None,
    collect_moment: str = "value_change",
    collect_period: Optional[float] = None,
    ui_port: Optional[int] = None,
    delay: float = 0.0,
    infinity: float = 10000,
    chaos=None,
    metrics_port: Optional[int] = None,
    replication_mode: str = "distributed",
) -> Orchestrator:
    """Orchestrator + one in-process agent per AgentDef (reference :145).
    Returns the started orchestrator with all agents registered; call
    ``deploy_computations`` / ``run`` / ``stop_agents`` / ``stop`` on it.

    ``chaos``: a ``ChaosController`` (chaos/controller.py) — every agent's
    outbound transport is wrapped for fault injection, kill events crash
    the in-process agents, and the barriers degrade gracefully instead of
    raising on partial completion.

    ``metrics_port``: serve the graftwatch live surface (``/metrics``,
    ``/metrics.json``, ``/status``) from the orchestrator on this port
    (0 = ephemeral) for ``pydcop_tpu watch`` / Prometheus scrapes."""
    algo_def, cg, distribution = _build(dcop, algo_def, distribution)
    agent_defs = list(dcop.agents.values())
    orchestrator = Orchestrator(
        algo_def,
        cg,
        agent_defs,
        dcop,
        distribution=distribution,
        collector=collector,
        collect_moment=collect_moment,
        collect_period=collect_period,
        n_cycles=n_cycles,
        seed=seed,
        infinity=infinity,
        degrade_on_timeout=chaos is not None,
        metrics_port=metrics_port,
        replication_mode=replication_mode,
    )
    orchestrator.chaos = chaos
    orchestrator.start()
    for i, a in enumerate(agent_defs):
        comm = InProcessCommunicationLayer()
        if chaos is not None:
            from ..chaos.layer import ChaosCommunicationLayer

            comm = ChaosCommunicationLayer(comm, chaos)
        agent = OrchestratedAgent(
            a.name,
            comm,
            orchestrator.address,
            agent_def=a,
            ui_port=(ui_port + i) if ui_port else None,
            delay=delay,
        )
        agent.start()
        orchestrator._local_agents[a.name] = agent
    return orchestrator


def _run_process_agent(
    names: List[str],
    ports: List[int],
    orchestrator_host: str,
    orchestrator_port: int,
    agent_def_reprs: List[Any],
    trace_path: Optional[str] = None,
) -> None:
    """Agent process entry point (reference _build_process_agent:268): hosts
    one or more agents over HTTP until they are stopped.

    ``trace_path``: enable span tracing in this process and export a
    Chrome trace file on exit — one file per agent process, merged into a
    single cross-process timeline by ``pydcop_tpu telemetry stitch``
    (the freshly captured epoch pair in this new interpreter is what the
    stitcher aligns on)."""
    if trace_path is not None:
        from ..telemetry.tracing import tracer

        tracer.service = names[0] if len(names) == 1 else ",".join(names)
        tracer.reset()
        tracer.enabled = True
    agents = []
    for name, port, ad_repr in zip(names, ports, agent_def_reprs):
        comm = HttpCommunicationLayer(("127.0.0.1", port))
        agent = OrchestratedAgent(
            name,
            comm,
            (orchestrator_host, orchestrator_port),
            agent_def=from_repr(ad_repr),
        )
        agent.start()
        agents.append(agent)
    while any(a.is_running for a in agents):
        time.sleep(0.1)
    if trace_path is not None:
        from ..telemetry.tracing import tracer

        tracer.enabled = False
        try:
            tracer.export_chrome(trace_path)
        except OSError:
            logger.exception("could not write agent trace %s", trace_path)


def run_local_process_dcop(
    algo_def: Union[str, AlgorithmDef],
    dcop: DCOP,
    distribution: Union[str, Any] = "oneagent",
    n_cycles: int = 100,
    seed: int = 0,
    collector=None,
    collect_moment: str = "value_change",
    collect_period: Optional[float] = None,
    port: int = 9000,
    infinity: float = 10000,
    metrics_port: Optional[int] = None,
    trace_out: Optional[str] = None,
    replication_mode: str = "distributed",
) -> Orchestrator:
    """Orchestrator over HTTP + one OS process per agent (reference :225).
    Ports: orchestrator on ``port``, agents on ``port+1...``.  Uses the spawn
    start method like the reference's process mode (solve.py:530).

    ``trace_out``: the parent's ``--trace-out`` path; each agent process
    then traces itself and writes ``<trace_out>.<agent>.json``, so a
    multi-process run yields one trace file per process —
    ``pydcop_tpu telemetry stitch`` merges them into one timeline."""
    algo_def, cg, distribution = _build(dcop, algo_def, distribution)
    agent_defs = list(dcop.agents.values())
    comm = HttpCommunicationLayer(("127.0.0.1", port))
    orchestrator = Orchestrator(
        algo_def,
        cg,
        agent_defs,
        dcop,
        distribution=distribution,
        comm=comm,
        collector=collector,
        collect_moment=collect_moment,
        collect_period=collect_period,
        n_cycles=n_cycles,
        seed=seed,
        infinity=infinity,
        metrics_port=metrics_port,
        replication_mode=replication_mode,
    )
    orchestrator.start()
    ctx = multiprocessing.get_context("spawn")
    procs = []
    agent_traces = []
    for i, a in enumerate(agent_defs):
        trace_path = f"{trace_out}.{a.name}.json" if trace_out else None
        if trace_path:
            agent_traces.append(trace_path)
        p = ctx.Process(
            target=_run_process_agent,
            args=(
                [a.name],
                [port + 1 + i],
                "127.0.0.1",
                port,
                [simple_repr(a)],
                trace_path,
            ),
            name=f"agent-{a.name}",
            daemon=True,
        )
        p.start()
        procs.append(p)
    orchestrator._agent_processes = procs
    orchestrator._agent_trace_files = agent_traces
    return orchestrator


def solve(
    dcop: DCOP,
    algo_def: Union[str, AlgorithmDef],
    distribution: Union[str, Any] = "oneagent",
    timeout: Optional[float] = None,
    n_cycles: int = 100,
    seed: int = 0,
) -> Dict[str, Any]:
    """One-call solve through the FULL runtime — orchestrator, agents,
    deployment, device solve, metrics (reference run.py:52).  Returns the
    final assignment.  ``pydcop_tpu.api.solve`` is the faster direct path
    (no control plane); this one exists for parity and for tests of the
    runtime itself."""
    orchestrator = run_local_thread_dcop(
        algo_def, dcop, distribution, n_cycles=n_cycles, seed=seed
    )
    try:
        orchestrator.deploy_computations()
        orchestrator.run(timeout=timeout)
        assignment, _ = orchestrator.current_solution()
        return assignment
    finally:
        orchestrator.stop_agents()
        orchestrator.stop()
