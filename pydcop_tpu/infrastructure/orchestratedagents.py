"""Orchestrated agents: workers wired to an orchestrator.

Role parity with /root/reference/pydcop/infrastructure/orchestratedagents.py:
``OrchestratedAgent`` (:71 — an agent pre-wired to the orchestrator's
directory) and ``OrchestrationComputation`` (:178 — the worker-side management
endpoint ``_mgt_<agent>`` handling deploy / run / pause / resume /
replication / repair / stop and pushing ValueChange / Metrics / Stopped
messages up).

TPU-first note: deployment instantiates host-side bookkeeping computations
(``DeviceShardComputation``) — the algorithm itself runs on device under the
orchestrator (see orchestrator.py docstring).  Everything else (registration
protocol, lifecycle, metrics reporting, repair negotiation) matches the
reference's message protocol one-to-one, so multi-machine topologies and the
resilience machinery behave identically.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from ..algorithms import ComputationDef
from .agents import Agent
from .communication import CommunicationLayer, MSG_MGT, MSG_VALUE
from .computations import (
    Message,
    MessagePassingComputation,
    build_computation,
    register,
)
from .orchestrator import (
    AgentStoppedMessage,
    ComputationFinishedMessage,
    ComputationReplicatedMessage,
    DeployedMessage,
    MetricsMessage,
    ORCHESTRATOR,
    ORCHESTRATOR_MGT,
    RegisterAgentMessage,
    RepairDoneMessage,
    RepairReadyMessage,
    ValueChangeMessage,
)

__all__ = ["OrchestratedAgent", "OrchestrationComputation"]

logger = logging.getLogger("pydcop_tpu.orchestratedagents")


class OrchestrationComputation(MessagePassingComputation):
    """Management endpoint ``_mgt_<agent>`` on every orchestrated agent."""

    def __init__(self, agent: "OrchestratedAgent") -> None:
        super().__init__(f"_mgt_{agent.name}")
        self.agent = agent

    def on_start(self) -> None:
        # register with the orchestrator (the reference's retry loop,
        # agents.py:623-636, is unnecessary: the route is known up front)
        self.post_msg(
            ORCHESTRATOR_MGT,
            RegisterAgentMessage(
                agent=self.agent.name,
                address=self.agent.communication.address,
            ),
            MSG_MGT,
        )

    # -- deployment ----------------------------------------------------

    @register("deploy")  # graftproto: replies=deployed
    def _on_deploy(self, sender: str, msg, t: float) -> None:
        comp_def: ComputationDef = msg.comp_def
        comp = build_computation(comp_def)
        self.agent.add_computation(comp)
        self.agent.deployed.append(comp_def.name)
        logger.debug(
            "%s: deployed computation %s", self.agent.name, comp_def.name
        )
        # graftucs: a deployment consumes capacity — drop a now-shadowed
        # own-computation replica (migration) and shed over-capacity ones
        self.agent.replication.on_deployed(comp_def.name)
        # ack only the NEW computation: a cumulative list would make the
        # ack payloads (and the orchestrator's readiness scan) quadratic
        # in the computation count — measured 300+ s of deployment at
        # 100k computations before this
        self.post_msg(
            ORCHESTRATOR_MGT,
            DeployedMessage(
                agent=self.agent.name, computations=[comp_def.name]
            ),
            MSG_MGT,
        )

    # -- lifecycle -----------------------------------------------------

    @register("run_computations")
    def _on_run(self, sender: str, msg, t: float) -> None:
        self.agent.run_computations(msg.computations)

    @register("pause_computations")
    def _on_pause(self, sender: str, msg, t: float) -> None:
        self.agent.pause_computations(msg.computations, paused=True)

    @register("resume_computations")
    def _on_resume(self, sender: str, msg, t: float) -> None:
        self.agent.pause_computations(msg.computations, paused=False)

    @register("stop_agent")  # graftproto: replies=agent_stopped
    def _on_stop_agent(self, sender: str, msg, t: float) -> None:
        self.post_msg(
            ORCHESTRATOR_MGT,
            AgentStoppedMessage(
                agent=self.agent.name, metrics=self.agent.metrics()
            ),
            MSG_MGT,
        )
        if msg.forced:
            self.agent.stop()
        else:
            self.agent.clean_shutdown()

    @register("agent_removed")
    def _on_agent_removed(self, sender: str, msg, t: float) -> None:
        logger.info(
            "%s: removed from the system (%s)", self.agent.name, msg.reason
        )
        self.agent.clean_shutdown()

    # -- value readbacks (device solve -> bookkeeping computations) ----

    @register("value_readback_fwd")
    def _on_value_readback_fwd(self, sender: str, msg, t: float) -> None:
        comp_name, value, cost = msg.content
        try:
            comp = self.agent.computation(comp_name)
        except Exception:
            return
        handler = getattr(comp, "_on_value_readback", None)
        if handler is not None:
            # dispatching value_readback fires the computation's
            # on_value_selection hook, which the agent wrapped to push the
            # ValueChangeMessage up — no second post here
            comp.on_message(
                "_device", Message("value_readback", (value, cost)), t
            )

    # -- metrics -------------------------------------------------------

    @register("metrics_request")  # graftproto: replies=metrics
    def _on_metrics_request(self, sender: str, msg, t: float) -> None:
        self.post_msg(
            ORCHESTRATOR_MGT,
            MetricsMessage(
                agent=self.agent.name, metrics=self.agent.metrics()
            ),
            MSG_MGT,
        )

    # -- resilience ----------------------------------------------------

    @register("replication")  # graftproto: replies=replicated
    def _on_replication(self, sender: str, msg, t: float) -> None:
        self.agent.known_agents = dict(msg.agents or {})
        mode = getattr(msg, "mode", None) or "local"
        round_id = getattr(msg, "round", None)
        if mode == "distributed":
            # graftucs: the negotiation round acks asynchronously (the
            # round posts ComputationReplicatedMessage when it finishes,
            # possibly at partial k)
            self.agent.replication.start_round(
                msg.k, dict(msg.agents or {}), round_id=round_id
            )
            return  # graftproto: disable=proto-reply-gap (the 'replicated' ack is posted asynchronously by _finish_round when the negotiation completes)
        hosts = self.agent.replicate(
            msg.k, agent_defs=getattr(msg, "agent_defs", None)
        )
        self.post_msg(
            ORCHESTRATOR_MGT,
            ComputationReplicatedMessage(
                agent=self.agent.name, replica_hosts=hosts,
                round=round_id,
            ),
            MSG_MGT,
        )

    @register("store_replica")
    def _on_store_replica(self, sender: str, msg, t: float) -> None:
        comp_name, comp_def = msg.content
        owner = sender[len("_mgt_"):] if sender.startswith("_mgt_") else sender
        # through the same ledger as negotiated replicas, so retraction
        # and capacity shedding treat both replication modes alike
        self.agent.replication.adopt_replica(owner, comp_name, comp_def)

    @register("setup_repair")  # graftproto: replies=repair_ready
    def _on_setup_repair(self, sender: str, msg, t: float) -> None:
        comps = self.agent.setup_repair(msg.repair_info)
        # echo the episode's round so a late ack after a barrier
        # timeout can never release the NEXT episode's barrier
        self.post_msg(
            ORCHESTRATOR_MGT,
            RepairReadyMessage(
                agent=self.agent.name, computations=comps,
                round=(msg.repair_info or {}).get("round"),
            ),
            MSG_MGT,
        )

    @register("repair_run")  # graftproto: replies=repair_done
    def _on_repair_run(self, sender: str, msg, t: float) -> None:
        selected = self.agent.repair_run()
        repair_info = getattr(self.agent, "_repair_info", None) or {}
        self.post_msg(
            ORCHESTRATOR_MGT,
            RepairDoneMessage(
                agent=self.agent.name, selected=selected,
                round=repair_info.get("round"),
            ),
            MSG_MGT,
        )


class OrchestratedAgent(Agent):
    """An agent managed by a remote orchestrator (reference
    orchestratedagents.py:71)."""

    def __init__(
        self,
        name: str,
        comm: CommunicationLayer,
        orchestrator_address: Any,
        agent_def: Any = None,
        metrics_period: Optional[float] = None,
        ui_port: Optional[int] = None,
        delay: float = 0.0,
    ) -> None:
        super().__init__(
            name, comm, agent_def=agent_def, ui_port=ui_port, delay=delay
        )
        self.orchestrator_address = orchestrator_address
        self.deployed: List[str] = []
        self.replica_store: Dict[str, ComputationDef] = {}
        self.messaging.register_route(
            ORCHESTRATOR_MGT, ORCHESTRATOR, orchestrator_address
        )
        self.messaging.register_route(
            "_directory", ORCHESTRATOR, orchestrator_address
        )
        self.orchestration = OrchestrationComputation(self)
        self.add_computation(self.orchestration, publish=False)
        # graftucs: both halves of the replication negotiation live here
        # (owner walk + candidate capacity ledger, resilience/)
        from ..resilience.negotiation import ReplicationComputation

        self.replication = ReplicationComputation(self)
        self.add_computation(self.replication, publish=False)
        if metrics_period:
            self.add_periodic_action(
                metrics_period, self._periodic_metrics
            )

    def _on_start(self) -> None:
        super()._on_start()
        self.orchestration.start()
        self.replication.start()

    def _periodic_metrics(self) -> None:
        self.orchestration.post_msg(
            ORCHESTRATOR_MGT,
            MetricsMessage(agent=self.name, metrics=self.metrics()),
            MSG_MGT,
        )

    def on_computation_value_changed(self, name: str, value, cost) -> None:
        # per-computation ValueChange push (collection mode value_change,
        # reference orchestratedagents.py:303-322)
        self.orchestration.post_msg(
            ORCHESTRATOR_MGT,
            ValueChangeMessage(
                computation=name, value=value, cost=cost, cycle=None
            ),
            MSG_VALUE,
        )

    def on_computation_finished(self, name: str) -> None:
        # completion push (reference agents.py:870): lands in
        # AgentsMgt._finished_computations — the receive half existed
        # since the seed, but until graftproto flagged the dead
        # conversation nothing ever sent it
        self.orchestration.post_msg(
            ORCHESTRATOR_MGT,
            ComputationFinishedMessage(computation=name),
            MSG_MGT,
        )

    # -- resilience hooks (full replication layer in replication/) -----

    def replicate(
        self, k: int, agent_defs: Optional[Dict[str, Any]] = None
    ) -> Dict[str, List[str]]:
        """Centralized (``replication_mode="local"``) replica placement:
        k replicas of every hosted computation def on other agents
        (reference ResilientAgent.replicate:1042, via replication/ucs).
        The distributed protocol goes through ``self.replication``
        instead."""
        from ..replication import replicate_computations

        return replicate_computations(self, k, agent_defs=agent_defs)

    def setup_repair(self, repair_info: Any) -> List[str]:
        """Accept repair responsibility for orphaned computations this agent
        holds replicas of (reference agents.py:1047): the repair_ready
        ack names only the orphans actually present in this agent's
        replica store — candidacy is a claim about held state, not an
        echo of the orchestrator's orphan list."""
        self._repair_info = repair_info
        orphans = set(repair_info.get("orphans", []))
        return sorted(orphans & set(self.replica_store))

    def repair_run(self) -> List[str]:
        """The repair decision itself is computed on device by the
        orchestrator (reparation.repair_distribution); agents acknowledge."""
        return []
