"""Agent runtime: the host-side worker that owns computations.

Role parity with /root/reference/pydcop/infrastructure/agents.py: ``Agent``
(:78) = one thread + the agent's ``Messaging`` queue, hosting computations
(add_computation :175, run/pause/stop :354-561, clean_shutdown :431), the main
dispatch loop (:785-838), periodic actions (:840) and per-agent metrics
(:717).  ``ResilientAgent`` (replication + repair, reference :927) lives in
``resilient.py`` / the replication layer.

TPU-first scope: in the reference the agent thread IS the compute engine —
every algorithm step happens inside ``_handle_message``.  Here agents carry
control-plane computations only (management, discovery, repair negotiation);
algorithm cycles run on device under the orchestrator's scan loop, so the
50ms-poll thread costs nothing during a solve.  Agents remain real,
addressable runtime objects so deployment, discovery, metrics, scenario
events and multi-machine topologies behave exactly like the reference's.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .communication import (
    CommunicationLayer,
    Messaging,
    MSG_MGT,
    UnknownComputation,
)
from ..telemetry.tracing import tracer
from .computations import Message, MessagePassingComputation
from .discovery import Discovery
from .events import event_bus

__all__ = ["Agent", "AgentException", "AgentMetrics"]

logger = logging.getLogger("pydcop_tpu.agents")


class AgentException(Exception):
    pass


class Agent:
    """A named runtime hosting computations behind one message queue.

    The agent is single-threaded: all computation handlers run on the agent
    thread, so computations never need locks (reference agents.py:279-281 in
    computations.py).  ``start()`` spins the thread; ``add_computation``
    registers a computation with messaging + discovery and wires its
    ``message_sender``; ``clean_shutdown`` drains the queue then stops.
    """

    def __init__(
        self,
        name: str,
        comm: CommunicationLayer,
        agent_def: Any = None,
        ui_port: Optional[int] = None,
        delay: float = 0.0,
    ) -> None:
        self.name = name
        self.agent_def = agent_def
        self.communication = comm
        self.messaging = Messaging(name, comm, delay=delay)
        self.discovery = Discovery(name, comm.address)
        self._computations: Dict[str, MessagePassingComputation] = {}
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._stopping = threading.Event()
        self._shutdown_clean = False
        self._crashed = False
        self._started_evt = threading.Event()
        self.t_active = 0.0
        self._last_tick = 0.0
        self._t_started: Optional[float] = None
        self._ui_server = None
        self._ui_port = ui_port
        self._periodic_cbs: List[Dict[str, Any]] = []
        # computations with registered periodic actions, keyed by object
        # id (see add_computation: the tick scan must not be O(hosted))
        self._ticking: Dict[int, MessagePassingComputation] = {}
        # the agent's own discovery endpoint is a hosted computation
        self.add_computation(
            self.discovery.discovery_computation, publish=False
        )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def is_running(self) -> bool:
        return self._running

    def start(self) -> "Agent":
        if self._running:
            raise AgentException(f"agent {self.name} already started")
        with tracer.span("agent.start", cat="lifecycle", agent=self.name):
            self._running = True
            self._stopping.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"agent-{self.name}", daemon=True
            )
            self._thread.start()
            self._started_evt.wait(timeout=5)
            if self._ui_port:
                from .ui import UiServer

                self._ui_server = UiServer(self, self._ui_port)
                self.add_computation(self._ui_server, publish=False)
                self._ui_server.start()
        return self

    def stop(self) -> None:
        """Hard stop: the loop exits after the current message."""
        self._stopping.set()

    def clean_shutdown(self) -> None:
        """Graceful stop: process pending messages first (reference :431)."""
        self._shutdown_clean = True
        self._stopping.set()

    def crash(self) -> None:
        """Simulate abrupt process death (graftchaos kill events): no
        clean shutdown, no queue draining, and the inbound transport dies
        immediately so peers see an unreachable agent — not a politely
        closing one."""
        self._crashed = True
        self._shutdown_clean = False
        self._stopping.set()
        # a dead process hosts nothing: sealing messaging makes in-process
        # peers get UnknownComputation (and re-park) instead of feeding a
        # dead queue that reports the send as delivered
        self.messaging.seal()
        try:
            self.communication.shutdown()
        except Exception:  # a dying transport must not mask the crash
            logger.debug("%s: transport shutdown during crash", self.name)
        # graftpulse flight recorder: an abrupt agent death is exactly the
        # moment the last-K health vectors stop being reconstructible —
        # dump them now (no-op unless pulse is enabled; never raises)
        from ..telemetry.pulse import pulse

        pulse.recorder.maybe_dump(f"agent-crash:{self.name}")
        event_bus.send(f"agents.crash.{self.name}", self.name)

    def join(self, timeout: float = 5.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    # computations
    # ------------------------------------------------------------------

    def add_computation(
        self,
        computation: MessagePassingComputation,
        name: Optional[str] = None,
        publish: bool = True,
    ) -> None:
        """Host a computation: wire its sender, register it locally and
        (optionally) in the directory (reference agents.py:175)."""
        name = name or computation.name
        if computation.message_sender is None:
            computation.message_sender = self._send_from_computation
        self._computations[name] = computation
        # the tick registry holds ONLY computations with periodic actions:
        # scanning every hosted computation each 10 ms tick was O(hosted)
        # and made agents decelerate during large deployments (measured:
        # ack rate fell from ~300/s to ~30/s per agent as hosted counts
        # crossed 60k).  Computations notify on (de)registration of
        # periodic actions, so dynamic additions land here too.
        computation._periodic_registry_notify = self._update_ticking
        if computation._periodic:
            self._ticking[id(computation)] = computation
        self.messaging.register_computation(name, computation)
        self.discovery.register_computation(
            name, self.name, self.communication.address, publish=publish
        )
        hook = getattr(computation, "on_value_selection", None)
        if hook is not None:
            computation.on_value_selection = self._notify_wrap(
                computation, hook
            )
        # finished() is the computation's completion signal (reference
        # agents.py:870 wraps it at deploy time).  Until graftproto's
        # proto-unsent-message rule flagged it, nothing wrapped it here,
        # so ComputationFinishedMessage was declared + handled but never
        # on the wire — the orchestrator could not observe completion.
        fin_hook = getattr(computation, "finished", None)
        if fin_hook is not None:
            computation.finished = self._finished_wrap(
                computation, fin_hook
            )
        event_bus.send(f"agents.add_computation.{self.name}", name)

    def _notify_wrap(self, computation, hook: Callable) -> Callable:
        def wrapped(value, cost):
            hook(value, cost)
            self.on_computation_value_changed(computation.name, value, cost)

        return wrapped

    def _finished_wrap(self, computation, hook: Callable) -> Callable:
        def wrapped():
            hook()
            self.on_computation_finished(computation.name)

        return wrapped

    def on_computation_value_changed(self, name: str, value, cost) -> None:
        """Overridden by orchestrated agents to push ValueChange messages."""

    def on_computation_finished(self, name: str) -> None:
        """Overridden by orchestrated agents to push ComputationFinished
        messages up to the orchestrator."""

    def _update_ticking(self, computation) -> None:
        # keyed by object identity, not name: a computation may be hosted
        # under an alias (add_computation's ``name`` parameter)
        if computation._periodic:
            self._ticking[id(computation)] = computation
        else:
            self._ticking.pop(id(computation), None)

    def remove_computation(self, name: str) -> None:
        comp = self._computations.pop(name, None)
        if comp is None:
            return
        self._ticking.pop(id(comp), None)
        if getattr(comp, "_periodic_registry_notify", None) is not None:
            comp._periodic_registry_notify = None
        if comp.is_running:
            comp.stop()
        self.messaging.unregister_computation(name)
        self.discovery.unregister_computation(name)
        event_bus.send(f"agents.rem_computation.{self.name}", name)

    def computation(self, name: str) -> MessagePassingComputation:
        try:
            return self._computations[name]
        except KeyError:
            raise UnknownComputation(
                f"{name} not hosted on {self.name}"
            ) from None

    @property
    def computations(self) -> List[MessagePassingComputation]:
        return list(self._computations.values())

    def run_computations(self, names: Optional[List[str]] = None) -> None:
        # a set: list membership per computation made starting 50k hosted
        # computations O(n^2) — the dominant cost of orchestrator.run at
        # 400k+ variables (sampled)
        wanted = None if names is None else set(names)
        for comp in self.computations:
            if wanted is None or comp.name in wanted:
                if not comp.is_running:
                    comp.start()

    def pause_computations(
        self, names: Optional[List[str]] = None, paused: bool = True
    ) -> None:
        """Pause/unpause hosted computations.  A blanket pause
        (``names=None`` — the repair freeze) applies only to ALGORITHM
        computations: control-plane endpoints (``_mgt_``, ``_discovery_``,
        ``_replication_`` — every "_"-prefixed name) must stay live, or
        the management computation pauses ITSELF and buffers the very
        Resume that would wake it — after the first repair the whole
        control plane (stop acks, metrics, replication rounds) was
        silently wedged forever."""
        wanted = None if names is None else set(names)
        for comp in self.computations:
            if wanted is None:
                if comp.name.startswith("_"):
                    continue
                comp.pause(paused)
            elif comp.name in wanted:
                comp.pause(paused)

    # ------------------------------------------------------------------
    # messaging
    # ------------------------------------------------------------------

    def _send_from_computation(
        self, sender_comp: str, dest_comp: str, msg: Message,
        prio: Optional[int],
    ) -> None:
        self.messaging.post_msg(sender_comp, dest_comp, msg, prio)

    def _run(self) -> None:
        logger.debug("agent %s thread started", self.name)
        self._t_started = time.perf_counter()
        self._on_start()
        self._started_evt.set()
        while not self._stopping.is_set() or (
            self._shutdown_clean and not self.messaging._queue.empty()
        ):
            item = self.messaging.next_msg(timeout=0.05)
            now = time.perf_counter()
            if item is not None:
                sender, dest, msg, t = item
                t0 = time.perf_counter()
                self._handle_message(sender, dest, msg, t)
                self.t_active += time.perf_counter() - t0
            # periodic actions have >= 10 ms granularity, and only the
            # ticking registry is scanned: iterating every hosted
            # computation here was O(hosted) per 10 ms, which starved
            # message processing during 100k+-computation deployments
            if now - self._last_tick >= 0.01:
                self._last_tick = now
                for comp in list(self._ticking.values()):
                    comp._tick(now)
            for p in self._periodic_cbs:
                if now - p["last"] >= p["period"]:
                    p["last"] = now
                    p["cb"]()
            if self._shutdown_clean and self.messaging._queue.empty():
                break
        self._on_stop()
        self._running = False
        logger.debug("agent %s thread stopped", self.name)

    def _handle_message(
        self, sender: str, dest: str, msg: Message, t: float
    ) -> None:
        comp = self._computations.get(dest)
        if comp is None:
            logger.warning(
                "%s: message for unknown computation %s (%s)",
                self.name, dest, msg.type,
            )
            return
        try:
            comp.on_message(sender, msg, t)
        except Exception:
            logger.exception(
                "%s: error handling %s message in %s",
                self.name, msg.type, dest,
            )

    def add_periodic_action(self, period: float, cb: Callable) -> None:
        """Run ``cb`` every ``period`` seconds on the agent loop.  Periods
        below the loop's 10 ms tick granularity are clamped rather than
        silently degraded (ADVICE round 4)."""
        self._periodic_cbs.append(
            {"period": max(period, 0.01), "cb": cb, "last": 0.0}
        )

    # hooks -------------------------------------------------------------

    def _on_start(self) -> None:
        """Runs on the agent thread before the loop (reference :591):
        register self in local discovery."""
        self.discovery.register_agent(
            self.name, self.communication.address, publish=False
        )

    def _on_stop(self) -> None:
        if tracer.enabled:
            tracer.instant(
                "agent.stop", cat="lifecycle", agent=self.name,
                clean=self._shutdown_clean,
            )
        for comp in self.computations:
            if comp.is_running:
                comp.stop()
        self.messaging.shutdown()

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------

    def metrics(self) -> Dict[str, Any]:
        """Per-agent metrics in the reference's shape (agents.py:717):
        cumulated external message count/size per computation + activity
        ratio."""
        elapsed = (
            time.perf_counter() - self._t_started if self._t_started else 0.0
        )
        return {
            "count_ext_msg": dict(self.messaging.count_ext_msg),
            "size_ext_msg": dict(self.messaging.size_ext_msg),
            "activity_ratio": self.t_active / elapsed if elapsed else 0.0,
            "cycles": {
                c.name: getattr(c, "cycle_count", getattr(c, "_cycle", 0))
                for c in self.computations
            },
        }

    def __repr__(self) -> str:
        return f"Agent({self.name})"


class AgentMetrics:
    """Event-bus subscriber aggregating value/cycle/message events (reference
    agents.py:878) — attach to observe a running system without touching the
    agents."""

    def __init__(self) -> None:
        self.value_events: List[Any] = []
        self.cycle_events: List[Any] = []
        event_bus.subscribe("computations.value.*", self._on_value)
        event_bus.subscribe("computations.cycle.*", self._on_cycle)

    def _on_value(self, topic: str, evt: Any) -> None:
        self.value_events.append((topic, evt, time.perf_counter()))

    def _on_cycle(self, topic: str, evt: Any) -> None:
        self.cycle_events.append((topic, evt, time.perf_counter()))

    def detach(self) -> None:
        event_bus.unsubscribe("computations.value.*", self._on_value)
        event_bus.unsubscribe("computations.cycle.*", self._on_cycle)
