"""Communication layers + per-agent messaging queues (control plane).

Role parity with /root/reference/pydcop/infrastructure/communication.py:
``CommunicationLayer`` protocol with ignore/fail/retry error modes (:56-79),
``InProcessCommunicationLayer`` (:207, address = the object itself, direct
function-call delivery), ``HttpCommunicationLayer`` (:313, JSON message POST
with routing headers), message priorities (:495-497) and ``Messaging`` (:500,
per-agent priority queue, parking of messages for unknown destinations,
per-computation metrics).

TPU-first scope (SURVEY.md §5.8): this backend carries CONTROL traffic only —
registration, deployment, metrics, scenario and repair coordination.
Algorithm messages never exist host-side: a solver cycle is one XLA step and
its "message passing" is gather/scatter over ICI (parallel/mesh.py).  The
reference pushes millions of algorithm messages through this path; we push
dozens of management ones, so a stdlib ``http.server`` + ``urllib`` transport
is fully sufficient for multi-machine runs.
"""

from __future__ import annotations

import itertools
import json
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..telemetry.metrics import metrics_registry
from ..telemetry.tracing import tracer
from ..utils.simple_repr import from_repr, simple_repr
from .computations import Message
from .events import event_bus
from .retry import RetryPolicy

__all__ = [
    "MSG_DISCOVERY",
    "MSG_MGT",
    "MSG_VALUE",
    "MSG_ALGO",
    "UnreachableAgent",
    "UnknownComputation",
    "UnknownAgent",
    "CommunicationLayer",
    "InProcessCommunicationLayer",
    "HttpCommunicationLayer",
    "Messaging",
    "RetryPolicy",
    "find_local_ip",
]

logger = logging.getLogger("pydcop_tpu.infrastructure.communication")

# Priorities, lower runs first (reference communication.py:495-497 and
# discovery.py:77).
MSG_DISCOVERY = 5
MSG_MGT = 10
MSG_VALUE = 15
MSG_ALGO = 20

# Telemetry handles, created once at import (creation never requires the
# registry to be enabled): per-call get-or-create would take the registry
# lock on the million-message delivery path.  Every write below is guarded
# by an enabled-flag check first — telemetry off costs one attribute read
# (see docs/observability.md for the measured numbers).
_m_sent = metrics_registry.counter(
    "comms.messages_sent", "messages posted through Messaging, by agent"
)
_m_recv = metrics_registry.counter(
    "comms.messages_received", "messages delivered to a queue, by agent"
)
_m_bytes_sent = metrics_registry.counter(
    "comms.payload_bytes_sent", "posted message payload bytes, by agent"
)
_m_bytes_recv = metrics_registry.counter(
    "comms.payload_bytes_received",
    "delivered message payload bytes, by agent",
)
_m_queue_depth = metrics_registry.gauge(
    "comms.queue_depth", "message-queue depth at last delivery, by agent"
)
_m_latency = metrics_registry.histogram(
    "comms.delivery_seconds",
    "enqueue-to-consume latency of delivered messages, by agent",
)
_m_http_sent = metrics_registry.counter(
    "comms.http_bytes_sent", "HTTP transport bytes posted to peers"
)
_m_http_recv = metrics_registry.counter(
    "comms.http_bytes_received", "HTTP transport bytes received from peers"
)
_m_send_failures = metrics_registry.counter(
    "comms.send_failures",
    "sends abandoned after exhausting retries, by agent and destination",
)
_m_retry_attempts = metrics_registry.counter(
    "comms.retry_attempts", "transport send retries performed, by agent"
)
_m_dead_letters = metrics_registry.counter(
    "comms.dead_letters",
    "parked messages dropped by TTL expiry or buffer cap, by agent",
)
_m_parked_depth = metrics_registry.gauge(
    "comms.parked_depth", "parked-message buffer depth, by agent"
)


class UnreachableAgent(Exception):
    pass


class UnknownComputation(Exception):
    pass


class UnknownAgent(Exception):
    pass


def find_local_ip() -> str:
    """Best-effort local IP (reference communication.py:297)."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class CommunicationLayer:
    """Transport protocol: delivers (sender_comp, dest_comp, msg, prio) to the
    agent at ``address``.  ``on_error``: 'ignore' | 'fail' | 'retry'
    (reference communication.py:68-79)."""

    def __init__(self, on_error: str = "ignore") -> None:
        if on_error not in ("ignore", "fail", "retry"):
            raise ValueError(f"invalid on_error mode {on_error!r}")
        self.on_error = on_error
        self.messaging: Optional["Messaging"] = None

    @property
    def address(self) -> Any:
        raise NotImplementedError

    def send_msg(
        self,
        src_agent: str,
        dest_agent: str,
        address: Any,
        sender_comp: str,
        dest_comp: str,
        msg: Message,
        prio: int,
    ) -> bool:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass

    def deliver(
        self, src_agent: str, sender_comp: str, dest_comp: str,
        msg: Message, prio: int,
    ) -> None:
        """Hand an inbound message to the local Messaging instance.

        Raises UnknownComputation when this agent does not host the
        destination — the reference's 404 answer (communication.py:447)."""
        if self.messaging is None:
            raise UnreachableAgent("communication layer has no messaging")
        if dest_comp not in self.messaging._local_computations:
            raise UnknownComputation(dest_comp)
        self.messaging.deliver_local(sender_comp, dest_comp, msg, prio)


class InProcessCommunicationLayer(CommunicationLayer):
    """Same-process transport: the address IS the layer object and sending is
    a direct function call into the target's queue (reference
    communication.py:207-276)."""

    @property
    def address(self) -> "InProcessCommunicationLayer":
        return self

    def send_msg(
        self, src_agent, dest_agent, address, sender_comp, dest_comp, msg,
        prio,
    ) -> bool:
        if not isinstance(address, InProcessCommunicationLayer):
            raise UnreachableAgent(
                f"in-process layer cannot reach address {address!r}"
            )
        address.deliver(src_agent, sender_comp, dest_comp, msg, prio)
        return True

    def __repr__(self) -> str:
        return f"InProcessCommunicationLayer({id(self):#x})"


class _HttpHandler:
    """Request handler factory bound to a communication layer (reference
    MPCHttpHandler:447)."""

    def __new__(cls, layer: "HttpCommunicationLayer"):
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                if metrics_registry.enabled:
                    _m_http_recv.inc(length)
                try:
                    payload = json.loads(raw.decode("utf-8"))
                    msg = from_repr(payload["msg"])
                    cycle_id = payload.get("cycle_id")
                    if cycle_id is not None:
                        msg._cycle_id = cycle_id
                    trace_ctx = payload.get("trace")
                    if trace_ctx is not None:
                        # restore the sender's trace context so the
                        # delivery/consume flow points in THIS process
                        # carry the same flow_id as the remote send
                        msg._trace_ctx = tuple(trace_ctx)
                    layer.deliver(
                        payload.get("src_agent", "?"),
                        payload["sender_comp"],
                        payload["dest_comp"],
                        msg,
                        int(payload.get("prio", MSG_ALGO)),
                    )
                except UnknownComputation:
                    self.send_response(404)
                    self.end_headers()
                    return
                except Exception as e:  # malformed payload
                    logger.error("bad http message: %s", e)
                    self.send_response(400)
                    self.end_headers()
                    return
                self.send_response(204)
                self.end_headers()

            def log_message(self, fmt, *args) -> None:  # silence stderr
                logger.debug("http: " + fmt, *args)

        return Handler


class HttpCommunicationLayer(CommunicationLayer):
    """Multi-machine transport: an embedded ``http.server`` thread receives
    JSON-serialized messages; sending is one POST per message with routing
    fields in the body (reference communication.py:313-441).  Addresses are
    ``(host, port)`` tuples."""

    def __init__(
        self,
        address: Optional[Tuple[str, int]] = None,
        on_error: str = "ignore",
        retry_policy: Optional[RetryPolicy] = None,
    ) -> None:
        super().__init__(on_error)
        # applies in 'retry' mode only; the default keeps roughly the old
        # 3-attempt cadence but with exponential backoff + full jitter so
        # many senders retrying into one recovering peer do not stampede
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=3, base_delay=0.2, max_delay=2.0
        )
        from http.server import ThreadingHTTPServer

        host, port = address or ("127.0.0.1", 9000)
        self._server = ThreadingHTTPServer(
            (host, port), _HttpHandler(self)
        )
        # advertise a routable address: a wildcard bind would make remote
        # peers POST to their own loopback (reference find_local_ip:297)
        public_host = find_local_ip() if host in ("", "0.0.0.0") else host
        self._address = (public_host, self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"http-comm-{self._address[1]}",
            daemon=True,
        )
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._address

    def send_msg(
        self, src_agent, dest_agent, address, sender_comp, dest_comp, msg,
        prio,
    ) -> bool:
        import urllib.error
        import urllib.request

        host, port = address
        payload: Dict[str, Any] = {
            "src_agent": src_agent,
            "sender_comp": sender_comp,
            "dest_comp": dest_comp,
            "prio": prio,
            "msg": simple_repr(msg),
        }
        cycle_id = getattr(msg, "_cycle_id", None)
        if cycle_id is not None:
            payload["cycle_id"] = cycle_id
        trace_ctx = getattr(msg, "_trace_ctx", None)
        if trace_ctx is not None:
            payload["trace"] = list(trace_ctx)
        data = json.dumps(payload).encode("utf-8")
        if metrics_registry.enabled:
            _m_http_sent.inc(len(data))
        req = urllib.request.Request(
            f"http://{host}:{port}/pydcop",
            data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        policy = self.retry_policy
        attempts = policy.max_attempts if self.on_error == "retry" else 1
        started = policy.start()
        attempt = 0
        last_error: Optional[Exception] = None
        while True:
            try:
                with urllib.request.urlopen(req, timeout=2.0):
                    return True
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                if (
                    isinstance(e, urllib.error.HTTPError)
                    and e.code == 404
                ):
                    # receiver does not host dest_comp: the sender's
                    # Messaging parks the message for re-send on discovery
                    raise UnknownComputation(dest_comp) from e
                # any other HTTP error (5xx from a peer mid-restart) is as
                # transient as a transport error: same fail/retry/backoff
                if self.on_error == "fail":
                    raise UnreachableAgent(
                        f"cannot reach {dest_agent} at {address}: {e}"
                    ) from e
                last_error = e
                logger.warning(
                    "http send to %s failed (attempt %d/%d): %s",
                    address, attempt + 1, attempts, e,
                )
                if attempt + 1 >= attempts:
                    break
                if not policy.sleep_before_retry(attempt, started):
                    break  # deadline exhausted
                if metrics_registry.enabled:
                    _m_retry_attempts.inc(agent=src_agent)
                attempt += 1
        # exhausted: a False return is indistinguishable from success at
        # most call sites, so the giving-up itself must be loud (one ERROR
        # line) and countable (comms.send_failures)
        logger.error(
            "giving up on message %s -> %s for %s at %s after %d "
            "attempt(s): %s",
            sender_comp, dest_comp, dest_agent, address, attempt + 1,
            last_error,
        )
        if metrics_registry.enabled:
            _m_send_failures.inc(agent=src_agent, dest=dest_agent)
        return False

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __repr__(self) -> str:
        return f"HttpCommunicationLayer({self._address})"


class Messaging:
    """Per-agent messaging: one priority queue feeding the agent thread;
    routing between local delivery and the communication layer; parking of
    messages whose destination is not known yet, resent on discovery
    (reference communication.py:500-726)."""

    #: default bounds on the parked-message buffer: parking exists to
    #: bridge the deploy/discovery window (milliseconds to seconds), so
    #: anything older than the TTL is a message to a destination that
    #: will never exist — unbounded growth was a slow leak on every
    #: long-lived agent
    PARKED_CAP = 10_000
    PARKED_TTL = 30.0

    def __init__(
        self,
        agent_name: str,
        comm: CommunicationLayer,
        delay: float = 0.0,
        parked_cap: int = PARKED_CAP,
        parked_ttl: Optional[float] = PARKED_TTL,
    ) -> None:
        self.agent_name = agent_name
        self.comm = comm
        comm.messaging = self
        self.delay = delay  # artificial delay for GUI observation (:582)
        self._queue: "queue.PriorityQueue" = queue.PriorityQueue()
        self._local_computations: Dict[str, Any] = {}
        self._counter = itertools.count()  # FIFO tie-break, lock-free
        self._lock = threading.Lock()
        # computation name -> (agent name, address)
        self._routes: Dict[str, Tuple[str, Any]] = {}
        # (parked-at monotonic time, sender, dest, msg, prio), oldest first
        self._parked: List[Tuple[float, str, str, Message, int]] = []
        self._parked_cap = max(1, parked_cap)
        self._parked_ttl = parked_ttl
        self._dead_letters = 0
        self.count_ext_msg: Dict[str, int] = {}
        self.size_ext_msg: Dict[str, int] = {}
        # single-writer: only the owning agent thread pops messages
        self._consumed = 0

    @property
    def msg_queue_count(self) -> int:
        """Cumulative deliveries so far (consumed + currently queued).
        Derived, not maintained: an unsynchronized counter store in
        deliver_local could go backward under concurrent deliveries, and
        a lock there was the 1M-deployment convoy.  The consistent-read
        loop makes successive readings monotone: a snapshot where
        ``_consumed`` did not move around the qsize read measures total
        deliveries, which only grows."""
        for _ in range(100):
            c1 = self._consumed
            q = self._queue.qsize()
            if self._consumed == c1:
                return c1 + q
        return c1 + q  # consumer never idle: accept a near snapshot

    # -- topology ------------------------------------------------------

    def register_computation(self, name: str, computation: Any) -> None:
        self._local_computations[name] = computation

    def seal(self) -> None:
        """Refuse all further inbound delivery (crash simulation):
        ``CommunicationLayer.deliver`` checks ``_local_computations``, so
        clearing it makes every delivery answer ``UnknownComputation`` —
        the in-process analogue of a dead process's connection-refused /
        404.  Senders then re-park instead of dropping messages into a
        dead queue that counts them as delivered."""
        self._local_computations.clear()

    def unregister_computation(self, name: str) -> None:
        self._local_computations.pop(name, None)

    def register_route(
        self, computation: str, agent_name: str, address: Any
    ) -> None:
        """Record where a remote computation lives; flushes any parked
        messages for it (reference :710-726)."""
        with self._lock:
            self._routes[computation] = (agent_name, address)
            parked, self._parked = self._parked, []
        if parked and metrics_registry.enabled:
            _m_parked_depth.set(0, agent=self.agent_name)
        # re-post outside the lock: post_msg re-parks what still lacks a
        # route (and may recurse into this lock).  _replayed: the original
        # post already counted these messages in the telemetry sinks.
        # TTL is deliberately NOT applied here: a message that waited past
        # the TTL but whose route finally arrived is exactly the delivery
        # parking exists for (expiry happens lazily, on new parks).
        # _parked_at rides along so a re-park keeps the ORIGINAL park
        # time — otherwise every route registration would reset every
        # still-parked message's TTL clock and the bound would never bind.
        for parked_at, sender_comp, dest_comp, msg, prio in parked:
            self.post_msg(
                sender_comp, dest_comp, msg, prio, _replayed=True,
                _parked_at=parked_at,
            )

    def unregister_route(self, computation: str) -> None:
        with self._lock:
            self._routes.pop(computation, None)

    @property
    def local_computations(self) -> List[str]:
        return list(self._local_computations)

    # -- parked-message bounds ----------------------------------------

    @property
    def parked_count(self) -> int:
        with self._lock:
            return len(self._parked)

    @property
    def dead_letter_count(self) -> int:
        """Parked messages dropped by TTL expiry or the buffer cap."""
        with self._lock:
            return self._dead_letters

    def _park_locked(
        self,
        sender_comp: str,
        dest_comp: str,
        msg: Message,
        prio: int,
        parked_at: Optional[float] = None,
    ) -> List[Tuple[str, Tuple[float, str, str, Message, int]]]:
        """Park one message; returns the (reason, entry) pairs
        dead-lettered to make room — logged by the caller OUTSIDE the
        lock.  ``parked_at`` carries a replayed message's ORIGINAL park
        time so its TTL clock keeps running across re-parks; the list is
        therefore not timestamp-sorted and expiry/eviction scan it
        (bounded by the cap, and only on the no-route slow path).  Every
        caller already holds ``self._lock`` (the per-method analysis
        cannot see a caller-held guard, hence the disables)."""
        now = time.monotonic()
        dead: List[Tuple[str, Tuple[float, str, str, Message, int]]] = []
        if self._parked_ttl is not None:
            cutoff = now - self._parked_ttl
            keep = []
            for entry in self._parked:  # graftlint: disable=lock-unguarded-read
                (dead if entry[0] < cutoff else keep).append(entry)
            dead = [("ttl", e) for e in dead]
            self._parked = keep  # graftlint: disable=lock-unguarded-write
        if len(self._parked) >= self._parked_cap:  # graftlint: disable=lock-unguarded-read
            # evict the oldest: it has waited longest for a route that
            # never came, so it is the most likely to be undeliverable
            oldest = min(range(len(self._parked)), key=lambda i: self._parked[i][0])  # graftlint: disable=lock-unguarded-read
            dead.append(("cap", self._parked.pop(oldest)))  # graftlint: disable
        self._parked.append((parked_at if parked_at is not None else now, sender_comp, dest_comp, msg, prio))  # graftlint: disable=lock-unguarded-write
        self._dead_letters += len(dead)
        if metrics_registry.enabled:
            _m_parked_depth.set(len(self._parked), agent=self.agent_name)  # graftlint: disable=lock-unguarded-read
        return dead

    def _report_dead_letters(
        self,
        dead: List[Tuple[str, Tuple[float, str, str, Message, int]]],
    ) -> None:
        for reason, (_parked_at, sender_comp, dest_comp, msg, _prio) in dead:
            logger.error(
                "%s: dead-lettered parked message %s -> %s (%s, %s)",
                self.agent_name, sender_comp, dest_comp, msg.type,
                "no route within TTL" if reason == "ttl"
                else "parked buffer full",
            )
            if metrics_registry.enabled:
                _m_dead_letters.inc(agent=self.agent_name)

    # -- sending -------------------------------------------------------

    def post_msg(
        self,
        sender_comp: str,
        dest_comp: str,
        msg: Message,
        prio: Optional[int] = None,
        *,
        _replayed: bool = False,
        _parked_at: Optional[float] = None,
    ) -> None:
        prio = MSG_ALGO if prio is None else prio
        # the documented ``computations.message_snd.<name>`` topic
        # (events.py) is published HERE, at the transport layer, so every
        # message — computation traffic and management messages posted
        # straight to Messaging — is observed exactly once: a message that
        # parks (no route yet, or a 404 re-park) re-enters through
        # register_route's flush with ``_replayed=True`` and is not
        # counted again
        if not _replayed:
            if event_bus.enabled:
                event_bus.send(
                    f"computations.message_snd.{sender_comp}",
                    (dest_comp, msg.type),
                )
            if metrics_registry.enabled:
                _m_sent.inc(agent=self.agent_name)
                _m_bytes_sent.inc(
                    getattr(msg, "size", 0) or 0, agent=self.agent_name
                )
            if tracer.enabled:
                # stamp the envelope with a compact trace context —
                # (trace_id, flow_id, send wall-clock, parent span) — and
                # emit the flow START anchored to a comms.send micro-slice
                # on this (sending) thread.  The context rides the message
                # across parks, replays and the HTTP transport, so the
                # delivery/consume points pair up by flow_id even in a
                # different process; a re-park keeps the ORIGINAL context
                # (one logical message == one flow).
                ctx = getattr(msg, "_trace_ctx", None)
                if ctx is None:
                    ctx = (
                        tracer.trace_id,
                        tracer.new_flow_id(),
                        time.time(),
                        tracer.current_span(),
                    )
                    try:
                        msg._trace_ctx = ctx
                    except AttributeError:
                        pass  # slotted message type: flow still recorded
                tracer.flow_point(
                    "s", "comms.send", ctx[1], src=sender_comp,
                    dest=dest_comp, type=msg.type, agent=self.agent_name,
                )
        if dest_comp in self._local_computations:
            self.deliver_local(sender_comp, dest_comp, msg, prio)
            return
        # lock-free fast path for the route lookup (a dict read): during
        # a 1M-computation deployment every agent thread posts acks
        # through here, and taking the lock per message formed a lock
        # convoy that turned deployment super-linear (sampled: the lock
        # acquisition dominated all useful work)
        route = self._routes.get(dest_comp)  # graftlint: disable=lock-unguarded-read
        if route is None:
            dead = None
            with self._lock:
                # re-check under the lock register_route swaps the parked
                # list under, so a message can never fall between the
                # route write and the flush (reference :637-650)
                route = self._routes.get(dest_comp)
                if route is None:
                    logger.debug(
                        "%s: parking message %s -> %s", self.agent_name,
                        sender_comp, dest_comp,
                    )
                    dead = self._park_locked(
                        sender_comp, dest_comp, msg, prio,
                        parked_at=_parked_at,
                    )
            if dead is not None:
                self._report_dead_letters(dead)
                return
        dest_agent, address = route
        try:
            delivered = self.comm.send_msg(
                self.agent_name, dest_agent, address, sender_comp,
                dest_comp, msg, prio,
            )
        except UnknownComputation:
            # destination moved or not deployed yet (receiver answered the
            # reference's 404): drop the stale route and park for re-send
            # once discovery updates it (reference :637-650)
            logger.info(
                "%s: %s not (yet) at %s, parking message from %s",
                self.agent_name, dest_comp, dest_agent, sender_comp,
            )
            with self._lock:
                self._routes.pop(dest_comp, None)
                dead = self._park_locked(
                    sender_comp, dest_comp, msg, prio, parked_at=_parked_at
                )
            self._report_dead_letters(dead)
            return
        if delivered and prio > MSG_MGT:
            # metrics track algorithm/value traffic only; management
            # and discovery messages are overhead, not workload
            # (reference communication.py, pinned by the reference's
            # test_do_not_count_mgt_messages).  Counted AFTER a successful
            # send so a 404 re-park + register_route replay cannot count
            # the same logical message twice (its replay is the one and
            # only successful send)
            with self._lock:
                self.count_ext_msg[sender_comp] = (
                    self.count_ext_msg.get(sender_comp, 0) + 1
                )
                self.size_ext_msg[sender_comp] = (
                    self.size_ext_msg.get(sender_comp, 0) + msg.size
                )

    # -- receiving -----------------------------------------------------

    def deliver_local(
        self, sender_comp: str, dest_comp: str, msg: Message, prio: int
    ) -> None:
        if self.delay:
            time.sleep(self.delay)
        # ``computations.message_rcv.<name>``: the receive-side twin of the
        # post_msg publication above, fired at delivery (covers remote
        # inbound via CommunicationLayer.deliver too).  All three sinks are
        # flag-gated: this is the million-message path where an
        # unconditional lock was the deployment convoy.
        if event_bus.enabled:
            event_bus.send(
                f"computations.message_rcv.{dest_comp}",
                (sender_comp, msg.type),
            )
        if metrics_registry.enabled:
            _m_recv.inc(agent=self.agent_name)
            _m_bytes_recv.inc(
                getattr(msg, "size", 0) or 0, agent=self.agent_name
            )
            _m_queue_depth.set(
                self._queue.qsize() + 1, agent=self.agent_name
            )
        if tracer.enabled:
            # transport arrival: a flow STEP on the delivering thread (the
            # sender's thread in-process; the http server thread remotely).
            # The consume point in next_msg emits the finish on the OWNING
            # agent's thread — the receiving agent's track in Perfetto.
            ctx = getattr(msg, "_trace_ctx", None)
            if ctx is not None:
                tracer.flow_point(
                    "t", "comms.recv", ctx[1], src=sender_comp,
                    dest=dest_comp, type=msg.type, agent=self.agent_name,
                )
            else:
                tracer.instant(
                    "comms.recv", cat="comms", src=sender_comp,
                    dest=dest_comp, type=msg.type,
                )
        # LOCK-FREE: itertools.count() is atomic under the GIL, and the
        # queue has its own (short-hold) mutex.  Serializing every
        # delivery through self._lock was the deployment bottleneck at
        # 1M computations — 9 threads funneling 2M+ control messages
        # into the orchestrator formed a lock convoy.
        self._queue.put(
            (
                prio, next(self._counter), time.perf_counter(),
                sender_comp, dest_comp, msg,
            )
        )

    def next_msg(
        self, timeout: float = 0.05
    ) -> Optional[Tuple[str, str, Message, float]]:
        """Pop the highest-priority pending message (the agent loop's 50ms
        poll, reference agents.py:785-795)."""
        try:
            prio, _, t, sender, dest, msg = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        self._consumed += 1  # single consumer: the owning agent thread
        if metrics_registry.enabled:
            _m_latency.observe(
                time.perf_counter() - t, agent=self.agent_name
            )
        if tracer.enabled:
            ctx = getattr(msg, "_trace_ctx", None)
            if ctx is not None:
                # the paired delivery span on the RECEIVING agent's track:
                # next_msg runs on the owning agent thread, so the flow
                # FINISH lands where the message is actually consumed.
                # latency_ms spans send→consume on the wall clock (the
                # only clock that crosses processes).
                tracer.flow_point(
                    "f", "comms.delivery", ctx[1], src=sender,
                    dest=dest, type=msg.type, agent=self.agent_name,
                    latency_ms=round((time.time() - ctx[2]) * 1000.0, 3),
                )
        return sender, dest, msg, t

    def computation(self, name: str) -> Any:
        try:
            return self._local_computations[name]
        except KeyError:
            raise UnknownComputation(name) from None

    def shutdown(self) -> None:
        self.comm.shutdown()
