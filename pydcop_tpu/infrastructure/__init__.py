"""Host-side runtime: agents, communication, discovery, orchestration.

The TPU build's control plane (SURVEY.md §2.5) — the reference's
``pydcop/infrastructure/`` re-designed so that algorithm cycles run on device
(compiled scans, parallel/mesh.py collectives) while deployment, discovery,
metrics, scenarios and resilience stay faithful, host-side, message-passing
protocols.
"""

from .agents import Agent, AgentException, AgentMetrics
from .communication import (
    CommunicationLayer,
    HttpCommunicationLayer,
    InProcessCommunicationLayer,
    Messaging,
    MSG_ALGO,
    MSG_DISCOVERY,
    MSG_MGT,
    MSG_VALUE,
)
from .computations import (
    ComputationException,
    DcopComputation,
    Message,
    MessagePassingComputation,
    SynchronousComputationMixin,
    VariableComputation,
    build_computation,
    message_type,
    register,
)
from .discovery import Directory, DirectoryComputation, Discovery
from .events import EventDispatcher, event_bus
from .orchestratedagents import OrchestratedAgent, OrchestrationComputation
from .orchestrator import AgentsMgt, Orchestrator
from .run import run_local_process_dcop, run_local_thread_dcop, solve

__all__ = [
    "Agent",
    "AgentException",
    "AgentMetrics",
    "AgentsMgt",
    "CommunicationLayer",
    "ComputationException",
    "DcopComputation",
    "Directory",
    "DirectoryComputation",
    "Discovery",
    "EventDispatcher",
    "HttpCommunicationLayer",
    "InProcessCommunicationLayer",
    "Message",
    "MessagePassingComputation",
    "Messaging",
    "MSG_ALGO",
    "MSG_DISCOVERY",
    "MSG_MGT",
    "MSG_VALUE",
    "OrchestratedAgent",
    "OrchestrationComputation",
    "Orchestrator",
    "SynchronousComputationMixin",
    "VariableComputation",
    "build_computation",
    "event_bus",
    "message_type",
    "register",
    "run_local_process_dcop",
    "run_local_thread_dcop",
    "solve",
]
