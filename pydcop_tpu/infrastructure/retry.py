"""Retry policies: exponential backoff with jitter and attempt/deadline caps.

Replaces the hardcoded 3-attempts-linear-sleep loop that used to live in
``HttpCommunicationLayer.send_msg``: transports (and anything else that
retries) take a :class:`RetryPolicy` so operators can tune attempts,
backoff shape and total budget, and chaos tests can pin a seed for
reproducible sleep sequences.

Jitter modes (AWS architecture-blog taxonomy):

- ``full``: sleep ~ U(0, backoff) — best collision avoidance, the
  default.
- ``equal``: sleep ~ backoff/2 + U(0, backoff/2) — bounded below, for
  callers that must guarantee a minimum spacing.
- ``none``: sleep = backoff exactly — deterministic, for tests.

Stdlib-only (imported by host-only CLI verbs through communication.py).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["RetryPolicy"]

_JITTER_MODES = ("full", "equal", "none")


@dataclass
class RetryPolicy:
    """Backoff schedule for retried operations.

    ``max_attempts`` counts the first try: 3 means one try + two
    retries.  ``deadline`` (seconds) caps the whole operation including
    sleeps — :meth:`start` + :meth:`sleep_before_retry` enforce it.
    ``seed`` pins the jitter PRNG for reproducible schedules."""

    max_attempts: int = 3
    base_delay: float = 0.1
    max_delay: float = 2.0
    deadline: Optional[float] = None
    jitter: str = "full"
    seed: Optional[int] = None
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.jitter not in _JITTER_MODES:
            raise ValueError(
                f"invalid jitter mode {self.jitter!r}: "
                f"expected one of {_JITTER_MODES}"
            )
        self._rng = random.Random(self.seed)

    def backoff(self, attempt: int) -> float:
        """Upper bound of the sleep after failed attempt ``attempt``
        (0-based): min(max_delay, base_delay * 2**attempt)."""
        return min(self.max_delay, self.base_delay * (2.0 ** attempt))

    def sleep_duration(self, attempt: int) -> float:
        """One jittered sleep for the given failed attempt."""
        cap = self.backoff(attempt)
        if self.jitter == "none":
            return cap
        if self.jitter == "equal":
            return cap / 2.0 + self._rng.uniform(0.0, cap / 2.0)
        return self._rng.uniform(0.0, cap)

    # -- deadline-aware driving ----------------------------------------

    def start(self) -> float:
        """Mark the start of an operation; pass the returned token to
        :meth:`sleep_before_retry`."""
        return time.monotonic()

    def sleep_before_retry(self, attempt: int, started: float) -> bool:
        """Sleep between failed attempt ``attempt`` and the next one.
        Returns False — without sleeping — when no attempt remains
        (attempt cap or deadline exhausted), True after sleeping."""
        if attempt + 1 >= self.max_attempts:
            return False
        duration = self.sleep_duration(attempt)
        if self.deadline is not None:
            remaining = self.deadline - (time.monotonic() - started)
            if remaining <= 0:
                return False
            duration = min(duration, remaining)
        if duration > 0:
            time.sleep(duration)
        return True
