"""Message-passing computation substrate (host side).

Role parity with /root/reference/pydcop/infrastructure/computations.py:
``Message``/``message_type`` (:53,:122), handler registration via ``@register``
and a collecting metaclass (:237,:576), ``MessagePassingComputation`` lifecycle
with pause buffering and periodic actions (:261), ``SynchronousComputationMixin``
(:633), ``DcopComputation``/``VariableComputation`` (:832,:967) and
``build_computation`` (:1156).

TPU-first inversion (SURVEY.md §2.8): in the reference EVERY algorithm runs as
message-passing computations on this substrate — millions of python dispatches
per solve.  Here the substrate carries only *control-plane* traffic
(registration, deployment, metrics, scenario/repair coordination, discovery):
algorithm cycles execute on device as compiled scans, where a "message" is a
row of an ``[n_edges, D]`` array and never touches these classes.  What
remains host-side is exactly the part of the reference that is NOT
performance-critical, so a faithful event-driven design is the right tool.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..algorithms import ComputationDef
from ..utils.simple_repr import SimpleRepr, simple_repr
from . import stats
from .events import event_bus

__all__ = [
    "Message",
    "message_type",
    "register",
    "ComputationException",
    "MessagePassingComputation",
    "SynchronousComputationMixin",
    "SynchronizationMsg",
    "DcopComputation",
    "VariableComputation",
    "build_computation",
]

logger = logging.getLogger("pydcop_tpu.infrastructure.computations")


class ComputationException(Exception):
    pass


class Message(SimpleRepr):
    """Base message: a type tag + optional content.  ``size`` feeds the
    communication metrics (reference computations.py:53-121)."""

    _repr_fields = ("msg_type", "content")

    def __init__(self, msg_type: str, content: Any = None) -> None:
        self._msg_type = msg_type
        self._content = content

    @property
    def type(self) -> str:
        return self._msg_type

    @property
    def msg_type(self) -> str:
        return self._msg_type

    @property
    def content(self) -> Any:
        return self._content

    @property
    def size(self) -> int:
        return 1

    @classmethod
    def _from_repr(cls, msg_type, content):
        return cls(msg_type, content)

    def __repr__(self) -> str:
        return f"Message({self._msg_type}, {self._content})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Message)
            and self.type == other.type
            and self.content == other.content
        )


class _MsgRegistry:
    """Attribute bag holding every ``message_type``-created class so that
    ``from_repr`` can resolve them by qualname
    (``_msg_registry.<type_name>``) — dynamic classes are not module-level
    names in their defining module."""


_msg_registry = _MsgRegistry()


def message_type(name: str, fields: List[str]):
    """Class factory for message types (reference computations.py:122):

        ValueMsg = message_type("value", ["value", "cost"])
        m = ValueMsg(value=3, cost=1.5); m.value, m.type
    """
    existing = getattr(_msg_registry, name, None)
    if existing is not None:
        if tuple(existing._repr_fields) != tuple(fields):
            raise ValueError(
                f"message type {name!r} already defined with fields "
                f"{existing._repr_fields}"
            )
        return existing

    def __init__(self, *args, **kwargs):
        named = dict(zip(fields, args))
        overlap = set(named) & set(kwargs)
        if overlap:
            raise TypeError(f"duplicate argument(s) {sorted(overlap)}")
        named.update(kwargs)
        unknown = set(named) - set(fields)
        if unknown:
            raise TypeError(f"unexpected argument(s) {sorted(unknown)}")
        missing = set(fields) - set(named)
        if missing:
            raise TypeError(f"missing argument(s) {sorted(missing)}")
        Message.__init__(self, name, None)
        for f in fields:
            setattr(self, "_" + f, named[f])

    def _make_prop(f):
        return property(lambda self: getattr(self, "_" + f))

    def _size(self) -> int:
        total = 0
        for f in fields:
            v = getattr(self, "_" + f)
            try:
                total += len(v)
            except TypeError:
                total += 1
        return total

    def _eq(self, other) -> bool:
        return type(other).__name__ == type(self).__name__ and all(
            getattr(other, f, None) == getattr(self, f) for f in fields
        )

    namespace: Dict[str, Any] = {
        "__init__": __init__,
        "_repr_fields": tuple(fields),
        "size": property(_size),
        "__eq__": _eq,
        "__hash__": None,
        "__repr__": lambda self: (
            name
            + "("
            + ", ".join(f"{f}={getattr(self, f)!r}" for f in fields)
            + ")"
        ),
    }
    for f in fields:
        namespace[f] = _make_prop(f)
    cls = type(name, (Message,), namespace)
    cls._from_repr = classmethod(lambda c, **kw: c(**kw))
    cls.__module__ = __name__
    cls.__qualname__ = f"_msg_registry.{name}"
    setattr(_msg_registry, name, cls)
    return cls


def register(msg_type: str):
    """Decorator marking a method as the handler for ``msg_type`` messages
    (reference computations.py:576)."""

    def deco(fn):
        fn._handles_msg_type = msg_type
        return fn

    return deco


class _HandlerCollector(type):
    """Metaclass collecting ``@register``-decorated handlers into
    ``_msg_handlers`` (reference ComputationMetaClass:237)."""

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        handlers: Dict[str, Callable] = {}
        for base in reversed(cls.__mro__):
            for attr in vars(base).values():
                t = getattr(attr, "_handles_msg_type", None)
                if t is not None:
                    handlers[t] = attr
        cls._msg_handlers = handlers
        return cls


class MessagePassingComputation(metaclass=_HandlerCollector):
    """A named computation that receives messages through ``on_message`` and
    sends through a pluggable ``message_sender`` (wired by the hosting Agent).

    Lifecycle: ``start`` -> (``pause``/``unpause``) -> ``stop``.  While paused,
    incoming and outgoing messages are buffered and delivered on unpause
    (reference computations.py:304-305,517-544).  Computations are
    single-threaded by design — the hosting agent serializes all calls — so no
    handler needs to be thread-safe (reference :279-281).
    """

    def __init__(self, name: str) -> None:
        self._name = name
        self._running = False
        self._paused = False
        self._stopped = False
        self._msg_sender: Optional[Callable] = None
        self._paused_in: List[Tuple[str, Message, float]] = []
        self._paused_out: List[Tuple[str, Message, int]] = []
        self._periodic: List[Dict[str, Any]] = []
        self.msg_count = 0

    # -- identity ------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def is_running(self) -> bool:
        return self._running

    @property
    def is_paused(self) -> bool:
        return self._paused

    @property
    def message_sender(self) -> Optional[Callable]:
        return self._msg_sender

    @message_sender.setter
    def message_sender(self, sender: Callable) -> None:
        if self._msg_sender is not None and sender is not self._msg_sender:
            raise AttributeError("message_sender can only be set once")
        self._msg_sender = sender

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._running = True
        self.on_start()

    def stop(self) -> None:
        self._running = False
        self._stopped = True
        self.on_stop()

    def pause(self, paused: bool = True) -> None:
        was = self._paused
        self._paused = paused
        if was and not paused:
            out, self._paused_out = self._paused_out, []
            for target, msg, prio in out:
                self.post_msg(target, msg, prio)
            inc, self._paused_in = self._paused_in, []
            for sender, msg, t in inc:
                self.on_message(sender, msg, t)

    def on_start(self) -> None:  # override points
        pass

    def on_stop(self) -> None:
        pass

    def on_pause(self, paused: bool) -> None:
        pass

    def finished(self) -> None:
        """Signal completion to the hosting agent (wrapped with notification
        hooks at deploy, reference agents.py:870)."""

    # -- messaging -----------------------------------------------------

    def on_message(self, sender: str, msg: Message, t: float) -> None:
        if self._paused:
            self._paused_in.append((sender, msg, t))
            return
        self.msg_count += 1
        # ``computations.message_rcv.<name>`` is published by the transport
        # (communication.py deliver_local), not here: publishing per layer
        # would double-count every message for bus subscribers
        handler = self._msg_handlers.get(msg.type)
        if handler is None:
            raise ComputationException(
                f"computation {self.name} has no handler for message "
                f"type {msg.type!r}"
            )
        # per-step trace row (reference stats.py:47-103 schema): one
        # handled message = one step; duration measured around the
        # handler, size from the message's own accounting.  cycle_count
        # is the synchronous mixin's integer round counter (plain async
        # computations have no rounds: 0)
        traced = stats.trace_active()
        t0 = time.perf_counter() if traced else 0.0
        handler(self, sender, msg, t)
        if traced:
            stats.trace_computation(
                self.name,
                int(getattr(self, "cycle_count", 0) or 0),
                time.perf_counter() - t0,
                msg_count=1,
                msg_size=getattr(msg, "size", 0) or 0,
            )

    def post_msg(
        self, target: str, msg: Message, prio: Optional[int] = None
    ) -> None:
        if self._paused:
            self._paused_out.append((target, msg, prio))
            return
        if self._msg_sender is None:
            raise ComputationException(
                f"computation {self.name} is not hosted: no message sender"
            )
        # ``computations.message_snd.<name>`` is published by the transport
        # (communication.py post_msg), which this sender routes into
        self._msg_sender(self.name, target, msg, prio)

    # -- periodic actions ---------------------------------------------

    def add_periodic_action(self, period: float, cb: Callable) -> Callable:
        """Register ``cb`` to run every ``period`` seconds while running; the
        hosting agent's loop drives these (reference computations.py:546).

        Granularity is 10 ms: the agent loop ticks computations at most
        every 0.01 s (agents.py agent loop), so shorter periods are
        clamped — they would silently degrade to the tick rate anyway
        (ADVICE round 4)."""
        self._periodic.append(
            {"period": max(period, 0.01), "cb": cb, "last": 0.0}
        )
        self._notify_periodic_registry()
        return cb

    def remove_periodic_action(self, cb: Callable) -> None:
        self._periodic = [p for p in self._periodic if p["cb"] is not cb]
        self._notify_periodic_registry()

    def _notify_periodic_registry(self) -> None:
        # the hosting agent keeps a registry of computations with periodic
        # actions so its 10 ms tick never scans every hosted computation
        # (agents.py add_computation)
        notify = getattr(self, "_periodic_registry_notify", None)
        if notify is not None:
            notify(self)

    def _tick(self, now: float) -> None:
        if not self._running or self._paused:
            return
        for p in self._periodic:
            if now - p["last"] >= p["period"]:
                p["last"] = now
                p["cb"]()

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name})"


SynchronizationMsg = message_type("_sync", ["cycle_id"])


class SynchronousComputationMixin:
    """Round-based (BSP) execution emulated on the async substrate.

    Parity with the reference's mixin (computations.py:633): every algorithm
    message is stamped with the sender's ``cycle_id``; a computation switches
    to cycle ``c+1`` once it holds one message from every neighbor for cycle
    ``c``, sending ``SynchronizationMsg`` padding to neighbors it has nothing
    to say to.  Messages one cycle ahead are buffered; skew beyond one cycle
    raises (a protocol race, reference :698-725).

    On the TPU solve path this machinery is unnecessary — a compiled scan step
    IS the round — so the mixin only serves host-side protocols (e.g. the
    MGM-2 repair negotiation) and tests.
    """

    @property
    def cycle_count(self) -> int:
        return getattr(self, "_cycle_count", 0)

    @property
    def current_cycle(self) -> Dict[str, Message]:
        return getattr(self, "_cycle_msgs", {})

    def synchronized_neighbors(self) -> List[str]:
        """Neighbor computation names participating in the rounds."""
        raise NotImplementedError

    def start_cycle(self) -> None:
        self._cycle_count = getattr(self, "_cycle_count", 0)
        self._cycle_msgs: Dict[str, Message] = {}
        self._next_msgs: Dict[str, Message] = {}
        self._sent_this_cycle: set = set()

    def post_sync_msg(
        self, target: str, msg: Message, prio: Optional[int] = None
    ) -> None:
        """Send an algorithm message stamped with the current cycle."""
        msg._cycle_id = self.cycle_count
        self._sent_this_cycle.add(target)
        self.post_msg(target, msg, prio)

    def _pad_sync(self) -> None:
        for n in self.synchronized_neighbors():
            if n not in self._sent_this_cycle:
                m = SynchronizationMsg(cycle_id=self.cycle_count)
                m._cycle_id = self.cycle_count
                self.post_msg(n, m)
        self._sent_this_cycle = set()

    @register("_sync")
    def _on_sync_padding(self, sender: str, msg: Message, t: float) -> None:
        """Default route for bare ``_sync`` padding messages: they carry no
        algorithm payload, so every mixin user buffers them the same way.
        (Before this handler existed the padding was silently dropped
        unless each concrete computation re-registered ``_sync`` itself —
        the exact protocol hole graftlint's proto-unhandled-message rule
        flagged.)  Concrete classes may still override with their own
        ``@register("_sync")`` handler; the collector keeps the subclass
        one."""
        if not hasattr(self, "_cycle_msgs"):
            # padding for a round protocol this computation never started
            # (start_cycle not called): drop it loudly instead of
            # crashing the agent thread
            logger.warning(
                "%s: _sync padding from %s before start_cycle()",
                self.name, sender,
            )
            return
        self.on_sync_message(sender, msg, t)

    def on_sync_message(self, sender: str, msg: Message, t: float) -> None:
        """Route an incoming algorithm message into the cycle buffers; call
        from the concrete computation's handlers."""
        cycle_id = getattr(msg, "_cycle_id", self.cycle_count)
        if cycle_id == self.cycle_count:
            if sender in self._cycle_msgs:
                raise ComputationException(
                    f"{self.name}: two messages from {sender} in cycle "
                    f"{self.cycle_count}"
                )
            self._cycle_msgs[sender] = msg
        elif cycle_id == self.cycle_count + 1:
            if sender in self._next_msgs:
                raise ComputationException(
                    f"{self.name}: two messages from {sender} in cycle "
                    f"{cycle_id}"
                )
            self._next_msgs[sender] = msg
        else:
            raise ComputationException(
                f"{self.name}: message from {sender} for cycle {cycle_id} "
                f"while in cycle {self.cycle_count} (skew > 1)"
            )
        if set(self._cycle_msgs) >= set(self.synchronized_neighbors()):
            cycle_msgs = self._cycle_msgs
            self._cycle_count += 1
            self._cycle_msgs = self._next_msgs
            self._next_msgs = {}
            event_bus.send(
                f"computations.cycle.{self.name}", self._cycle_count
            )
            self.on_new_cycle(cycle_msgs, self._cycle_count)
            self._pad_sync()

    def on_new_cycle(self, messages: Dict[str, Message], cycle_id: int):
        """Called once per completed round with that round's messages."""
        raise NotImplementedError


class DcopComputation(MessagePassingComputation):
    """A computation attached to a node of a computation graph (reference
    computations.py:832): knows its neighbors and footprint."""

    def __init__(self, name: str, comp_def: Optional[ComputationDef]) -> None:
        super().__init__(name)
        self.computation_def = comp_def
        self._cycle = 0

    @property
    def neighbors(self) -> List[str]:
        if self.computation_def is None:
            return []
        return list(self.computation_def.node.neighbors)

    def footprint(self) -> float:
        """Memory footprint from the algorithm module's ``computation_memory``
        (reference computations.py:1019-1056)."""
        if self.computation_def is None:
            return 0.0
        from ..algorithms import load_algorithm_module

        mod = load_algorithm_module(self.computation_def.algo.algo)
        fn = getattr(mod, "computation_memory", None)
        if fn is None:
            return 0.0
        try:
            return float(fn(self.computation_def.node))
        except (NotImplementedError, ValueError):
            return 0.0

    def new_cycle(self) -> None:
        self._cycle += 1
        event_bus.send(f"computations.cycle.{self.name}", self._cycle)

    def post_to_all_neighbors(
        self, msg: Message, prio: Optional[int] = None
    ) -> None:
        for n in self.neighbors:
            self.post_msg(n, msg, prio)


class VariableComputation(DcopComputation):
    """A computation responsible for selecting one variable's value
    (reference computations.py:967).  ``value_selection`` fires the event bus
    and the agent's notification hooks."""

    def __init__(self, variable, comp_def: Optional[ComputationDef] = None):
        name = variable.name if comp_def is None else comp_def.node.name
        super().__init__(name, comp_def)
        self._variable = variable
        self.current_value: Any = None
        self.current_cost: Optional[float] = None
        self._previous_values: List[Any] = []

    @property
    def variable(self):
        return self._variable

    @property
    def previous_values(self) -> List[Any]:
        return list(self._previous_values)

    def value_selection(self, value: Any, cost: float = 0.0) -> None:
        if value != self.current_value:
            self._previous_values.append(self.current_value)
        self.current_value = value
        self.current_cost = cost
        event_bus.send(
            f"computations.value.{self.name}", (value, cost)
        )
        self.on_value_selection(value, cost)

    def on_value_selection(self, value: Any, cost: float) -> None:
        """Hook wrapped by the hosting agent to push ValueChange messages to
        the orchestrator (reference agents.py:870)."""


class DeviceShardComputation(DcopComputation):
    """Host-side stand-in for a computation whose algorithm executes on
    device.

    In the reference, deploying a ComputationDef instantiates a python object
    that will run the algorithm (computations.py:1156).  Here the algorithm
    advances as batched device arrays; the deployed object only (a) anchors
    the computation in discovery/metrics/distribution bookkeeping and (b)
    receives the per-cycle value readbacks the orchestrator publishes, so the
    rest of the control plane (UI, metrics modes, repair) sees exactly the
    same events as in the reference.
    """

    current_value: Any = None
    current_cost: Optional[float] = None

    @register("value_readback")
    def _on_value_readback(self, sender: str, msg: Message, t: float) -> None:
        value, cost = msg.content
        self.current_value = value
        self.current_cost = cost
        event_bus.send(f"computations.value.{self.name}", (value, cost))
        self.on_value_selection(value, cost)

    def on_value_selection(self, value: Any, cost: float) -> None:
        """Hook wrapped by the hosting agent (same contract as
        VariableComputation.on_value_selection)."""


def build_computation(comp_def: ComputationDef) -> MessagePassingComputation:
    """Instantiate the computation for a deployed ComputationDef (reference
    computations.py:1156).  Algorithm modules may export a host-side
    ``build_computation``; by default a DeviceShardComputation placeholder is
    created since the algorithm itself runs on device."""
    from ..algorithms import load_algorithm_module

    mod = load_algorithm_module(comp_def.algo.algo)
    factory = getattr(mod, "build_computation", None)
    if factory is not None:
        return factory(comp_def)
    return DeviceShardComputation(comp_def.node.name, comp_def)
