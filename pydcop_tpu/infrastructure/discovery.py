"""Discovery: the name service mapping computations to agents and agents to
addresses, with membership subscriptions.

Role parity with /root/reference/pydcop/infrastructure/discovery.py:
``Directory`` (:294, server state + subscription tables) hosted as a
``DirectoryComputation`` (:121) on the orchestrator's agent; a per-agent
``Discovery`` cache/API (:654) backed by a ``DiscoveryComputation`` (:557)
client.  Registrations may be published to the directory or kept local;
subscriptions deliver add/remove callbacks for agents, computations and
replicas.  Discovery traffic uses the lowest priority number = highest
priority (MSG_DISCOVERY, reference discovery.py:77).

In the TPU build this service only routes *control-plane* names (management
computations, replicas, shard bookkeeping) — algorithm traffic is compiled
into device collectives and needs no name resolution.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .communication import MSG_DISCOVERY
from .computations import Message, MessagePassingComputation, message_type, register

__all__ = [
    "DiscoveryException",
    "UnknownAgent",
    "UnknownComputation",
    "Directory",
    "DirectoryComputation",
    "Discovery",
    "DiscoveryComputation",
    "DIRECTORY_COMP_NAME",
]

logger = logging.getLogger("pydcop_tpu.infrastructure.discovery")

DIRECTORY_COMP_NAME = "_directory"


class DiscoveryException(Exception):
    pass


class UnknownAgent(DiscoveryException):
    pass


class UnknownComputation(DiscoveryException):
    pass


PublishAgentMessage = message_type(
    "publish_agent", ["agent", "address"]
)
UnpublishAgentMessage = message_type("unpublish_agent", ["agent"])
PublishComputationMessage = message_type(
    "publish_computation", ["computation", "agent", "address"]
)
UnpublishComputationMessage = message_type(
    "unpublish_computation", ["computation"]
)
PublishReplicaMessage = message_type(
    "publish_replica", ["replica", "agent"]
)
UnpublishReplicaMessage = message_type(
    "unpublish_replica", ["replica", "agent"]
)
SubscribeMessage = message_type(
    # kind: 'agent' | 'computation' | 'replica'; name may be None for all
    "subscribe", ["kind", "name", "subscribe"]
)


class Directory:
    """Server-side state: registrations + subscription tables (reference
    discovery.py:294)."""

    def __init__(self) -> None:
        self.agents: Dict[str, Any] = {}
        self.computations: Dict[str, str] = {}  # comp -> agent
        self.replicas: Dict[str, Set[str]] = {}  # comp -> {agents}
        # kind -> name (or '*') -> {subscriber agent names}
        self.subscriptions: Dict[str, Dict[str, Set[str]]] = {
            "agent": {},
            "computation": {},
            "replica": {},
        }

    def subscribers(self, kind: str, name: str) -> Set[str]:
        table = self.subscriptions[kind]
        return set(table.get(name, set())) | set(table.get("*", set()))

    def subscribe(self, kind: str, name: Optional[str], agent: str) -> None:
        self.subscriptions[kind].setdefault(name or "*", set()).add(agent)

    def unsubscribe(self, kind: str, name: Optional[str], agent: str) -> None:
        self.subscriptions[kind].get(name or "*", set()).discard(agent)


class DirectoryComputation(MessagePassingComputation):
    """The directory service as a message-passing computation hosted on the
    orchestrator's agent (reference discovery.py:121)."""

    def __init__(self, directory: Optional[Directory] = None) -> None:
        super().__init__(DIRECTORY_COMP_NAME)
        self.directory = directory or Directory()

    def _notify(self, kind: str, name: str, msg: Message) -> None:
        for sub in self.directory.subscribers(kind, name):
            self.post_msg(f"_discovery_{sub}", msg, MSG_DISCOVERY)

    @register("publish_agent")
    def _on_publish_agent(self, sender: str, msg, t: float) -> None:
        self.directory.agents[msg.agent] = msg.address
        self._notify("agent", msg.agent, msg)

    @register("unpublish_agent")
    def _on_unpublish_agent(self, sender: str, msg, t: float) -> None:
        self.directory.agents.pop(msg.agent, None)
        self._notify("agent", msg.agent, msg)

    @register("publish_computation")
    def _on_publish_computation(self, sender: str, msg, t: float) -> None:
        self.directory.computations[msg.computation] = msg.agent
        self._notify("computation", msg.computation, msg)

    @register("unpublish_computation")
    def _on_unpublish_computation(self, sender: str, msg, t: float) -> None:
        self.directory.computations.pop(msg.computation, None)
        self._notify("computation", msg.computation, msg)

    @register("publish_replica")
    def _on_publish_replica(self, sender: str, msg, t: float) -> None:
        self.directory.replicas.setdefault(msg.replica, set()).add(msg.agent)
        self._notify("replica", msg.replica, msg)

    @register("unpublish_replica")
    def _on_unpublish_replica(self, sender: str, msg, t: float) -> None:
        self.directory.replicas.get(msg.replica, set()).discard(msg.agent)
        self._notify("replica", msg.replica, msg)

    @register("subscribe")
    def _on_subscribe(self, sender: str, msg, t: float) -> None:
        # sender is the subscriber's discovery computation: _discovery_<agent>
        agent = sender[len("_discovery_"):]
        if msg.subscribe:
            self.directory.subscribe(msg.kind, msg.name, agent)
            # send current state so the subscriber starts consistent
            if msg.kind == "agent":
                for a, addr in self.directory.agents.items():
                    if msg.name in (None, a):
                        self.post_msg(
                            sender,
                            PublishAgentMessage(agent=a, address=addr),
                            MSG_DISCOVERY,
                        )
            elif msg.kind == "computation":
                for c, a in self.directory.computations.items():
                    if msg.name in (None, c):
                        addr = self.directory.agents.get(a)
                        self.post_msg(
                            sender,
                            PublishComputationMessage(
                                computation=c, agent=a, address=addr
                            ),
                            MSG_DISCOVERY,
                        )
            elif msg.kind == "replica":
                for c, agents in self.directory.replicas.items():
                    if msg.name in (None, c):
                        for a in agents:
                            self.post_msg(
                                sender,
                                PublishReplicaMessage(replica=c, agent=a),
                                MSG_DISCOVERY,
                            )
        else:
            self.directory.unsubscribe(msg.kind, msg.name, agent)


class DiscoveryComputation(MessagePassingComputation):
    """Client-side discovery endpoint: receives publish/unpublish events from
    the directory and updates the agent's Discovery cache (reference
    discovery.py:557)."""

    def __init__(self, discovery: "Discovery") -> None:
        super().__init__(f"_discovery_{discovery.agent_name}")
        self.discovery = discovery

    @register("publish_agent")
    def _on_agent(self, sender: str, msg, t: float) -> None:
        self.discovery._cache_agent(msg.agent, msg.address)

    @register("unpublish_agent")
    def _on_agent_removed(self, sender: str, msg, t: float) -> None:
        self.discovery._uncache_agent(msg.agent)

    @register("publish_computation")
    def _on_computation(self, sender: str, msg, t: float) -> None:
        self.discovery._cache_computation(
            msg.computation, msg.agent, msg.address
        )

    @register("unpublish_computation")
    def _on_computation_removed(self, sender: str, msg, t: float) -> None:
        self.discovery._uncache_computation(msg.computation)

    @register("publish_replica")
    def _on_replica(self, sender: str, msg, t: float) -> None:
        self.discovery._cache_replica(msg.replica, msg.agent, True)

    @register("unpublish_replica")
    def _on_replica_removed(self, sender: str, msg, t: float) -> None:
        self.discovery._cache_replica(msg.replica, msg.agent, False)


class Discovery:
    """Per-agent discovery API: a synchronous local cache plus asynchronous
    publish/subscribe against the directory (reference discovery.py:654).

    Callbacks registered with ``subscribe_*`` fire as
    ``cb(event, name, value)`` with event 'agent_added'/'agent_removed'/
    'computation_added'/'computation_removed'/'replica_added'/
    'replica_removed'.
    """

    def __init__(self, agent_name: str, address: Any = None) -> None:
        self.agent_name = agent_name
        self.own_address = address
        self._agents: Dict[str, Any] = {}
        self._computations: Dict[str, str] = {}
        self._replicas: Dict[str, Set[str]] = {}
        self._lock = threading.RLock()
        # subscription records (callback | None, one_shot): None marks a
        # cache-only subscription (subscribe with no callback) that still
        # counts as local interest, so another consumer's unsubscribe
        # cannot cancel the directory pushes it relies on
        self._agent_cbs: List[Tuple[Optional[Callable], bool]] = []
        self._computation_cbs: Dict[
            str, List[Tuple[Optional[Callable], bool]]
        ] = {}
        self._replica_cbs: Dict[
            str, List[Tuple[Optional[Callable], bool]]
        ] = {}
        self.discovery_computation = DiscoveryComputation(self)

    # -- registration (sync local cache + optional publication) --------

    def register_agent(
        self, agent: str, address: Any, publish: bool = True
    ) -> None:
        with self._lock:
            self._agents[agent] = address
        if publish:
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                PublishAgentMessage(agent=agent, address=address),
                MSG_DISCOVERY,
            )

    def unregister_agent(self, agent: str, publish: bool = True) -> None:
        with self._lock:
            self._agents.pop(agent, None)
            for c in [
                c for c, a in self._computations.items() if a == agent
            ]:
                del self._computations[c]
        if publish:
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                UnpublishAgentMessage(agent=agent),
                MSG_DISCOVERY,
            )

    def register_computation(
        self,
        computation: str,
        agent: Optional[str] = None,
        address: Any = None,
        publish: bool = True,
    ) -> None:
        agent = agent or self.agent_name
        address = address if address is not None else self.own_address
        with self._lock:
            self._computations[computation] = agent
            if address is not None:
                self._agents.setdefault(agent, address)
        if publish:
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                PublishComputationMessage(
                    computation=computation, agent=agent, address=address
                ),
                MSG_DISCOVERY,
            )

    def unregister_computation(
        self, computation: str, publish: bool = True
    ) -> None:
        with self._lock:
            self._computations.pop(computation, None)
        if publish:
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                UnpublishComputationMessage(computation=computation),
                MSG_DISCOVERY,
            )

    def register_replica(self, replica: str, agent: Optional[str] = None):
        agent = agent or self.agent_name
        with self._lock:
            self._replicas.setdefault(replica, set()).add(agent)
        self.discovery_computation.post_msg(
            DIRECTORY_COMP_NAME,
            PublishReplicaMessage(replica=replica, agent=agent),
            MSG_DISCOVERY,
        )

    def unregister_replica(self, replica: str, agent: Optional[str] = None):
        agent = agent or self.agent_name
        with self._lock:
            self._replicas.get(replica, set()).discard(agent)
        self.discovery_computation.post_msg(
            DIRECTORY_COMP_NAME,
            UnpublishReplicaMessage(replica=replica, agent=agent),
            MSG_DISCOVERY,
        )

    # -- queries -------------------------------------------------------

    def agents(self) -> List[str]:
        with self._lock:
            return list(self._agents)

    def agent_address(self, agent: str) -> Any:
        with self._lock:
            try:
                return self._agents[agent]
            except KeyError:
                raise UnknownAgent(agent) from None

    def computation_agent(self, computation: str) -> str:
        with self._lock:
            try:
                return self._computations[computation]
            except KeyError:
                raise UnknownComputation(computation) from None

    def agent_computations(self, agent: str) -> List[str]:
        with self._lock:
            return [c for c, a in self._computations.items() if a == agent]

    def computations(self) -> List[str]:
        with self._lock:
            return list(self._computations)

    def replica_agents(self, replica: str) -> Set[str]:
        with self._lock:
            return set(self._replicas.get(replica, set()))

    # -- subscriptions -------------------------------------------------

    def subscribe_all_agents(
        self, cb: Optional[Callable] = None, one_shot: bool = False
    ) -> None:
        '''``one_shot``: the callback fires for the first event only,
        then auto-removes (reference discovery.py one-shot
        subscriptions).'''
        with self._lock:
            self._agent_cbs.append((cb, one_shot if cb else False))
            # the post stays inside the lock: posting after release lets
            # a concurrent unsubscribe's directory message overtake this
            # one, leaving live local records with no directory pushes
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                SubscribeMessage(kind="agent", name=None, subscribe=True),
                MSG_DISCOVERY,
            )

    def unsubscribe_all_agents(self, cb: Optional[Callable] = None) -> None:
        '''Remove ``cb`` (or every callback when None); the directory
        stops pushing agent events once no callback remains.'''
        with self._lock:
            existed = bool(self._agent_cbs)
            self._agent_cbs = (
                [] if cb is None
                else [rec for rec in self._agent_cbs if rec[0] is not cb]
            )
            if existed and not self._agent_cbs:
                self.discovery_computation.post_msg(
                    DIRECTORY_COMP_NAME,
                    SubscribeMessage(
                        kind="agent", name=None, subscribe=False
                    ),
                    MSG_DISCOVERY,
                )

    def subscribe_computation(
        self,
        computation: str,
        cb: Optional[Callable] = None,
        one_shot: bool = False,
    ) -> None:
        with self._lock:
            self._computation_cbs.setdefault(computation, []).append(
                (cb, one_shot if cb else False)
            )
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                SubscribeMessage(
                    kind="computation", name=computation, subscribe=True
                ),
                MSG_DISCOVERY,
            )

    def unsubscribe_computation(
        self, computation: str, cb: Optional[Callable] = None
    ) -> None:
        with self._lock:
            cbs = self._computation_cbs.get(computation, [])
            existed = bool(cbs)
            cbs = [] if cb is None else [r for r in cbs if r[0] is not cb]
            if cbs:
                self._computation_cbs[computation] = cbs
            else:
                self._computation_cbs.pop(computation, None)
            if existed and not cbs:
                self.discovery_computation.post_msg(
                    DIRECTORY_COMP_NAME,
                    SubscribeMessage(
                        kind="computation", name=computation,
                        subscribe=False,
                    ),
                    MSG_DISCOVERY,
                )

    def subscribe_replica(
        self,
        replica: str,
        cb: Optional[Callable] = None,
        one_shot: bool = False,
    ) -> None:
        with self._lock:
            self._replica_cbs.setdefault(replica, []).append(
                (cb, one_shot if cb else False)
            )
            self.discovery_computation.post_msg(
                DIRECTORY_COMP_NAME,
                SubscribeMessage(
                    kind="replica", name=replica, subscribe=True
                ),
                MSG_DISCOVERY,
            )

    def unsubscribe_replica(
        self, replica: str, cb: Optional[Callable] = None
    ) -> None:
        with self._lock:
            cbs = self._replica_cbs.get(replica, [])
            existed = bool(cbs)
            cbs = [] if cb is None else [r for r in cbs if r[0] is not cb]
            if cbs:
                self._replica_cbs[replica] = cbs
            else:
                self._replica_cbs.pop(replica, None)
            if existed and not cbs:
                self.discovery_computation.post_msg(
                    DIRECTORY_COMP_NAME,
                    SubscribeMessage(
                        kind="replica", name=replica, subscribe=False
                    ),
                    MSG_DISCOVERY,
                )

    def _fire(self, kind: str, name: Optional[str], *event) -> None:
        '''Invoke subscription callbacks for one event.

        One-shot records are removed after their first event; when that
        leaves no records at all, the subscription is torn down exactly
        like unsubscribe_* (key dropped, directory told to stop pushing)
        so a one-shot subscriber does not leak directory traffic.  The
        teardown post happens INSIDE the lock, serialized with the
        record mutation: posted after release, a concurrent subscribe_*
        could append a record and post its subscribe first, and the
        late unsubscribe would silently stop directory pushes while a
        live local record exists.  Callbacks still run OUTSIDE the lock
        (a callback may re-subscribe).'''
        with self._lock:
            if kind == "agent":
                cbs = self._agent_cbs
            elif kind == "computation":
                cbs = self._computation_cbs.get(name, [])
            else:
                cbs = self._replica_cbs.get(name, [])
            to_call = [rec[0] for rec in cbs if rec[0] is not None]
            remaining = [rec for rec in cbs if not rec[1]]
            if kind == "agent":
                self._agent_cbs = remaining
            elif kind == "computation":
                if remaining:
                    self._computation_cbs[name] = remaining
                else:
                    self._computation_cbs.pop(name, None)
            else:
                if remaining:
                    self._replica_cbs[name] = remaining
                else:
                    self._replica_cbs.pop(name, None)
            if cbs and not remaining:
                self.discovery_computation.post_msg(
                    DIRECTORY_COMP_NAME,
                    SubscribeMessage(
                        kind=kind, name=name, subscribe=False
                    ),
                    MSG_DISCOVERY,
                )
        for cb in to_call:
            cb(*event)

    # -- cache updates from the discovery computation ------------------

    def _cache_agent(self, agent: str, address: Any) -> None:
        with self._lock:
            known = agent in self._agents
            self._agents[agent] = address
        if not known:
            self._fire("agent", None, "agent_added", agent, address)

    def _uncache_agent(self, agent: str) -> None:
        with self._lock:
            existed = self._agents.pop(agent, None) is not None
        if existed:
            self._fire("agent", None, "agent_removed", agent, None)

    def _cache_computation(
        self, computation: str, agent: str, address: Any
    ) -> None:
        with self._lock:
            self._computations[computation] = agent
            if address is not None:
                self._agents.setdefault(agent, address)
        self._fire(
            "computation", computation,
            "computation_added", computation, agent,
        )

    def _uncache_computation(self, computation: str) -> None:
        with self._lock:
            self._computations.pop(computation, None)
        self._fire(
            "computation", computation,
            "computation_removed", computation, None,
        )

    def _cache_replica(self, replica: str, agent: str, added: bool) -> None:
        with self._lock:
            if added:
                self._replicas.setdefault(replica, set()).add(agent)
            else:
                self._replicas.get(replica, set()).discard(agent)
        self._fire(
            "replica", replica,
            "replica_added" if added else "replica_removed", replica, agent,
        )
