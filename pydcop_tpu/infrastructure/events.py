"""In-process event bus: the observability spine.

Role parity with /root/reference/pydcop/infrastructure/Events.py
(EventDispatcher:41, singleton event_bus:98): topic-keyed callbacks with
``*``-suffix wildcard subscription, disabled by default (enabled when a UI or
metrics collector attaches).  Topics follow the reference's naming:
``computations.value.<name>``, ``computations.cycle.<name>``,
``computations.message_rcv/message_snd.<name>``, ``agents.add_computation.<agent>``.

In the TPU build the bus carries *host-side* events only: per-cycle device
state is surfaced by the solver loop (which reads back value/cost arrays every
k cycles) and republished here, instead of every computation firing python
callbacks from its own thread.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List

__all__ = ["EventDispatcher", "event_bus"]

logger = logging.getLogger("pydcop_tpu.infrastructure.events")


class EventDispatcher:
    """Topic -> callbacks dispatcher with ``*`` suffix wildcards."""

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._lock = threading.RLock()
        self._subs: Dict[str, List[Callable[[str, Any], None]]] = {}

    def subscribe(self, topic: str, cb: Callable[[str, Any], None]) -> None:
        with self._lock:
            self._subs.setdefault(topic, []).append(cb)

    def unsubscribe(self, topic: str, cb: Callable[[str, Any], None]) -> None:
        with self._lock:
            cbs = self._subs.get(topic, [])
            if cb in cbs:
                cbs.remove(cb)
            if not cbs and topic in self._subs:
                del self._subs[topic]

    def send(self, topic: str, event: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            targets: List[Callable[[str, Any], None]] = []
            for sub_topic, cbs in self._subs.items():
                if sub_topic.endswith("*"):
                    if topic.startswith(sub_topic[:-1]):
                        targets.extend(cbs)
                elif sub_topic == topic:
                    targets.extend(cbs)
        # callbacks run outside the lock from a snapshot (a subscriber may
        # re-enter subscribe/unsubscribe); a RAISING callback must not kill
        # the SENDER's thread — an agent loop or the orchestrator — nor
        # starve the remaining subscribers, so each error is contained,
        # logged and counted (telemetry.dispatch_errors)
        for cb in targets:
            try:
                cb(topic, event)
            except Exception:
                logger.exception(
                    "event-bus callback %r failed on topic %s", cb, topic
                )
                # lazy import: telemetry must stay importable without the
                # infrastructure package (and vice versa)
                from ..telemetry.metrics import metrics_registry

                metrics_registry.counter(
                    "telemetry.dispatch_errors",
                    "event-bus callbacks that raised, by topic",
                ).inc(topic=topic)

    def reset(self) -> None:
        with self._lock:
            self._subs.clear()


#: Process-wide singleton, like the reference's ``event_bus`` (Events.py:98).
event_bus = EventDispatcher()
