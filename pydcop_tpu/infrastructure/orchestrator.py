"""Orchestrator: bootstrap, deployment, run control, metrics sink, scenario
driver and repair coordinator.

Role parity with /root/reference/pydcop/infrastructure/orchestrator.py:
``Orchestrator`` (:62 — an Agent named "orchestrator" hosting the Directory
and an ``AgentsMgt`` management computation; API start:170,
deploy_computations:203, start_replication:223, run:245, stop_agents:291,
current_solution:309, end_metrics:312) and ``AgentsMgt`` (:535 — registration
barriers, deploy fan-out, value/cycle/metric collection, scenario handling,
repair barriers).  The management message taxonomy mirrors the reference's
(:385-438).

TPU-first inversion (SURVEY.md §2.8): the reference's agents *compute* — the
orchestrator only coordinates.  Here the orchestrator also owns the device:
``run()`` compiles the DCOP once and advances ALL computations as one scan on
the TPU, then publishes per-cycle metrics and value readbacks to the hosting
agents so the rest of the control plane (metrics modes, UI, discovery,
resilience) observes exactly what the reference's would.  Agents host
bookkeeping computations + the repair protocol; algorithm messages never
exist host-side.  On a multi-host mesh the same orchestrator drives the
sharded solve through ``parallel/mesh.py`` (jax.distributed), which is the
TPU equivalent of the reference's process/HTTP deployment.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..algorithms import AlgorithmDef, ComputationDef
from ..dcop.dcop import DCOP
from ..dcop.scenario import Scenario
from ..distribution.objects import Distribution
from .agents import Agent
from .communication import (
    CommunicationLayer,
    InProcessCommunicationLayer,
    MSG_MGT,
    MSG_VALUE,
)
from .computations import (
    Message,
    MessagePassingComputation,
    message_type,
    register,
)
from ..telemetry.tracing import tracer
from .discovery import DirectoryComputation
from .events import event_bus

__all__ = ["Orchestrator", "AgentsMgt", "ORCHESTRATOR"]

logger = logging.getLogger("pydcop_tpu.orchestrator")

ORCHESTRATOR = "orchestrator"
ORCHESTRATOR_MGT = "_mgt_orchestrator"

#: the valid replica-placement paths (graftucs negotiation vs the
#: centralized UCS oracle).  Canonical here — the infrastructure layer —
#: because ``pydcop_tpu.resilience`` imports this module and re-exports
#: the tuple (importing the other way would be circular).
REPLICATION_MODES = ("distributed", "local")

# -- management message taxonomy (reference orchestrator.py:385-438) --------

DeployMessage = message_type("deploy", ["comp_def"])
RunAgentMessage = message_type("run_computations", ["computations"])
PauseMessage = message_type("pause_computations", ["computations"])
ResumeMessage = message_type("resume_computations", ["computations"])
StopAgentMessage = message_type("stop_agent", ["forced"])
AgentRemovedMessage = message_type("agent_removed", ["reason"])
RegisterAgentMessage = message_type("register_agent", ["agent", "address"])
DeployedMessage = message_type("deployed", ["agent", "computations"])
ValueChangeMessage = message_type(
    "value_change", ["computation", "value", "cost", "cycle"]
)
CycleChangeMessage = message_type("cycle_change", ["cycle", "cost"])
MetricsMessage = message_type("metrics", ["agent", "metrics"])
ComputationFinishedMessage = message_type(
    "computation_finished", ["computation"]
)
AgentStoppedMessage = message_type("agent_stopped", ["agent", "metrics"])
# ``mode`` selects the replication path ("distributed" = graftucs
# negotiation, "local" = centralized UCS oracle); ``agent_defs`` ships
# serialized AgentDefs (hosting costs, capacities) ONLY in local mode —
# the distributed protocol discovers both by visiting.  ``round`` is the
# barrier's epoch: the ack echoes it so a stale round's ack (late after a
# barrier timeout, or chaos-duplicated) can never release the NEXT
# round's barrier
ReplicateComputationsMessage = message_type(
    "replication", ["k", "agents", "mode", "agent_defs", "round"]
)
ComputationReplicatedMessage = message_type(
    "replicated", ["agent", "replica_hosts", "round"]
)
# the repair handshake is epoch'd exactly like replication: ``round``
# (shipped inside repair_info, echoed by both acks) stops a straggler's
# late repair_ready from a timed-out episode releasing the NEXT
# episode's barrier — the same stale-ack class proto-stale-guard exists
# to catch
SetupRepairMessage = message_type("setup_repair", ["repair_info"])
RepairReadyMessage = message_type(
    "repair_ready", ["agent", "computations", "round"]
)
RepairRunMessage = message_type("repair_run", [])
RepairDoneMessage = message_type(
    "repair_done", ["agent", "selected", "round"]
)
MetricsRequestMessage = message_type("metrics_request", [])


def replication_timeout_detail(
    timeout: float,
    expected: set,
    acked: set,
    levels: Dict[str, int],
    k: int,
) -> str:
    """The replication-barrier diagnostic: WHO never acked and WHICH
    computations sit below the k-target — a missed barrier with no culprit
    left operators bisecting agent logs, and a partial-k completion with
    no level report looked identical to full resilience."""
    missing = sorted(expected - acked)
    below = {c: n for c, n in sorted(levels.items()) if n < k}
    detail = (
        f"replication did not complete within {timeout}s: no "
        f"ReplicateComputations ack from {len(missing)} agent(s) "
        f"{missing} (acked: {sorted(acked)})"
    )
    if below:
        detail += (
            f"; {len(below)} computation(s) below the k-target "
            f"{k}: {below}"
        )
    return detail


class Orchestrator:
    """Control plane for one DCOP run."""

    def __init__(
        self,
        algo: AlgorithmDef,
        cg,
        agent_defs: List[Any],
        dcop: DCOP,
        distribution: Optional[Distribution] = None,
        comm: Optional[CommunicationLayer] = None,
        collector: Optional[Callable[[Dict[str, Any]], None]] = None,
        collect_moment: str = "value_change",
        collect_period: Optional[float] = None,
        n_cycles: int = 100,
        seed: int = 0,
        infinity: float = 10000,
        degrade_on_timeout: bool = False,
        metrics_port: Optional[int] = None,
        replication_mode: str = "distributed",
    ) -> None:
        self.algo = algo
        self.cg = cg
        self.dcop = dcop
        self.agent_defs = list(agent_defs)
        self.distribution = distribution
        self.collector = collector
        self.collect_moment = collect_moment
        self.collect_period = collect_period
        self.n_cycles = n_cycles
        self.seed = seed
        self.infinity = infinity
        # barrier policy under injected faults: strict (default) raises on
        # a missed deployment/replication barrier; degraded mode reports
        # WHO missed it, proceeds with what arrived and still returns the
        # best-known assignment (chaos runs set this)
        self.degrade_on_timeout = degrade_on_timeout
        # graftucs: how start_replication places replicas — "distributed"
        # runs the visit/accept/refuse negotiation (resilience/), "local"
        # keeps the centralized UCS as a verifiable oracle (replication/)
        if replication_mode not in REPLICATION_MODES:
            raise ValueError(
                f"replication_mode must be one of {REPLICATION_MODES}, "
                f"got {replication_mode!r}"
            )
        self.replication_mode = replication_mode
        # the standing k-target: set by start_replication, reused by the
        # elasticity path (an agent ARRIVAL re-replicates onto the newcomer)
        self.ktarget: Optional[int] = None
        # graftchaos hooks: a ChaosController driving kills/device faults
        # (chaos/controller.py) and, on thread topologies, the local agent
        # objects so kill events can crash them abruptly
        self.chaos = None
        self._local_agents: Dict[str, Any] = {}

        self._comm = comm or InProcessCommunicationLayer()
        self._agent = Agent(ORCHESTRATOR, self._comm)
        self.directory = DirectoryComputation()
        self._agent.add_computation(self.directory, publish=False)
        self.mgt = AgentsMgt(self)
        self._agent.add_computation(self.mgt, publish=False)

        self.start_time: Optional[float] = None
        self.status = "NOT_STARTED"
        self._result_lock = threading.Lock()
        # serializes whole removals (pause -> repair -> resume): a chaos
        # kill fires on the timeline thread and may race a scenario
        # removal on the caller's thread; concurrent repair_orphans would
        # each rewrite self.distribution and lose the other's re-hosting
        self._repair_lock = threading.Lock()
        self._assignment: Dict[str, Any] = {}
        self._cost: Optional[float] = None
        self._violation: Optional[int] = None
        self._cycle = 0
        self._cost_curve: Optional[List[float]] = None
        self._solve_thread: Optional[threading.Thread] = None
        self._solve_done = threading.Event()
        self._repair_metrics: List[Dict[str, Any]] = []
        self.solve_msg_count = 0
        self.solve_msg_size = 0
        # graftwatch live surface: /metrics (Prometheus), /metrics.json,
        # /status — started with the orchestrator when a port is given
        # (0 = ephemeral; the bound port is on .metrics_server.port)
        self.metrics_port = metrics_port
        self.metrics_server = None

    # ------------------------------------------------------------------
    # public API (reference orchestrator.py:170-330)
    # ------------------------------------------------------------------

    @property
    def address(self) -> Any:
        return self._comm.address

    def start(self) -> "Orchestrator":
        self._agent.start()
        self._agent.computation(self.directory.name).start()
        self._agent.computation(self.mgt.name).start()
        if self.metrics_port is not None:
            from .ui import MetricsHttpServer

            self.metrics_server = MetricsHttpServer(
                self.metrics_port, status_cb=self.watch_status
            )
        self.status = "STARTED"
        return self

    def deploy_computations(self, timeout: float = 10.0) -> None:
        """Wait for all agents to register, then ship every ComputationDef to
        its hosting agent's management computation (reference :203,:915)."""
        with tracer.span(
            "orchestrator.deploy", cat="lifecycle",
            n_agents=len(self.agent_defs), n_computations=len(self.cg.nodes),
        ):
            if not self.mgt.all_registered.wait(timeout):
                missing = set(a.name for a in self.agent_defs) - set(
                    self.mgt.registered_agents
                )
                raise TimeoutError(
                    f"agents failed to register in {timeout}s: "
                    f"{sorted(missing)}"
                )
            if self.distribution is None:
                raise ValueError("no distribution to deploy")
            for agent_name in self.distribution.agents:
                comp_defs = []
                for comp_name in self.distribution.computations_hosted(
                    agent_name
                ):
                    node = self.cg.computation(comp_name)
                    comp_defs.append(ComputationDef(node, self.algo))
                for cd in comp_defs:
                    self.mgt.post_msg(
                        f"_mgt_{agent_name}", DeployMessage(comp_def=cd),
                        MSG_MGT,
                    )

    def start_replication(
        self, k: int, timeout: float = 10.0, mode: Optional[str] = None
    ) -> Dict[str, int]:
        """Ask every agent to replicate its computations k times
        (reference :223); blocks until the replication barrier passes and
        returns the achieved replication level per computation.

        ``mode`` overrides the orchestrator's ``replication_mode`` for
        this round.  When fewer than k hosts can accept (capacity, too few
        agents), agents ack at *partial k* — the achieved level is
        recorded in ``AgentsMgt.replication_levels`` instead of hanging
        the barrier (reference behavior).  A missed barrier names the
        agents that never acked AND the computations below target — with
        ``degrade_on_timeout`` the run proceeds on the replicas that did
        land (partial k-resilience beats none when the faults are already
        happening).  Re-invocations re-negotiate: a larger candidate set
        (agent arrival) can move replicas onto cheaper hosts and a smaller
        ``k`` retracts the surplus (graftucs retraction)."""
        mode = mode or self.replication_mode
        if mode not in REPLICATION_MODES:
            raise ValueError(f"unknown replication mode {mode!r}")
        self.ktarget = int(k)
        targets = list(self.distribution.agents)
        self.mgt.expect_replication(set(targets), k=int(k), mode=mode)
        agent_defs = None
        if mode == "local":
            from ..utils.simple_repr import simple_repr

            agent_defs = {
                a.name: simple_repr(a) for a in self.agent_defs
            }
        known = dict(self.mgt.agent_addresses)
        for agent_name in targets:
            self.mgt.post_msg(
                f"_mgt_{agent_name}",
                ReplicateComputationsMessage(
                    k=k, agents=known, mode=mode, agent_defs=agent_defs,
                    round=self.mgt.replication_round,
                ),
                MSG_MGT,
            )
        if not self.mgt.all_replicated.wait(timeout):
            detail = replication_timeout_detail(
                timeout,
                expected=self.mgt.expected_replication_agents,
                acked=self.mgt.replicated_agents,
                levels=self.mgt.replication_levels,
                k=int(k),
            )
            if not self.degrade_on_timeout:
                raise TimeoutError(detail)
            logger.error(
                "%s — proceeding with partial replication "
                "(degrade_on_timeout)", detail,
            )
        else:
            partial = {
                c: n
                for c, n in self.mgt.replication_levels.items()
                if n < k
            }
            if partial:
                logger.warning(
                    "replication completed at partial k for %d "
                    "computation(s): %s (k-target %d)",
                    len(partial), partial, k,
                )
        return dict(self.mgt.replication_levels)

    def set_agent_capacity(self, agent_name: str, capacity: float) -> None:
        """Tell ``agent_name`` its effective capacity changed (elastic
        resize, operator action).  The agent's replication ledger re-checks
        and sheds its most expensive replicas until it fits again —
        graftucs retraction's capacity-loss trigger."""
        from ..resilience.messages import CapacityMessage
        from ..resilience.negotiation import replication_name

        addr = self.mgt.agent_addresses.get(agent_name)
        if addr is not None:
            self._agent.messaging.register_route(
                replication_name(agent_name), agent_name, addr
            )
        self.mgt.post_msg(
            replication_name(agent_name),
            CapacityMessage(capacity=capacity),
            MSG_MGT,
        )

    def run(
        self,
        scenario: Optional[Scenario] = None,
        timeout: Optional[float] = None,
        repair_only: bool = False,
        ready_timeout: Optional[float] = None,
    ) -> None:
        """Start the computations and drive the device solve to completion
        (reference :245).  Blocks until finished / timeout.

        ``ready_timeout`` bounds the wait for deployment confirmations;
        the default scales with the number of computations (each is one
        round-trip through the management plane — measured ~1ms each, so
        10k computations need more than a fixed 10s).
        """
        if ready_timeout is None:
            ready_timeout = 10.0 + 0.005 * len(self.cg.nodes)
        if not self.mgt.ready_to_run.wait(ready_timeout):
            # _pending_deploy stays None until the FIRST ack arrives —
            # distinguish "some stragglers" from "nothing acked at all"
            if self.mgt._pending_deploy is None:
                detail = (
                    f"deployment did not complete within {ready_timeout}s:"
                    f" no deploy ack received at all (0 of "
                    f"{len(self.cg.nodes)} computations confirmed)"
                )
            else:
                pending = sorted(self.mgt._pending_deploy)
                detail = (
                    f"deployment did not complete within {ready_timeout}s:"
                    f" {len(pending)} computation(s) unconfirmed "
                    f"(e.g. {pending[:5]})"
                )
            if not self.degrade_on_timeout:
                raise TimeoutError(detail)
            logger.error(
                "%s — proceeding with partial deployment "
                "(degrade_on_timeout)", detail,
            )
        self.start_time = time.perf_counter()
        self.status = "RUNNING"
        metrics_poll = None
        if self.collect_period and self.collect_moment == "period":
            # periodic metric collection mode (reference orchestrator
            # period mode): poll every agent's metrics on the configured
            # cadence; replies stream through the 'metrics' handler into
            # the collector.  The bound method is kept so the removal in
            # the finally below targets the SAME callback object —
            # re-reading self.request_agent_metrics would bind a fresh
            # one and the identity-based removal would miss.
            metrics_poll = self.request_agent_metrics
            self.mgt.add_periodic_action(self.collect_period, metrics_poll)
        for agent_name in self.distribution.agents:
            self.mgt.post_msg(
                f"_mgt_{agent_name}",
                RunAgentMessage(
                    computations=self.distribution.computations_hosted(
                        agent_name
                    )
                ),
                MSG_MGT,
            )
        self._solve_thread = threading.Thread(
            target=self._device_solve, name="device-solve", daemon=True
        )
        self._solve_thread.start()

        if self.chaos is not None:
            self.chaos.start(self.kill_agent)
        t_run = time.perf_counter()
        try:
            if scenario is not None:
                self._play_scenario(scenario)

            budget = None if timeout is None else timeout
            finished = self._solve_done.wait(budget)
            if not finished:
                self.status = "TIMEOUT"
            elif self.status == "RUNNING":
                self.status = "FINISHED"
        finally:
            if metrics_poll is not None:
                # a finished run must stop polling: agents are about to
                # stop and every further MetricsRequest would only park
                # and dead-letter; removal also keeps a second run()
                # from stacking a double-rate poll
                self.mgt.remove_periodic_action(metrics_poll)
            if self.chaos is not None:
                # the fault timeline is part of the run: a solve that
                # returns before a scheduled kill still gets killed (and
                # repaired), otherwise the same schedule would exercise
                # different faults depending on machine speed.  What is
                # LEFT of the run's timeout bounds the wait (the whole
                # call must not exceed ~timeout); without one, 60s does.
                if timeout is None:
                    grace = 60.0
                else:
                    grace = max(
                        0.0, timeout - (time.perf_counter() - t_run)
                    )
                if not self.chaos.wait_timeline(timeout=grace):
                    logger.warning(
                        "chaos timeline still running at shutdown; "
                        "cancelling remaining events"
                    )
                self.chaos.stop()

    def current_solution(self):
        with self._result_lock:
            return dict(self._assignment), self._cost

    def dead_letter_total(self) -> int:
        """Parked messages dropped (TTL/cap) across the orchestrator and
        every locally hosted agent — the zero-loss assertion of chaos
        runs (`--max-dead-letters`)."""
        return self._agent.messaging.dead_letter_count + sum(
            a.messaging.dead_letter_count
            for a in self._local_agents.values()
        )

    def stop_agents(self, timeout: float = 5.0) -> None:
        """Ask every agent to stop cleanly (reference :291)."""
        with tracer.span(
            "orchestrator.stop_agents", cat="lifecycle",
            n_agents=len(self.mgt.registered_agents),
        ):
            for a in list(self.mgt.registered_agents):
                self.mgt.post_msg(
                    f"_mgt_{a}", StopAgentMessage(forced=False), MSG_MGT
                )
            self.mgt.all_stopped.wait(timeout)

    def stop(self) -> None:
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server = None
        self._agent.clean_shutdown()
        self._agent.join()
        self.status = "STOPPED" if self.status != "FINISHED" else self.status

    def request_agent_metrics(self) -> None:
        """Broadcast a metrics poll to every registered agent; replies
        land in ``AgentsMgt.agent_metrics`` (and the collector) via the
        existing ``metrics`` handler.  This is the send half of the
        agents' ``metrics_request`` handler — which sat dead (graftlint
        proto-dead-handler) until this method existed: nothing could
        sample agent metrics mid-run, only at stop time."""
        for a in list(self.mgt.registered_agents):
            self.mgt.post_msg(
                f"_mgt_{a}", MetricsRequestMessage(), MSG_MGT
            )

    def end_metrics(self) -> Dict[str, Any]:
        """Global metrics in the reference's schema (orchestrator.py:1215)."""
        with self._result_lock:
            msg_count = sum(
                m.get("count_ext_msg", {}).get(c, 0)
                for m in self.mgt.agent_metrics.values()
                for c in m.get("count_ext_msg", {})
            )
            msg_size = sum(
                m.get("size_ext_msg", {}).get(c, 0)
                for m in self.mgt.agent_metrics.values()
                for c in m.get("size_ext_msg", {})
            )
            return {
                "status": self.status,
                "assignment": dict(self._assignment),
                "cost": self._cost,
                "violation": self._violation,
                "cycle": self._cycle,
                "msg_count": self.solve_msg_count + msg_count,
                "msg_size": self.solve_msg_size + msg_size,
                "time": (
                    time.perf_counter() - self.start_time
                    if self.start_time
                    else 0.0
                ),
                "cost_curve": self._cost_curve,
                "repair_metrics": list(self._repair_metrics),
            }

    def watch_status(self) -> Dict[str, Any]:
        """The ``/status`` payload for ``pydcop_tpu watch``: run state,
        anytime-best progress (live from the ``solve.best_cost`` /
        ``solve.cycles_to_best`` gauges while a chunked device solve is
        still running), a decimated cost curve once one exists, and
        per-agent queue health.  Read-only — safe to call from the scrape
        thread at any point in the run."""
        from ..telemetry.metrics import metrics_registry

        def _gauge(name: str) -> Optional[float]:
            m = metrics_registry.get(name)
            if m is None:
                return None
            values = m.snapshot()["values"]
            return values[0]["value"] if values else None

        # the gauge carries the device's INTERNAL minimization cost
        # (negated utility on max-objective problems, so its series is
        # non-increasing); /status sits next to external-sign fields
        # (cost, cost_curve), so convert before the two meet in one view
        sign = -1.0 if self.dcop.objective == "max" else 1.0
        best = _gauge("solve.best_cost")
        if best is not None:
            best = sign * best

        with self._result_lock:
            cost = self._cost
            violation = self._violation
            cycle = self._cycle
            curve = list(self._cost_curve) if self._cost_curve else None
        if curve:
            from ..telemetry.summary import decimate_series

            # keep the /status payload terminal-sized; the last point
            # (current incumbent) always survives
            curve = decimate_series(curve, 120)
        agents = {}
        # snapshot first: a scenario add_agent may grow the dict while
        # the scrape thread iterates
        for name, agent in sorted(dict(self._local_agents).items()):
            messaging = getattr(agent, "messaging", None)
            if messaging is None:
                continue
            agents[name] = {
                "queue": messaging._queue.qsize(),
                "parked": messaging.parked_count,
                "dead_letters": messaging.dead_letter_count,
            }
        out = {
            "status": self.status,
            "cost": cost,
            "violation": violation,
            "cycle": cycle,
            "best_cost": best,
            "cycles_to_best": _gauge("solve.cycles_to_best"),
            "cost_curve": curve,
            "agents": agents,
            "registered_agents": len(self.mgt.registered_agents),
            "dead_letters": self.dead_letter_total(),
            "time": (
                time.perf_counter() - self.start_time
                if self.start_time
                else 0.0
            ),
        }
        # graftpulse: solver-health block (diagnosis + churn series) for
        # the watch verb — present only when pulse is on and a device
        # solve has published health rows
        from ..telemetry.pulse import pulse

        pulse_block = pulse.status_block()
        if pulse_block is not None:
            out["pulse"] = pulse_block
        # graftdur: durability block (checkpoint dir/cadence/census,
        # scenario cursor, what this run resumed from) once configured
        from ..durability import durability

        dura_block = durability.status_block()
        if dura_block is not None:
            out["durability"] = dura_block
        # graftucs: replication block (mode, k-target, achieved levels,
        # visit/refusal/retraction counters) once a round was requested
        from ..resilience import replication_status_block

        rep_block = replication_status_block(
            self.mgt, self.ktarget,
            self.mgt.replication_mode_active or self.replication_mode,
        )
        if rep_block is not None:
            out["replication"] = rep_block
        # graftmem: device-memory block (last live sample, guard config,
        # refusal counts) so watch/status sees the memory plane
        from ..telemetry.memplane import memory_status

        mem_block = memory_status()
        if mem_block is not None:
            out["memory"] = mem_block
        return out

    # ------------------------------------------------------------------
    # the device solve (replaces the reference's per-agent algorithm run)
    # ------------------------------------------------------------------

    def _device_solve(self) -> None:
        from ..api import solve_result

        # one retry: a transient device failure (preempted accelerator,
        # chaos-injected step fault) must not take down a run whose whole
        # control plane is healthy; a deterministic error just fails twice
        attempts = 2
        r = None
        for attempt in range(attempts):
            try:
                with tracer.span(
                    "orchestrator.device_solve", cat="solve",
                    algo=self.algo.algo, n_cycles=self.n_cycles,
                ):
                    if self.chaos is not None and self.chaos.device_fault():
                        raise RuntimeError(
                            "chaos: injected device step fault"
                        )
                    r = solve_result(
                        self.dcop,
                        self.algo,
                        n_cycles=self.n_cycles,
                        seed=self.seed,
                        collect_curve=True,
                        infinity=self.infinity,
                    )
                break
            except Exception:
                if attempt + 1 < attempts:
                    logger.warning(
                        "device solve failed (attempt %d/%d), retrying",
                        attempt + 1, attempts, exc_info=True,
                    )
                    continue
                logger.exception("device solve failed")
                self.status = "ERROR"
                self._solve_done.set()
                return
        # everything below reads the solve RESULT, not the shared
        # attributes: the locals keep the publication free of unguarded
        # reads of the _result_lock-protected state (graftlint
        # lock-unguarded-read — the four baselined entries this paid down)
        assignment = r["assignment"]
        cost = r["cost"]
        cost_curve = r.get("cost_curve")
        with self._result_lock:
            self._assignment = assignment
            self._cost = cost
            self._violation = r["violation"]
            self._cycle = r["cycle"]
            self._cost_curve = cost_curve
            self.solve_msg_count = r["msg_count"]
            self.solve_msg_size = r["msg_size"]
        # per-cycle metrics stream (collection mode cycle_change)
        if cost_curve and self.collect_moment == "cycle_change":
            for i, c in enumerate(cost_curve):
                self.mgt.post_msg(
                    self.mgt.name,
                    CycleChangeMessage(cycle=i + 1, cost=c),
                    MSG_VALUE,
                )
        # value readbacks to the hosting agents: the deployed computations
        # see their final value exactly as reference computations see their
        # own value_selection
        if self.distribution is not None:
            for comp_name, value in assignment.items():
                try:
                    agent = self.distribution.agent_for(comp_name)
                except KeyError:
                    continue
                self.mgt.post_msg(
                    f"_mgt_{agent}",
                    Message(
                        "value_readback_fwd",
                        (comp_name, value, cost),
                    ),
                    MSG_VALUE,
                )
        self._solve_done.set()

    # ------------------------------------------------------------------
    # scenario handling (reference :340,:955)
    # ------------------------------------------------------------------

    def _play_scenario(self, scenario: Scenario) -> None:
        # graftdur: the event cursor rides every checkpoint manifest, so
        # a killed scenario run resumes AFTER the events it already
        # played (--resume slices the scenario by the recorded cursor).
        # A RESUMED run plays an already-sliced scenario: the cursor
        # base it seeded (commands/run.py) keeps the recorded cursor in
        # full-scenario coordinates across repeated kill/resume cycles.
        from ..durability import durability

        base = int(
            durability.runtime_extra().get("scenario_cursor", 0) or 0
        )
        for i, event in enumerate(scenario.events):
            if event.is_delay:
                time.sleep(event.delay)
            else:
                for action in event.actions:
                    if action.type == "remove_agent":
                        self._remove_agent(action.args["agent"])
                    elif action.type == "add_agent":
                        self._add_agent(action.args["agent"])
            durability.note_extra(
                scenario_cursor=base + i + 1, scenario_event=event.id
            )

    def _add_agent(self, agent_name: str) -> None:
        """Agent ARRIVAL — elasticity beyond the reference, whose scenario
        handling is remove-only (agent arrival is an explicit TODO at its
        orchestrator.py:1032-1037).  Thread topology: a fresh
        OrchestratedAgent joins in-process, registers with the directory
        and becomes a host candidate for subsequent re-replications and
        repairs.  In a multi-machine run new agents instead join by
        starting their own ``pydcop_tpu agent`` process — arrival there
        IS registration, so this event only logs."""
        from .communication import InProcessCommunicationLayer
        from .orchestratedagents import OrchestratedAgent

        if not isinstance(self._comm, InProcessCommunicationLayer):
            logger.warning(
                "scenario add_agent %s ignored on a networked topology: "
                "start a standalone agent process to join", agent_name,
            )
            return
        if agent_name in self.mgt.registered_agents:
            # a duplicate would re-register the name and hijack the live
            # agent's management route — every message for its hosted
            # computations would land on the empty newcomer
            logger.warning(
                "scenario add_agent %s ignored: an agent with that name "
                "is already registered", agent_name,
            )
            return
        agent_def = self.dcop.agents.get(agent_name)
        if agent_def is None:
            from ..dcop.objects import AgentDef

            agent_def = AgentDef(agent_name)
        self.agent_defs.append(agent_def)
        comm = InProcessCommunicationLayer()
        if self.chaos is not None:
            from ..chaos.layer import ChaosCommunicationLayer

            comm = ChaosCommunicationLayer(comm, self.chaos)
        agent = OrchestratedAgent(
            agent_name,
            comm,
            self.address,
            agent_def=agent_def,
        )
        agent.start()
        self._local_agents[agent_name] = agent
        # block (bounded) until the newcomer has registered: the next
        # scenario event may be a removal whose repair filters candidates
        # by registered_agents — returning early would silently exclude
        # the very agent this event added to help
        deadline = time.perf_counter() + 10.0
        while (
            agent_name not in self.mgt.registered_agents
            and time.perf_counter() < deadline
        ):
            time.sleep(0.02)
        if agent_name not in self.mgt.registered_agents:
            logger.warning(
                "scenario: added agent %s did not register within 10s",
                agent_name,
            )
        else:
            logger.info("scenario: added agent %s", agent_name)
            if self.ktarget is not None:
                # combined elasticity (the reference's orchestrator.py:1032
                # TODO): a newcomer immediately becomes a replication
                # candidate — re-run the negotiation so cheap capacity is
                # used NOW, and a later failure can repair onto it
                logger.info(
                    "re-replicating (k=%d) to include newcomer %s",
                    self.ktarget, agent_name,
                )
                try:
                    self.start_replication(self.ktarget, timeout=15.0)
                except TimeoutError:
                    logger.error(
                        "re-replication after adding %s timed out; "
                        "continuing with previous placements", agent_name,
                    )

    def kill_agent(self, agent_name: str) -> None:
        """Abrupt failure (graftchaos kill events): crash the agent — no
        clean shutdown, inbound transport dies — then run the same repair
        a scenario removal gets.  On thread topologies the local agent
        object is crashed directly; elsewhere the agent is simply treated
        as gone (its process is presumed dead)."""
        if agent_name not in self.mgt.registered_agents:
            logger.warning(
                "chaos: kill of %s ignored: not a registered agent "
                "(registered: %s)",
                agent_name, sorted(self.mgt.registered_agents),
            )
            return
        agent = self._local_agents.get(agent_name)
        if agent is not None:
            agent.crash()
        self._remove_agent(agent_name, crashed=True)

    def _remove_agent(self, agent_name: str, crashed: bool = False) -> None:
        """Simulated failure + repair (reference :955-1124): pause, remove
        the agent, rehost its computations, resume.  ``crashed`` skips the
        polite AgentRemoved notification — a dead agent cannot read it,
        and the message would only sit parked until dead-lettered."""
        logger.info(
            "%s: removing agent %s", "chaos" if crashed else "scenario",
            agent_name,
        )
        event_bus.send("orchestrator.scenario.remove_agent", agent_name)
        with self._repair_lock, tracer.span(
            "orchestrator.repair", cat="lifecycle", agent=agent_name
        ) as sp:
            # pause all surviving agents' computations
            for a in list(self.mgt.registered_agents):
                if a == agent_name:
                    continue
                self.mgt.post_msg(
                    f"_mgt_{a}", PauseMessage(computations=None), MSG_MGT
                )
            if not crashed:
                self.mgt.post_msg(
                    f"_mgt_{agent_name}",
                    AgentRemovedMessage(reason="scenario"),
                    MSG_MGT,
                )
            self.mgt.registered_agents.discard(agent_name)
            # graftucs: the dead agent can neither ack a replication round
            # nor host replicas — prune it before repair picks candidates
            self.mgt.note_agent_gone(agent_name)
            try:
                repair_metrics = self.mgt.repair_orphans(agent_name)
                self._repair_metrics.append(repair_metrics)
                sp.set(orphans=len(repair_metrics.get("orphans", [])))
            except Exception:
                logger.exception(
                    "repair after removing %s failed", agent_name
                )
            for a in list(self.mgt.registered_agents):
                self.mgt.post_msg(
                    f"_mgt_{a}", ResumeMessage(computations=None), MSG_MGT
                )


class AgentsMgt(MessagePassingComputation):
    """The orchestrator's management computation (reference AgentsMgt:535):
    registration barriers, deployment confirmation, metric collection and the
    repair coordination."""

    def __init__(self, orchestrator: Orchestrator) -> None:
        super().__init__(ORCHESTRATOR_MGT)
        self.orchestrator = orchestrator
        self.registered_agents: set = set()
        self.agent_addresses: Dict[str, Any] = {}
        self.deployed: Dict[str, set] = {}
        # computations awaiting a deploy ack; None until the first ack
        # (the distribution may not exist yet at construction time)
        self._pending_deploy: Optional[set] = None
        self.agent_metrics: Dict[str, Dict[str, Any]] = {}
        self.replica_hosts: Dict[str, List[str]] = {}
        self.expected_replications = 0
        self._n_replicated = 0
        # agents whose ReplicateComputations ack arrived: a missed
        # replication barrier reports exactly who stalled
        self.replicated_agents: set = set()
        # graftucs: the agents the CURRENT replication round still expects
        # (an agent dying mid-round is discarded via note_agent_gone so
        # the barrier completes on the survivors), the achieved level per
        # computation (partial k is a result, not a failure) and the
        # round's mode — all surfaced in /status
        self.expected_replication_agents: set = set()
        self.replication_levels: Dict[str, int] = {}
        self.replication_mode_active: Optional[str] = None
        self._replication_armed = False
        # barrier epoch: bumped per round; acks echo it (see the message
        # taxonomy comment on ReplicateComputationsMessage)
        self.replication_round = 0
        self.all_registered = threading.Event()
        self.ready_to_run = threading.Event()
        self.all_replicated = threading.Event()
        self.all_stopped = threading.Event()
        self._stopped_agents: set = set()
        self._finished_computations: set = set()
        # distributed-repair handshake state (reference AgentsMgt repair
        # barriers :1060-1120): agents that acked setup_repair with the
        # computations they can host, and the selections repair_run
        # produced.  repair_orphans today solves the placement centrally;
        # these acks make the agent-side handshake observable so the
        # decentralized negotiation (ROADMAP item 4) lands on live state.
        self.repair_ready_agents: Dict[str, List[str]] = {}
        self.repair_selected: Dict[str, List[str]] = {}
        self.all_repair_ready = threading.Event()
        self.expected_repair_acks = 0
        # barrier epoch, bumped per episode; acks echo it (same contract
        # as replication_round — see the message taxonomy comment)
        self.repair_round = 0

    # -- registration --------------------------------------------------

    @register("register_agent")
    def _on_register_agent(self, sender: str, msg, t: float) -> None:
        self.registered_agents.add(msg.agent)
        self.agent_addresses[msg.agent] = msg.address
        self.orchestrator.directory.directory.agents[msg.agent] = msg.address
        # make the agent's mgt computation routable from the orchestrator
        self.orchestrator._agent.messaging.register_route(
            f"_mgt_{msg.agent}", msg.agent, msg.address
        )
        expected = {a.name for a in self.orchestrator.agent_defs}
        if expected and expected <= self.registered_agents:
            self.all_registered.set()

    @register("deployed")
    def _on_deployed(self, sender: str, msg, t: float) -> None:
        # acks are incremental (one computation each); readiness is a
        # pending-set subtraction, not a rescan of every agent's hosted
        # list — the rescan made deployment O(n^2) at 100k computations.
        # The record is a SET per agent so a re-sent ack (agent
        # reconnect/redeploy) stays idempotent at O(1) (ADVICE round 4)
        self.deployed.setdefault(msg.agent, set()).update(msg.computations)
        dist = self.orchestrator.distribution
        if dist is None:
            return
        if self._pending_deploy is None:
            self._pending_deploy = {
                c for a in dist.agents
                for c in dist.computations_hosted(a)
            }
            for comps in self.deployed.values():
                self._pending_deploy.difference_update(comps)
        else:
            self._pending_deploy.difference_update(msg.computations)
        if not self._pending_deploy:
            self.ready_to_run.set()

    # -- metric collection ---------------------------------------------

    @register("value_change")
    def _on_value_change(self, sender: str, msg, t: float) -> None:
        if self.orchestrator.collector is not None:
            self.orchestrator.collector(
                {
                    "event": "value_change",
                    "computation": msg.computation,
                    "value": msg.value,
                    "cost": msg.cost,
                    "cycle": msg.cycle,
                    "time": t,
                }
            )

    @register("cycle_change")
    def _on_cycle_change(self, sender: str, msg, t: float) -> None:
        if self.orchestrator.collector is not None:
            self.orchestrator.collector(
                {
                    "event": "cycle_change",
                    "cycle": msg.cycle,
                    "cost": msg.cost,
                    "time": t,
                }
            )

    @register("metrics")
    def _on_metrics(self, sender: str, msg, t: float) -> None:
        self.agent_metrics[msg.agent] = msg.metrics
        if self.orchestrator.collector is not None:
            self.orchestrator.collector(
                {"event": "metrics", "agent": msg.agent,
                 "metrics": msg.metrics, "time": t}
            )

    @register("computation_finished")
    def _on_computation_finished(self, sender: str, msg, t: float) -> None:
        self._finished_computations.add(msg.computation)

    @register("agent_stopped")
    def _on_agent_stopped(self, sender: str, msg, t: float) -> None:
        self._stopped_agents.add(msg.agent)
        if msg.metrics:
            self.agent_metrics[msg.agent] = msg.metrics
        if self._stopped_agents >= self.registered_agents:
            self.all_stopped.set()

    def expect_replication(self, agents: set, k: int, mode: str) -> None:
        """Arm the replication barrier for one round: expect an ack from
        every agent in ``agents`` and clear the previous round's ack set
        (a stale ack must never release a new barrier).  Achieved levels
        persist across rounds — a re-replication round overwrites them."""
        self.expected_replication_agents = set(agents)
        self.expected_replications = len(agents)
        self.replicated_agents.clear()
        self.all_replicated.clear()
        self.replication_mode_active = mode
        self.replication_round += 1
        self._replication_armed = True

    def note_agent_gone(self, agent_name: str) -> None:
        """An agent died or was removed: the current replication round
        must not wait for its ack, it is not routable (a later round must
        not ship the corpse as a candidate), and it can no longer host
        replicas — drop it everywhere placement decisions read.

        Runs on the chaos-timeline/scenario thread while the mgt thread
        may be inserting round reports — iterate over SNAPSHOTS, the same
        discipline watch_status uses for the agents dict."""
        self.expected_replication_agents.discard(agent_name)
        self._check_replication_barrier()
        self.agent_addresses.pop(agent_name, None)
        for comp, hosts in list(self.replica_hosts.items()):
            if agent_name in hosts:
                hosts.remove(agent_name)
                self.replication_levels[comp] = len(hosts)
        for holders in list(
            self.orchestrator.directory.directory.replicas.values()
        ):
            holders.discard(agent_name)

    def _check_replication_barrier(self) -> None:
        self._n_replicated = len(self.replicated_agents)
        if (
            self._replication_armed
            and self.replicated_agents >= self.expected_replication_agents
        ):
            self.all_replicated.set()

    @register("replicated")
    def _on_replicated(self, sender: str, msg, t: float) -> None:
        # placements are real regardless of the round that produced them
        # (the owner DID ship those replicas) — always merge the view,
        # but never re-admit a host that died since the owner committed
        # it (the owner's fire-and-forget commit may have landed on a
        # corpse note_agent_gone already pruned)
        for comp, hosts in (msg.replica_hosts or {}).items():
            live = [h for h in hosts if h in self.registered_agents]
            self.replica_hosts[comp] = live
            self.replication_levels[comp] = len(live)
            for h in live:
                self.orchestrator.directory.directory.replicas.setdefault(
                    comp, set()
                ).add(h)
        # ...but only an ack of the CURRENT round counts toward the
        # barrier: a round-1 ack arriving after round 1's timeout must not
        # release round 2 while that agent's new negotiation still runs.
        # Set-based like the registration/stop barriers: a duplicated ack
        # (at-least-once transport, chaos 'duplicate' faults) must not
        # release the barrier while another agent is still replicating
        ack_round = getattr(msg, "round", None)
        if ack_round is not None and ack_round != self.replication_round:
            logger.info(
                "stale replication ack from %s (round %s, current %s)",
                msg.agent, ack_round, self.replication_round,
            )
            return
        self.replicated_agents.add(msg.agent)
        self._check_replication_barrier()

    @register("replica_retracted")
    def _on_replica_retracted(self, sender: str, msg, t: float) -> None:
        """A host removed a committed replica (released by its owner, shed
        on capacity loss, dropped on migration): prune the orchestrator's
        placement view so repair candidates and ``/status`` levels track
        reality — replicas no longer only accumulate."""
        hosts = self.replica_hosts.get(msg.comp)
        if hosts and msg.agent in hosts:
            hosts.remove(msg.agent)
            self.replication_levels[msg.comp] = len(hosts)
        self.orchestrator.directory.directory.replicas.get(
            msg.comp, set()
        ).discard(msg.agent)
        logger.debug(
            "replica of %s retracted by %s (%s)",
            msg.comp, msg.agent, msg.reason,
        )

    # -- repair --------------------------------------------------------

    def expect_repair_acks(self, n: int) -> None:
        """Arm the repair-ready barrier for one repair episode: expect
        ``n`` ``repair_ready`` acks and clear state left over from any
        previous episode.  The bumped ``repair_round`` is what actually
        keeps stale acks out: a straggler's late ack from a timed-out
        episode echoes the old round and is dropped by the handlers.
        The bump happens FIRST — bumping after arming would leave a
        window where a queued stale ack still matches the live round
        and counts toward the fresh barrier (no current-round ack can
        exist yet, since no setup_repair has been sent)."""
        self.repair_round += 1
        self.repair_ready_agents.clear()
        self.repair_selected.clear()
        self.all_repair_ready.clear()
        self.expected_repair_acks = n

    @register("repair_ready")
    def _on_repair_ready(self, sender: str, msg, t: float) -> None:
        """An agent finished ``setup_repair`` and names the orphaned
        computations it is a candidate host for.  Until this handler
        existed the ack was silently dropped (graftlint
        proto-unhandled-message), so the repair barrier could only be
        inferred, never observed."""
        ack_round = getattr(msg, "round", None)
        if ack_round is not None and ack_round != self.repair_round:
            logger.info(
                "stale repair_ready ack from %s (round %s, current %s)",
                msg.agent, ack_round, self.repair_round,
            )
            return
        self.repair_ready_agents[msg.agent] = list(msg.computations or [])
        if ack_round is not None and ack_round != self.repair_round:
            # a new episode armed on the scenario thread between the
            # check above and the insert: this ack belongs to the dead
            # episode — withdraw it instead of counting it toward the
            # fresh barrier (the residual window after this re-check is
            # the same advisory-barrier semantics a timeout has)
            self.repair_ready_agents.pop(msg.agent, None)
            return
        if (
            self.expected_repair_acks
            and len(self.repair_ready_agents) >= self.expected_repair_acks
        ):
            self.all_repair_ready.set()

    @register("repair_done")
    def _on_repair_done(self, sender: str, msg, t: float) -> None:
        """An agent's ``repair_run`` selection: the computations it chose
        to host.  Recorded per agent so a decentralized repair (ROADMAP
        item 4) can reconcile selections against the orchestrator's
        distribution instead of assuming orchestrator-accurate
        knowledge."""
        ack_round = getattr(msg, "round", None)
        if ack_round is not None and ack_round != self.repair_round:
            logger.info(
                "stale repair_done ack from %s (round %s, current %s)",
                msg.agent, ack_round, self.repair_round,
            )
            return
        self.repair_selected[msg.agent] = list(msg.selected or [])
        if ack_round is not None and ack_round != self.repair_round:
            # lost the race with a new episode arming: withdraw
            self.repair_selected.pop(msg.agent, None)

    #: bound on the repair-ready barrier: the repair must never hang on
    #: a silent survivor (it may itself be mid-crash), it degrades to
    #: the orchestrator's own knowledge after naming the stragglers
    REPAIR_READY_TIMEOUT = 5.0

    def repair_orphans(self, removed_agent: str) -> Dict[str, Any]:
        """Re-host the computations of a removed agent.

        The conversation is the reference's repair handshake
        (orchestrator.py:1060-1120): ``setup_repair`` fans out to every
        survivor, which answers ``repair_ready`` naming the orphans it
        holds replicas of; once the (bounded) ready barrier passes, the
        placement is decided and shipped, and ``repair_run`` tells the
        survivors to activate — their ``repair_done`` selections land in
        :attr:`repair_selected`.  Until graftproto's
        proto-unsent-message rule flagged it, the send half of this
        conversation did not exist: setup_repair/repair_run were
        declared + handled but never posted, so the handlers added for
        the PR-6 protocol-debt paydown were dead code and the barrier
        state they feed never armed.

        With replicas (start_replication ran): candidates = replica holders,
        and the selection is the reference's repair DCOP — binary variables
        x_(computation, agent) under hosted/capacity/hosting-cost/comm-cost
        constraints (reparation/__init__.py) — solved with MGM-2 *on device*
        like any other DCOP (the reference solves it with distributed MGM-2
        on the surviving agents, agents.py:1047-1258).  Without replicas,
        fall back to the distribution module's greedy re-distribution.
        """
        from ..reparation import repair_distribution

        dist = self.orchestrator.distribution
        orphans = list(dist.computations_hosted(removed_agent))
        if not orphans:
            return {"orphans": [], "migrated": {}}
        # phase 1: setup_repair -> repair_ready (bounded barrier).
        # Survivors' _mgt_ computations stay live through the repair
        # freeze (blanket pauses skip control-plane computations), so
        # the acks flow while the algorithm computations are paused.
        survivors = sorted(self.registered_agents)
        self.expect_repair_acks(len(survivors))
        repair_info = {
            "orphans": orphans,
            "removed": removed_agent,
            "round": self.repair_round,
        }
        for a in survivors:
            self.post_msg(
                f"_mgt_{a}",
                SetupRepairMessage(repair_info=repair_info),
                MSG_MGT,
            )
        if survivors and not self.all_repair_ready.wait(
            self.REPAIR_READY_TIMEOUT
        ):
            # snapshot before iterating: the mgt thread may still be
            # inserting the very ack we timed out on (dict() is one
            # C-level copy under the GIL — the discipline note_agent_gone
            # and watch_status follow)
            acked = dict(self.repair_ready_agents)
            missing = sorted(set(survivors) - set(acked))
            logger.warning(
                "repair-ready barrier missed %d/%d ack(s) within "
                "%.1fs (no repair_ready from %s) — proceeding with "
                "the orchestrator's own placement knowledge",
                len(missing), len(survivors),
                self.REPAIR_READY_TIMEOUT, missing,
            )
        new_dist, metrics = repair_distribution(
            self.orchestrator.cg,
            [
                a
                for a in self.orchestrator.agent_defs
                if a.name in self.registered_agents
            ],
            dist,
            removed_agent,
            self.orchestrator.algo,
            replica_hosts=self.replica_hosts or None,
        )
        self.orchestrator.distribution = new_dist
        # phase 2: deploy migrated computations on their new hosts
        for comp in orphans:
            new_agent = new_dist.agent_for(comp)
            node = self.orchestrator.cg.computation(comp)
            self.post_msg(
                f"_mgt_{new_agent}",
                DeployMessage(
                    comp_def=ComputationDef(node, self.orchestrator.algo)
                ),
                MSG_MGT,
            )
        # phase 3: repair_run -> repair_done (fire-and-forget: the
        # selections are bookkeeping for the decentralized repair of
        # ROADMAP item 4, nothing blocks on them)
        for a in survivors:
            self.post_msg(f"_mgt_{a}", RepairRunMessage(), MSG_MGT)
        metrics["orphans"] = orphans
        metrics["repair_ready_agents"] = sorted(
            dict(self.repair_ready_agents)
        )
        return metrics
