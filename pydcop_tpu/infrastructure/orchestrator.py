"""Orchestrator: bootstrap, deployment, run control, metrics sink, scenario
driver and repair coordinator.

Role parity with /root/reference/pydcop/infrastructure/orchestrator.py:
``Orchestrator`` (:62 — an Agent named "orchestrator" hosting the Directory
and an ``AgentsMgt`` management computation; API start:170,
deploy_computations:203, start_replication:223, run:245, stop_agents:291,
current_solution:309, end_metrics:312) and ``AgentsMgt`` (:535 — registration
barriers, deploy fan-out, value/cycle/metric collection, scenario handling,
repair barriers).  The management message taxonomy mirrors the reference's
(:385-438).

TPU-first inversion (SURVEY.md §2.8): the reference's agents *compute* — the
orchestrator only coordinates.  Here the orchestrator also owns the device:
``run()`` compiles the DCOP once and advances ALL computations as one scan on
the TPU, then publishes per-cycle metrics and value readbacks to the hosting
agents so the rest of the control plane (metrics modes, UI, discovery,
resilience) observes exactly what the reference's would.  Agents host
bookkeeping computations + the repair protocol; algorithm messages never
exist host-side.  On a multi-host mesh the same orchestrator drives the
sharded solve through ``parallel/mesh.py`` (jax.distributed), which is the
TPU equivalent of the reference's process/HTTP deployment.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..algorithms import AlgorithmDef, ComputationDef
from ..dcop.dcop import DCOP
from ..dcop.scenario import Scenario
from ..distribution.objects import Distribution
from .agents import Agent
from .communication import (
    CommunicationLayer,
    InProcessCommunicationLayer,
    MSG_MGT,
    MSG_VALUE,
)
from .computations import (
    Message,
    MessagePassingComputation,
    message_type,
    register,
)
from ..telemetry.tracing import tracer
from .discovery import DirectoryComputation
from .events import event_bus

__all__ = ["Orchestrator", "AgentsMgt", "ORCHESTRATOR"]

logger = logging.getLogger("pydcop_tpu.orchestrator")

ORCHESTRATOR = "orchestrator"
ORCHESTRATOR_MGT = "_mgt_orchestrator"

# -- management message taxonomy (reference orchestrator.py:385-438) --------

DeployMessage = message_type("deploy", ["comp_def"])
RunAgentMessage = message_type("run_computations", ["computations"])
PauseMessage = message_type("pause_computations", ["computations"])
ResumeMessage = message_type("resume_computations", ["computations"])
StopAgentMessage = message_type("stop_agent", ["forced"])
AgentRemovedMessage = message_type("agent_removed", ["reason"])
RegisterAgentMessage = message_type("register_agent", ["agent", "address"])
DeployedMessage = message_type("deployed", ["agent", "computations"])
ValueChangeMessage = message_type(
    "value_change", ["computation", "value", "cost", "cycle"]
)
CycleChangeMessage = message_type("cycle_change", ["cycle", "cost"])
MetricsMessage = message_type("metrics", ["agent", "metrics"])
ComputationFinishedMessage = message_type(
    "computation_finished", ["computation"]
)
AgentStoppedMessage = message_type("agent_stopped", ["agent", "metrics"])
ReplicateComputationsMessage = message_type("replication", ["k", "agents"])
ComputationReplicatedMessage = message_type(
    "replicated", ["agent", "replica_hosts"]
)
SetupRepairMessage = message_type("setup_repair", ["repair_info"])
RepairReadyMessage = message_type("repair_ready", ["agent", "computations"])
RepairRunMessage = message_type("repair_run", [])
RepairDoneMessage = message_type("repair_done", ["agent", "selected"])


class Orchestrator:
    """Control plane for one DCOP run."""

    def __init__(
        self,
        algo: AlgorithmDef,
        cg,
        agent_defs: List[Any],
        dcop: DCOP,
        distribution: Optional[Distribution] = None,
        comm: Optional[CommunicationLayer] = None,
        collector: Optional[Callable[[Dict[str, Any]], None]] = None,
        collect_moment: str = "value_change",
        collect_period: Optional[float] = None,
        n_cycles: int = 100,
        seed: int = 0,
        infinity: float = 10000,
    ) -> None:
        self.algo = algo
        self.cg = cg
        self.dcop = dcop
        self.agent_defs = list(agent_defs)
        self.distribution = distribution
        self.collector = collector
        self.collect_moment = collect_moment
        self.collect_period = collect_period
        self.n_cycles = n_cycles
        self.seed = seed
        self.infinity = infinity

        self._comm = comm or InProcessCommunicationLayer()
        self._agent = Agent(ORCHESTRATOR, self._comm)
        self.directory = DirectoryComputation()
        self._agent.add_computation(self.directory, publish=False)
        self.mgt = AgentsMgt(self)
        self._agent.add_computation(self.mgt, publish=False)

        self.start_time: Optional[float] = None
        self.status = "NOT_STARTED"
        self._result_lock = threading.Lock()
        self._assignment: Dict[str, Any] = {}
        self._cost: Optional[float] = None
        self._violation: Optional[int] = None
        self._cycle = 0
        self._cost_curve: Optional[List[float]] = None
        self._solve_thread: Optional[threading.Thread] = None
        self._solve_done = threading.Event()
        self._repair_metrics: List[Dict[str, Any]] = []
        self.solve_msg_count = 0
        self.solve_msg_size = 0

    # ------------------------------------------------------------------
    # public API (reference orchestrator.py:170-330)
    # ------------------------------------------------------------------

    @property
    def address(self) -> Any:
        return self._comm.address

    def start(self) -> "Orchestrator":
        self._agent.start()
        self._agent.computation(self.directory.name).start()
        self._agent.computation(self.mgt.name).start()
        self.status = "STARTED"
        return self

    def deploy_computations(self, timeout: float = 10.0) -> None:
        """Wait for all agents to register, then ship every ComputationDef to
        its hosting agent's management computation (reference :203,:915)."""
        with tracer.span(
            "orchestrator.deploy", cat="lifecycle",
            n_agents=len(self.agent_defs), n_computations=len(self.cg.nodes),
        ):
            if not self.mgt.all_registered.wait(timeout):
                missing = set(a.name for a in self.agent_defs) - set(
                    self.mgt.registered_agents
                )
                raise TimeoutError(
                    f"agents failed to register in {timeout}s: "
                    f"{sorted(missing)}"
                )
            if self.distribution is None:
                raise ValueError("no distribution to deploy")
            for agent_name in self.distribution.agents:
                comp_defs = []
                for comp_name in self.distribution.computations_hosted(
                    agent_name
                ):
                    node = self.cg.computation(comp_name)
                    comp_defs.append(ComputationDef(node, self.algo))
                for cd in comp_defs:
                    self.mgt.post_msg(
                        f"_mgt_{agent_name}", DeployMessage(comp_def=cd),
                        MSG_MGT,
                    )

    def start_replication(self, k: int, timeout: float = 10.0) -> None:
        """Ask every agent to replicate its computations k times
        (reference :223); blocks until the replication barrier passes."""
        self.mgt.expected_replications = len(
            [a for a in self.distribution.agents]
        )
        known = dict(self.mgt.agent_addresses)
        for agent_name in self.distribution.agents:
            self.mgt.post_msg(
                f"_mgt_{agent_name}",
                ReplicateComputationsMessage(k=k, agents=known),
                MSG_MGT,
            )
        if not self.mgt.all_replicated.wait(timeout):
            raise TimeoutError("replication did not complete")

    def run(
        self,
        scenario: Optional[Scenario] = None,
        timeout: Optional[float] = None,
        repair_only: bool = False,
        ready_timeout: Optional[float] = None,
    ) -> None:
        """Start the computations and drive the device solve to completion
        (reference :245).  Blocks until finished / timeout.

        ``ready_timeout`` bounds the wait for deployment confirmations;
        the default scales with the number of computations (each is one
        round-trip through the management plane — measured ~1ms each, so
        10k computations need more than a fixed 10s).
        """
        if ready_timeout is None:
            ready_timeout = 10.0 + 0.005 * len(self.cg.nodes)
        if not self.mgt.ready_to_run.wait(ready_timeout):
            raise TimeoutError("deployment did not complete")
        self.start_time = time.perf_counter()
        self.status = "RUNNING"
        for agent_name in self.distribution.agents:
            self.mgt.post_msg(
                f"_mgt_{agent_name}",
                RunAgentMessage(
                    computations=self.distribution.computations_hosted(
                        agent_name
                    )
                ),
                MSG_MGT,
            )
        self._solve_thread = threading.Thread(
            target=self._device_solve, name="device-solve", daemon=True
        )
        self._solve_thread.start()

        if scenario is not None:
            self._play_scenario(scenario)

        budget = None if timeout is None else timeout
        finished = self._solve_done.wait(budget)
        if not finished:
            self.status = "TIMEOUT"
        elif self.status == "RUNNING":
            self.status = "FINISHED"

    def current_solution(self):
        with self._result_lock:
            return dict(self._assignment), self._cost

    def stop_agents(self, timeout: float = 5.0) -> None:
        """Ask every agent to stop cleanly (reference :291)."""
        with tracer.span(
            "orchestrator.stop_agents", cat="lifecycle",
            n_agents=len(self.mgt.registered_agents),
        ):
            for a in list(self.mgt.registered_agents):
                self.mgt.post_msg(
                    f"_mgt_{a}", StopAgentMessage(forced=False), MSG_MGT
                )
            self.mgt.all_stopped.wait(timeout)

    def stop(self) -> None:
        self._agent.clean_shutdown()
        self._agent.join()
        self.status = "STOPPED" if self.status != "FINISHED" else self.status

    def end_metrics(self) -> Dict[str, Any]:
        """Global metrics in the reference's schema (orchestrator.py:1215)."""
        with self._result_lock:
            msg_count = sum(
                m.get("count_ext_msg", {}).get(c, 0)
                for m in self.mgt.agent_metrics.values()
                for c in m.get("count_ext_msg", {})
            )
            msg_size = sum(
                m.get("size_ext_msg", {}).get(c, 0)
                for m in self.mgt.agent_metrics.values()
                for c in m.get("size_ext_msg", {})
            )
            return {
                "status": self.status,
                "assignment": dict(self._assignment),
                "cost": self._cost,
                "violation": self._violation,
                "cycle": self._cycle,
                "msg_count": self.solve_msg_count + msg_count,
                "msg_size": self.solve_msg_size + msg_size,
                "time": (
                    time.perf_counter() - self.start_time
                    if self.start_time
                    else 0.0
                ),
                "cost_curve": self._cost_curve,
                "repair_metrics": list(self._repair_metrics),
            }

    # ------------------------------------------------------------------
    # the device solve (replaces the reference's per-agent algorithm run)
    # ------------------------------------------------------------------

    def _device_solve(self) -> None:
        from ..api import solve_result

        try:
            with tracer.span(
                "orchestrator.device_solve", cat="solve",
                algo=self.algo.algo, n_cycles=self.n_cycles,
            ):
                r = solve_result(
                    self.dcop,
                    self.algo,
                    n_cycles=self.n_cycles,
                    seed=self.seed,
                    collect_curve=True,
                    infinity=self.infinity,
                )
        except Exception:
            logger.exception("device solve failed")
            self.status = "ERROR"
            self._solve_done.set()
            return
        with self._result_lock:
            self._assignment = r["assignment"]
            self._cost = r["cost"]
            self._violation = r["violation"]
            self._cycle = r["cycle"]
            self._cost_curve = r.get("cost_curve")
            self.solve_msg_count = r["msg_count"]
            self.solve_msg_size = r["msg_size"]
        # per-cycle metrics stream (collection mode cycle_change)
        if self._cost_curve and self.collect_moment == "cycle_change":
            for i, c in enumerate(self._cost_curve):
                self.mgt.post_msg(
                    self.mgt.name,
                    CycleChangeMessage(cycle=i + 1, cost=c),
                    MSG_VALUE,
                )
        # value readbacks to the hosting agents: the deployed computations
        # see their final value exactly as reference computations see their
        # own value_selection
        if self.distribution is not None:
            for comp_name, value in self._assignment.items():
                try:
                    agent = self.distribution.agent_for(comp_name)
                except KeyError:
                    continue
                self.mgt.post_msg(
                    f"_mgt_{agent}",
                    Message(
                        "value_readback_fwd",
                        (comp_name, value, self._cost),
                    ),
                    MSG_VALUE,
                )
        self._solve_done.set()

    # ------------------------------------------------------------------
    # scenario handling (reference :340,:955)
    # ------------------------------------------------------------------

    def _play_scenario(self, scenario: Scenario) -> None:
        for event in scenario.events:
            if event.is_delay:
                time.sleep(event.delay)
                continue
            for action in event.actions:
                if action.type == "remove_agent":
                    self._remove_agent(action.args["agent"])
                elif action.type == "add_agent":
                    self._add_agent(action.args["agent"])

    def _add_agent(self, agent_name: str) -> None:
        """Agent ARRIVAL — elasticity beyond the reference, whose scenario
        handling is remove-only (agent arrival is an explicit TODO at its
        orchestrator.py:1032-1037).  Thread topology: a fresh
        OrchestratedAgent joins in-process, registers with the directory
        and becomes a host candidate for subsequent re-replications and
        repairs.  In a multi-machine run new agents instead join by
        starting their own ``pydcop_tpu agent`` process — arrival there
        IS registration, so this event only logs."""
        from .communication import InProcessCommunicationLayer
        from .orchestratedagents import OrchestratedAgent

        if not isinstance(self._comm, InProcessCommunicationLayer):
            logger.warning(
                "scenario add_agent %s ignored on a networked topology: "
                "start a standalone agent process to join", agent_name,
            )
            return
        if agent_name in self.mgt.registered_agents:
            # a duplicate would re-register the name and hijack the live
            # agent's management route — every message for its hosted
            # computations would land on the empty newcomer
            logger.warning(
                "scenario add_agent %s ignored: an agent with that name "
                "is already registered", agent_name,
            )
            return
        agent_def = self.dcop.agents.get(agent_name)
        if agent_def is None:
            from ..dcop.objects import AgentDef

            agent_def = AgentDef(agent_name)
        self.agent_defs.append(agent_def)
        agent = OrchestratedAgent(
            agent_name,
            InProcessCommunicationLayer(),
            self.address,
            agent_def=agent_def,
        )
        agent.start()
        # block (bounded) until the newcomer has registered: the next
        # scenario event may be a removal whose repair filters candidates
        # by registered_agents — returning early would silently exclude
        # the very agent this event added to help
        deadline = time.perf_counter() + 10.0
        while (
            agent_name not in self.mgt.registered_agents
            and time.perf_counter() < deadline
        ):
            time.sleep(0.02)
        if agent_name not in self.mgt.registered_agents:
            logger.warning(
                "scenario: added agent %s did not register within 10s",
                agent_name,
            )
        else:
            logger.info("scenario: added agent %s", agent_name)

    def _remove_agent(self, agent_name: str) -> None:
        """Simulated failure + repair (reference :955-1124): pause, remove
        the agent, rehost its computations, resume."""
        logger.info("scenario: removing agent %s", agent_name)
        event_bus.send("orchestrator.scenario.remove_agent", agent_name)
        with tracer.span(
            "orchestrator.repair", cat="lifecycle", agent=agent_name
        ) as sp:
            # pause all surviving agents' computations
            for a in list(self.mgt.registered_agents):
                self.mgt.post_msg(
                    f"_mgt_{a}", PauseMessage(computations=None), MSG_MGT
                )
            self.mgt.post_msg(
                f"_mgt_{agent_name}", AgentRemovedMessage(reason="scenario"),
                MSG_MGT,
            )
            self.mgt.registered_agents.discard(agent_name)
            try:
                repair_metrics = self.mgt.repair_orphans(agent_name)
                self._repair_metrics.append(repair_metrics)
                sp.set(orphans=len(repair_metrics.get("orphans", [])))
            except Exception:
                logger.exception(
                    "repair after removing %s failed", agent_name
                )
            for a in list(self.mgt.registered_agents):
                self.mgt.post_msg(
                    f"_mgt_{a}", ResumeMessage(computations=None), MSG_MGT
                )


class AgentsMgt(MessagePassingComputation):
    """The orchestrator's management computation (reference AgentsMgt:535):
    registration barriers, deployment confirmation, metric collection and the
    repair coordination."""

    def __init__(self, orchestrator: Orchestrator) -> None:
        super().__init__(ORCHESTRATOR_MGT)
        self.orchestrator = orchestrator
        self.registered_agents: set = set()
        self.agent_addresses: Dict[str, Any] = {}
        self.deployed: Dict[str, set] = {}
        # computations awaiting a deploy ack; None until the first ack
        # (the distribution may not exist yet at construction time)
        self._pending_deploy: Optional[set] = None
        self.agent_metrics: Dict[str, Dict[str, Any]] = {}
        self.replica_hosts: Dict[str, List[str]] = {}
        self.expected_replications = 0
        self._n_replicated = 0
        self.all_registered = threading.Event()
        self.ready_to_run = threading.Event()
        self.all_replicated = threading.Event()
        self.all_stopped = threading.Event()
        self._stopped_agents: set = set()
        self._finished_computations: set = set()

    # -- registration --------------------------------------------------

    @register("register_agent")
    def _on_register_agent(self, sender: str, msg, t: float) -> None:
        self.registered_agents.add(msg.agent)
        self.agent_addresses[msg.agent] = msg.address
        self.orchestrator.directory.directory.agents[msg.agent] = msg.address
        # make the agent's mgt computation routable from the orchestrator
        self.orchestrator._agent.messaging.register_route(
            f"_mgt_{msg.agent}", msg.agent, msg.address
        )
        expected = {a.name for a in self.orchestrator.agent_defs}
        if expected and expected <= self.registered_agents:
            self.all_registered.set()

    @register("deployed")
    def _on_deployed(self, sender: str, msg, t: float) -> None:
        # acks are incremental (one computation each); readiness is a
        # pending-set subtraction, not a rescan of every agent's hosted
        # list — the rescan made deployment O(n^2) at 100k computations.
        # The record is a SET per agent so a re-sent ack (agent
        # reconnect/redeploy) stays idempotent at O(1) (ADVICE round 4)
        self.deployed.setdefault(msg.agent, set()).update(msg.computations)
        dist = self.orchestrator.distribution
        if dist is None:
            return
        if self._pending_deploy is None:
            self._pending_deploy = {
                c for a in dist.agents
                for c in dist.computations_hosted(a)
            }
            for comps in self.deployed.values():
                self._pending_deploy.difference_update(comps)
        else:
            self._pending_deploy.difference_update(msg.computations)
        if not self._pending_deploy:
            self.ready_to_run.set()

    # -- metric collection ---------------------------------------------

    @register("value_change")
    def _on_value_change(self, sender: str, msg, t: float) -> None:
        if self.orchestrator.collector is not None:
            self.orchestrator.collector(
                {
                    "event": "value_change",
                    "computation": msg.computation,
                    "value": msg.value,
                    "cost": msg.cost,
                    "cycle": msg.cycle,
                    "time": t,
                }
            )

    @register("cycle_change")
    def _on_cycle_change(self, sender: str, msg, t: float) -> None:
        if self.orchestrator.collector is not None:
            self.orchestrator.collector(
                {
                    "event": "cycle_change",
                    "cycle": msg.cycle,
                    "cost": msg.cost,
                    "time": t,
                }
            )

    @register("metrics")
    def _on_metrics(self, sender: str, msg, t: float) -> None:
        self.agent_metrics[msg.agent] = msg.metrics
        if self.orchestrator.collector is not None:
            self.orchestrator.collector(
                {"event": "metrics", "agent": msg.agent,
                 "metrics": msg.metrics, "time": t}
            )

    @register("computation_finished")
    def _on_computation_finished(self, sender: str, msg, t: float) -> None:
        self._finished_computations.add(msg.computation)

    @register("agent_stopped")
    def _on_agent_stopped(self, sender: str, msg, t: float) -> None:
        self._stopped_agents.add(msg.agent)
        if msg.metrics:
            self.agent_metrics[msg.agent] = msg.metrics
        if self._stopped_agents >= self.registered_agents:
            self.all_stopped.set()

    @register("replicated")
    def _on_replicated(self, sender: str, msg, t: float) -> None:
        for comp, hosts in (msg.replica_hosts or {}).items():
            self.replica_hosts[comp] = list(hosts)
            for h in hosts:
                self.orchestrator.directory.directory.replicas.setdefault(
                    comp, set()
                ).add(h)
        self._n_replicated += 1
        if self._n_replicated >= self.expected_replications:
            self.all_replicated.set()

    # -- repair --------------------------------------------------------

    def repair_orphans(self, removed_agent: str) -> Dict[str, Any]:
        """Re-host the computations of a removed agent.

        With replicas (start_replication ran): candidates = replica holders,
        and the selection is the reference's repair DCOP — binary variables
        x_(computation, agent) under hosted/capacity/hosting-cost/comm-cost
        constraints (reparation/__init__.py) — solved with MGM-2 *on device*
        like any other DCOP (the reference solves it with distributed MGM-2
        on the surviving agents, agents.py:1047-1258).  Without replicas,
        fall back to the distribution module's greedy re-distribution.
        """
        from ..reparation import repair_distribution

        dist = self.orchestrator.distribution
        orphans = list(dist.computations_hosted(removed_agent))
        if not orphans:
            return {"orphans": [], "migrated": {}}
        new_dist, metrics = repair_distribution(
            self.orchestrator.cg,
            [
                a
                for a in self.orchestrator.agent_defs
                if a.name in self.registered_agents
            ],
            dist,
            removed_agent,
            self.orchestrator.algo,
            replica_hosts=self.replica_hosts or None,
        )
        self.orchestrator.distribution = new_dist
        # deploy migrated computations on their new hosts
        for comp in orphans:
            new_agent = new_dist.agent_for(comp)
            node = self.orchestrator.cg.computation(comp)
            self.post_msg(
                f"_mgt_{new_agent}",
                DeployMessage(
                    comp_def=ComputationDef(node, self.orchestrator.algo)
                ),
                MSG_MGT,
            )
        metrics["orphans"] = orphans
        return metrics
