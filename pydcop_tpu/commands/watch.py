"""``pydcop_tpu watch``: live terminal view of a running solve.

Polls the orchestrator's graftwatch surface (``--metrics-port`` on
``solve``/``run``/``chaos``) and renders, in place: the run status, the
anytime cost descending (``solve.best_cost`` sparkline), per-agent queue
depths, message rates (derived from counter deltas between polls) and the
reliability/chaos counters.  Host-only: never touches a device backend —
it is safe to run from a second terminal next to a TPU solve.

``--once`` prints a single frame and exits (scriptable health check, the
watch-smoke gate); ``--json`` emits the merged status+metrics document
instead of the terminal view.

``--fleet URL`` points the watch at a graftfleet federation surface
(``pydcop_tpu fleet``) instead of a single worker: the frame becomes the
live per-worker table — up/down, scrape age, queue depth + watermark,
solves and solves/s (from ``fleet.worker_solves_total`` counter deltas
between polls, clamped at 0 across worker restarts), batch occupancy,
pulse digest, burn rate — plus the fleet totals and fleet SLO lines.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict, Optional, Tuple

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.watch")

#: terminal statuses: once the run reports one of these, stop polling
_TERMINAL = {"FINISHED", "STOPPED", "ERROR", "TIMEOUT"}

_SPARK = "▁▂▃▄▅▆▇█"


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "watch", help="live terminal view of a running solve's metrics"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "url", nargs="?", default=None,
        help="base URL of the orchestrator metrics surface "
        "(default http://127.0.0.1:PORT from --port)",
    )
    parser.add_argument(
        "--port", type=int, default=9001,
        help="metrics port when no URL is given (default 9001)",
    )
    parser.add_argument(
        "--host", default="127.0.0.1",
        help="metrics host when no URL is given",
    )
    parser.add_argument(
        "--interval", type=float, default=1.0,
        help="seconds between polls (default 1.0)",
    )
    parser.add_argument(
        "--duration", type=float, default=None,
        help="stop watching after this many seconds",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (non-zero if unreachable)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the merged status+metrics JSON instead of the view",
    )
    parser.add_argument(
        "--fleet", default=None, metavar="URL",
        help="watch a graftfleet federation surface (pydcop_tpu fleet) "
        "instead of a single worker: renders the per-worker table",
    )


def _fetch_json(base: str, path: str) -> Optional[Dict[str, Any]]:
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(base + path, timeout=2.0) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError, TimeoutError):
        return None


def _metric_values(
    metrics: Dict[str, Any], name: str
) -> Dict[Tuple[Tuple[str, str], ...], float]:
    m = metrics.get(name)
    if not m:
        return {}
    out = {}
    for entry in m.get("values", []):
        value = entry.get("value")
        if isinstance(value, dict):  # histogram: use the count
            value = value.get("count", 0)
        out[tuple(sorted(entry.get("labels", {}).items()))] = float(value)
    return out


def _total(metrics: Dict[str, Any], name: str) -> float:
    return sum(_metric_values(metrics, name).values())


def sparkline(values, width: int = 60) -> str:
    """Unicode sparkline of a numeric series, decimated to ``width``."""
    from ..telemetry.summary import decimate_series

    vals = [float(v) for v in values if v is not None]
    if not vals:
        return ""
    vals = decimate_series(vals, width)
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * (len(_SPARK) - 1)))]
        for v in vals
    )


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _render_frame(
    status: Dict[str, Any],
    metrics: Dict[str, Any],
    rates: Dict[str, Dict[str, float]],
) -> str:
    lines = []
    best = status.get("best_cost")
    lines.append(
        f"status: {status.get('status', '?'):<10} "
        f"t={status.get('time', 0.0):>7.2f}s  "
        f"cycle={status.get('cycle', 0)}  "
        f"cost={status.get('cost')}  "
        f"best={best if best is not None else '-'}"
        + (
            f" (cycle {int(status['cycles_to_best'])})"
            if status.get("cycles_to_best") is not None
            else ""
        )
    )
    curve = status.get("cost_curve")
    if curve:
        lines.append(f"anytime cost  {sparkline(curve)}")
        lines.append(
            f"              {curve[0]:.6g} -> {curve[-1]:.6g} "
            f"({len(curve)} points)"
        )
    pulse_b = status.get("pulse")
    if pulse_b:
        # graftpulse solver-health block: one diagnosis line + the churn
        # sparkline (fraction of variables flipping per cycle)
        lines.append(
            f"pulse: {pulse_b.get('diagnosis', '?'):<24} "
            f"cycle={pulse_b.get('cycle', 0)}  "
            f"churn={pulse_b.get('churn', 0.0):.3f}  "
            f"residual={pulse_b.get('residual', 0.0):.4g}  "
            f"violations={int(pulse_b.get('violations', 0))}"
        )
        churn_series = pulse_b.get("churn_series")
        if churn_series:
            lines.append(f"churn         {sparkline(churn_series)}")
    slo_b = status.get("slo")
    if slo_b:
        # graftslo: one line per objective — budget left, the fast-window
        # burn rate, and the firing alert if any (the actionable part)
        for name, ob in sorted(
            (slo_b.get("objectives") or {}).items()
        ):
            alert = ob.get("alert")
            lines.append(
                f"slo: {name:<18} budget={100.0 * ob.get('budget_remaining', 1.0):6.1f}%  "
                f"burn={ob.get('burn_fast', 0.0):6.2f}  "
                f"good/bad={int(ob.get('good', 0))}/{int(ob.get('bad', 0))}"
                + (f"  ALERT[{alert}]" if alert else "")
            )
    dura_b = status.get("durability")
    if dura_b:
        # graftdur: where the checkpoints land + how far the trail goes
        resumed = dura_b.get("resumed_from") or {}
        cursor = (dura_b.get("extra") or {}).get("scenario_cursor")
        lines.append(
            f"durability: {int(dura_b.get('checkpoints', 0))} "
            f"checkpoint(s) in {dura_b.get('directory', '?')}"
            + (
                f"  every={dura_b.get('every_cycles')}cyc"
                if dura_b.get("every_cycles") else ""
            )
            + (
                f" resumed@{resumed.get('cycle')}" if resumed else ""
            )
            + (f"  scenario_cursor={cursor}" if cursor else "")
        )
    rep_b = status.get("replication")
    if rep_b:
        # graftucs: k-resilience health — protocol counters plus the
        # computations still below the k-target (the actionable part)
        below = rep_b.get("below_target") or []
        lines.append(
            f"replication: mode={rep_b.get('mode', '?')} "
            f"k={rep_b.get('ktarget', '?')}  "
            f"visits={int(rep_b.get('visits', 0))}  "
            f"refusals={int(rep_b.get('refusals', 0))}  "
            f"retractions={int(rep_b.get('retractions', 0))}"
            + (f"  BELOW TARGET: {', '.join(below)}" if below else "")
        )
    device_cycles = _total(metrics, "solve.device_cycles")
    windows = _total(metrics, "solve.windows")
    if windows:
        lines.append(
            f"device: {int(device_cycles)} cycles over {int(windows)} "
            f"readback windows, "
            f"{int(_total(metrics, 'solve.readback_bytes'))} B read back"
        )
    mem_b = status.get("memory")
    if mem_b and (
        mem_b.get("limit_bytes") is not None
        or mem_b.get("bytes_in_use") is not None
        or (mem_b.get("guard") or {}).get("enabled")
    ):
        # graftmem: the live memory line — allocator gauges (degraded
        # backends show '-'), the model's prediction, guard + refusals
        guard = mem_b.get("guard") or {}
        headroom = mem_b.get("headroom_pct")
        predicted = _metric_values(metrics, "mem.predicted_bytes")
        pred = max(predicted.values()) if predicted else None
        lines.append(
            f"memory: in_use={_fmt_bytes(mem_b.get('bytes_in_use'))}  "
            f"peak={_fmt_bytes(mem_b.get('peak_bytes'))}  "
            f"limit={_fmt_bytes(mem_b.get('limit_bytes'))}  "
            f"headroom="
            + (f"{headroom:.1f}%" if headroom is not None else "-")
            + f"  predicted={_fmt_bytes(pred)}"
            + (
                f"  guard=on({guard.get('reserve_pct', 0):g}%)"
                if guard.get("enabled") else "  guard=off"
            )
            + (
                f"  refusals={int(mem_b['refusals_total'])}"
                if mem_b.get("refusals_total") else ""
            )
        )
    agents = status.get("agents") or {}
    sent = _metric_values(metrics, "comms.messages_sent")
    recv = _metric_values(metrics, "comms.messages_received")
    if agents or sent:
        lines.append("")
        lines.append(
            f"{'agent':<16} {'queue':>6} {'parked':>7} {'dead':>5} "
            f"{'sent':>8} {'recv':>8} {'msg/s':>8}"
        )
        names = sorted(
            set(agents)
            | {dict(k).get("agent", "?") for k in sent}
            | {dict(k).get("agent", "?") for k in recv}
        )
        for name in names:
            a = agents.get(name, {})
            key = (("agent", name),)
            rate = rates.get(name, {}).get("msg_s")
            lines.append(
                f"{name:<16} {a.get('queue', '-'):>6} "
                f"{a.get('parked', '-'):>7} {a.get('dead_letters', '-'):>5} "
                f"{int(sent.get(key, 0)):>8} {int(recv.get(key, 0)):>8} "
                f"{(f'{rate:.1f}' if rate is not None else '-'):>8}"
            )
    reliability = []
    for name in (
        "comms.send_failures", "comms.dead_letters", "comms.retry_attempts",
        "chaos.events", "telemetry.dispatch_errors",
    ):
        total = _total(metrics, name)
        if total:
            reliability.append(f"{name}={int(total)}")
    if reliability or status.get("dead_letters"):
        lines.append("")
        lines.append(
            "reliability: "
            + (" ".join(reliability) if reliability else "ok")
            + (
                f"  dead_letters={status['dead_letters']}"
                if status.get("dead_letters")
                else ""
            )
        )
    return "\n".join(lines)


def _render_fleet_frame(
    status: Dict[str, Any],
    rates: Dict[str, float],
) -> str:
    """The ``--fleet`` view: one row per worker + fleet totals + the
    fleet SLO lines (per-worker engines summarized by their alerts)."""
    lines = []
    fl = status.get("fleet") or {}
    lines.append(
        f"fleet: {status.get('workers_up', 0)}/"
        f"{status.get('workers_total', 0)} workers up  "
        f"solves={fl.get('solves', 0)}  "
        f"queue={fl.get('queue_depth', 0)}  "
        f"dead_letters={fl.get('dead_letters', 0)}  "
        f"solves/s={fl.get('solves_s', 0.0)}"
    )
    workers = status.get("workers") or {}
    if workers:
        lines.append("")
        lines.append(
            f"{'worker':<18} {'up':>4} {'age':>6} {'queue':>6} {'hwm':>5} "
            f"{'solves':>8} {'sol/s':>7} {'occ%':>5} {'mem':>9} "
            f"{'hdrm%':>6} {'pulse':<18} {'burn':>6} alert"
        )
        for name in sorted(workers):
            w = workers[name]
            age = w.get("age_s")
            rate = rates.get(name, w.get("solves_s"))
            burn = w.get("burn_fast")
            mem_h = w.get("mem_headroom_pct")
            lines.append(
                f"{name:<18} {('UP' if w.get('up') else 'DOWN'):>4} "
                f"{(f'{age:.1f}' if age is not None else '-'):>6} "
                f"{w.get('queue_depth', '-'):>6} "
                f"{w.get('queue_watermark', '-'):>5} "
                f"{w.get('solves', '-'):>8} "
                f"{(f'{rate:.1f}' if rate is not None else '-'):>7} "
                f"{w.get('occupancy_pct', '-'):>5} "
                f"{_fmt_bytes(w.get('mem_bytes_in_use')):>9} "
                f"{(f'{mem_h:.1f}' if mem_h is not None else '-'):>6} "
                f"{(w.get('pulse') or '-'):<18} "
                f"{(f'{burn:.2f}' if burn is not None else '-'):>6} "
                f"{w.get('alert', '')}"
                + (
                    f" mem_refused={w['mem_refusals']}"
                    if w.get("mem_refusals") else ""
                )
                + ("  STALE" if w.get("stale") else "")
            )
    slo_b = (status.get("slo") or {}).get("fleet")
    if slo_b:
        lines.append("")
        for name, ob in sorted((slo_b.get("objectives") or {}).items()):
            alert = ob.get("alert")
            worst = ob.get("worst_worker")
            lines.append(
                f"fleet slo: {name:<18} "
                f"budget={100.0 * ob.get('budget_remaining', 1.0):6.1f}%  "
                f"burn={ob.get('burn_fast', 0.0):6.2f}  "
                f"good/bad={int(ob.get('good', 0))}/{int(ob.get('bad', 0))}"
                + (f"  ALERT[{alert}] worst={worst}" if alert else "")
            )
    return "\n".join(lines)


def run_cmd(args, timeout: float = None) -> int:
    from ..telemetry.federate import clamped_rate

    # embedders call run_cmd with hand-built namespaces predating --fleet
    fleet = getattr(args, "fleet", None)
    base = fleet or args.url or f"http://{args.host}:{args.port}"
    base = base.rstrip("/")
    status_path = "/fleet/status" if fleet else "/status"
    deadline = (
        time.perf_counter() + args.duration if args.duration else None
    )
    if timeout is not None:
        t_cli = time.perf_counter() + timeout
        deadline = min(deadline, t_cli) if deadline else t_cli

    prev_sent: Dict[str, float] = {}
    prev_t: Optional[float] = None
    seen_ok = False
    frames = 0
    while True:
        status = _fetch_json(base, status_path)
        snapshot = _fetch_json(base, "/metrics.json")
        if status is None or snapshot is None:
            if args.once or not seen_ok:
                print(
                    f"error: no metrics surface at {base} — start the "
                    + ("fleet verb first" if fleet
                       else "solve with --metrics-port"),
                    file=sys.stderr,
                )
                return 1
            # the run (and its endpoint) ended between polls: that is the
            # normal way a watch of a finishing solve terminates
            print(f"\n{base} gone — run ended", file=sys.stderr)
            return 0
        seen_ok = True
        metrics = snapshot.get("metrics", {})

        now = time.perf_counter()
        # rates from counter deltas between OUR polls, clamped at 0 and
        # re-baselined when the scraped counter reset (worker restart) —
        # the same semantics the federated collector applies
        # (telemetry/federate.py:clamped_rate)
        rate_metric, rate_label = (
            ("fleet.worker_solves_total", "worker") if fleet
            else ("comms.messages_sent", "agent")
        )
        sent_now = {
            dict(k).get(rate_label, "?"): v
            for k, v in _metric_values(metrics, rate_metric).items()
        }
        rates: Dict[str, Any] = {}
        if prev_t is not None and now > prev_t:
            for name, v in sent_now.items():
                r = clamped_rate(prev_sent.get(name, 0.0), v, now - prev_t)
                rates[name] = {"msg_s": r} if not fleet else r
        prev_sent, prev_t = sent_now, now

        if args.as_json:
            write_output(args, {"status": status, "metrics": metrics})
        else:
            frame = (
                _render_fleet_frame(status, rates) if fleet
                else _render_frame(status, metrics, rates)
            )
            if frames and sys.stdout.isatty():
                # repaint in place; scrolling output otherwise
                print("\x1b[2J\x1b[H", end="")
            print(frame)
            sys.stdout.flush()
        frames += 1

        if args.once:
            return 0
        if status.get("status") in _TERMINAL:
            return 0
        if deadline is not None and time.perf_counter() >= deadline:
            return 0
        time.sleep(max(0.05, args.interval))
