"""``pydcop_tpu serve``: the many-tenant batched solve server.

No reference counterpart — the reference runs one problem per
orchestrator process; this verb is the graftserve front-end (ROADMAP
item 3): an HTTP surface where tenants POST DCOPs and a single device
solves a whole fleet of them behind shape-bucketed, vmapped executables
(pydcop_tpu/serve/).

Endpoints (all on ``--port``, next to the usual /metrics + /status):

- ``POST /solve``  body ``{"dcop_yaml": "...", "algo": "dsa",
  "params": {...}, "n_cycles": 100, "seed": 0, "tenant": "optional-id"}``
  -> ``{"tenant": id}``
- ``GET  /result/<tenant>`` -> status + cost/assignment once done
- ``GET  /status`` -> serve state, queue depth, per-tenant rows with
  anytime cost + graftpulse diagnosis
- ``POST /shutdown`` -> graceful drain, then the process exits

The server drains on SIGINT/SIGTERM too.  ``--fault-schedule`` composes
graftchaos: timed kills match tenant ids, a killed tenant dead-letters
without touching its co-batched neighbors (docs/serving.md).
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Any, Dict

from ._utils import (
    add_memguard_arguments,
    configure_memguard,
    write_output,
)

logger = logging.getLogger("pydcop_tpu.cli.serve")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "serve", help="serve many tenant solves behind batched executables"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "--port", type=int, default=9010,
        help="HTTP port for /solve, /result, /status, /metrics "
        "(default 9010; 0 = ephemeral, printed on stdout)",
    )
    parser.add_argument(
        "--host", default="127.0.0.1", help="bind address"
    )
    parser.add_argument(
        "--window-ms", type=float, default=25.0,
        help="micro-batching window: how long the first queued request "
        "waits for co-batchable tenants (default 25 ms)",
    )
    parser.add_argument(
        "--max-batch", type=int, default=32,
        help="max tenants per dispatched batch (default 32)",
    )
    parser.add_argument(
        "--batch-mode", choices=("vmap", "fused"), default="vmap",
        help="vmap (default): bit-exact per-tenant trajectories + "
        "shared warm executables per shape bucket; fused: tenants "
        "concatenate into one block-diagonal union solve — maximal "
        "throughput, trajectories not seed-reproducible solo "
        "(docs/serving.md)",
    )
    parser.add_argument(
        "--no-pulse", action="store_true",
        help="disable graftpulse per-tenant health rows (on by default: "
        "the /status surface is the point of a serve loop)",
    )
    parser.add_argument(
        "--fault-schedule", default=None, metavar="FILE",
        help="graftchaos YAML schedule: timed kills match tenant ids",
    )
    parser.add_argument(
        "--duration", type=float, default=None,
        help="serve for this many seconds, then drain and exit "
        "(default: until SIGINT/SIGTERM or POST /shutdown)",
    )
    parser.add_argument(
        "--checkpoint", nargs="?", const="", default=None, metavar="DIR",
        help="graftdur: a graceful drain writes a fleet checkpoint "
        "(tenant census + terminal results) into DIR (default "
        "$PYDCOP_TPU_STATE_DIR/checkpoints)",
    )
    parser.add_argument(
        "--slo", action="append", default=[], metavar="SPEC",
        help="graftslo objective (repeatable): p99<250ms, "
        "availability>=99.9%%, dead_letter_rate<=0.1%%, optionally "
        "NAME=... and ...@WINDOW (docs/observability.md).  Enables the "
        "burn-rate evaluator, the /slo endpoint, the /status slo block "
        "and alert postmortems",
    )
    parser.add_argument(
        "--slo-file", default=None, metavar="FILE",
        help="YAML file of objectives (+ fast_burn/slow_burn/"
        "eval_interval_s overrides); composes with --slo",
    )
    parser.add_argument(
        "--slo-interval", type=float, default=None, metavar="SECONDS",
        help="burn-rate evaluator tick interval (default 1 s)",
    )
    parser.add_argument(
        "--peer", action="append", default=[], metavar="URL",
        help="graftha: a fellow worker's base URL (repeatable) — "
        "handed to rejected clients in the structured 503 so they can "
        "fail over without guessing; sibling fleet manifests under the "
        "checkpoint directory's parent are discovered automatically",
    )
    add_memguard_arguments(parser)


def run_cmd(args, timeout: float = None) -> int:
    # the global -t timeout maps onto --duration (serve then drains
    # instead of being SIGALRM-killed mid-batch)
    if timeout and not args.duration:
        args.duration = max(1.0, timeout - 5.0)
    from ..serve import ServeServer
    from ..telemetry.metrics import metrics_registry
    from ..telemetry.pulse import pulse

    metrics_registry.enabled = True
    if configure_memguard(args):
        from ..telemetry.memplane import memguard

        logger.warning(
            "graftmem admission guard armed (reserve %.1f%%%s)",
            memguard.reserve_pct,
            f", limit override {memguard.limit_bytes} B"
            if memguard.limit_bytes else "",
        )
    if not args.no_pulse:
        pulse.reset()
        pulse.enabled = True
    schedule = None
    if args.fault_schedule:
        from ..chaos.schedule import load_fault_schedule

        schedule = load_fault_schedule(args.fault_schedule)
    checkpoint_dir = args.checkpoint
    if checkpoint_dir == "":
        from ..durability import default_checkpoint_dir

        checkpoint_dir = default_checkpoint_dir()
    engine = None
    if args.slo or args.slo_file:
        import os

        from ..telemetry.slo import SloEngine, load_slo_file, parse_objective

        objectives, options = (
            load_slo_file(args.slo_file) if args.slo_file else ([], {})
        )
        objectives += [parse_objective(s) for s in args.slo]
        if args.slo_interval is not None:
            options["eval_interval_s"] = args.slo_interval
        state = os.environ.get("PYDCOP_TPU_STATE_DIR") or ".bench_state"
        os.makedirs(state, exist_ok=True)
        engine = SloEngine(
            objectives,
            postmortem_path=os.path.join(state, "slo_postmortem.json"),
            **options,
        )
        for o in objectives:
            logger.warning("slo objective: %s = %s", o.name, o.describe())
    srv = ServeServer(
        port=args.port,
        host=args.host,
        window_ms=args.window_ms,
        max_batch=args.max_batch,
        fault_schedule=schedule,
        mode=args.batch_mode,
        checkpoint_dir=checkpoint_dir,
        slo=engine,
        peers=args.peer,
    )
    # ephemeral ports are useless unless announced; keep the line
    # machine-parseable for tools/serve_smoke.py
    print(f"SERVE_PORT={srv.http.port}", flush=True)
    logger.warning(
        "serving on http://%s:%s (window %.0f ms, max batch %d)",
        args.host, srv.http.port, args.window_ms, args.max_batch,
    )
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    deadline = (
        time.monotonic() + args.duration
        if args.duration is not None else None
    )
    # POST /shutdown drains the server itself; watch its state too
    while not stop.is_set():
        if deadline is not None and time.monotonic() >= deadline:
            break
        if srv.status()["state"] != "serving":
            break
        stop.wait(0.2)
    st_before = srv.status()
    drained = (
        srv.shutdown(drain=True)
        if st_before["state"] == "serving"
        else srv.wait_drained(120.0)
    )
    final = srv.status()
    payload: Dict[str, Any] = {
        "drained": bool(drained),
        "solves": final["solves"],
        "batches": final["batches"],
        "dead_letters": final["dead_letters"],
        "tenant_counts": final["tenant_counts"],
        "queue_ms": final["queue_ms"],
    }
    if srv.fleet_checkpoint_path:
        payload["fleet_checkpoint"] = srv.fleet_checkpoint_path
    if engine is not None:
        # the drain already ran the engine's final tick: the block is
        # the run's full SLO verdict (budget, alerts, phase percentiles)
        payload["slo"] = engine.bench_block()
        payload["slo"]["alert_transitions"] = engine.transitions
        payload["slo"]["postmortem"] = (
            engine.postmortem_path
            if engine.transitions else None
        )
    write_output(args, payload)
    if pulse.enabled:
        pulse.enabled = False
    metrics_registry.enabled = False
    return 0 if drained else 1
