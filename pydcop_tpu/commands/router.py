"""``pydcop_tpu router``: the graftha HA serve tier.

No reference counterpart — the reference replicates *computations*
inside one orchestrator (PAPER.md's replication + repair); this verb
replicates the serving layer itself: N ``pydcop_tpu serve`` workers
behind one :class:`~pydcop_tpu.serve.router.Router` that places tenants
by bucket affinity (``distribution/tpu_part``), sheds/defers on
fast-burn SLO alerts, and fails a chaos-killed worker's tenants over
onto survivors (docs/serving.md, "HA fleet").

Endpoints (on ``--port``, next to the federated /metrics + /status):

- ``POST /solve``  serve-compatible body plus an optional
  ``"priority": "high"|"normal"|"low"`` — 200 forwarded, 202 deferred,
  structured 503 (+ ``Retry-After`` + live peers) when shed;
- ``GET  /result/<tenant>``  router-cached terminal result, or a live
  proxy to the owning worker;
- ``GET  /status``, ``GET /fleet/status``  placement map, admission
  counters, structured event tail, per-worker fleet table;
- ``GET  /healthz``  router readiness; ``GET /slo`` / ``GET /fleet/slo``
  the router-local and fleet SLO reports;
- ``POST /shutdown``  graceful drain (flush deferred, wait for
  in-flight tenants, write the router ownership manifest).

Workers come from the same sources as ``fleet`` (positional
``NAME=URL``, ``--fleet-file``, ``--manifest``) or are SPAWNED:
``--spawn N`` starts N serve subprocesses checkpointing into
``--state-dir`` — each announced as a machine-parseable
``ROUTER_WORKER name=.. pid=.. port=..`` line so a chaos driver
(tools/fleet_soak.py) can SIGKILL one mid-run and restart it in place.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Any, Dict, List, Tuple

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.router")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "router",
        help="HA serve fleet: SLO-driven router over N workers (graftha)",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "targets", nargs="*", default=[], metavar="URL",
        help="worker endpoints: URL or NAME=URL (composes with "
        "--fleet-file / --manifest / --spawn)",
    )
    parser.add_argument(
        "--fleet-file", default=None, metavar="FILE",
        help="YAML fleet file with a workers: section (name -> url)",
    )
    parser.add_argument(
        "--manifest", default=None, metavar="PATH",
        help="graftdur fleet-manifest.json (or a directory searched for "
        "them): adopt workers from their recorded endpoints",
    )
    parser.add_argument(
        "--spawn", type=int, default=0, metavar="N",
        help="spawn N serve worker subprocesses (each checkpointing "
        "into --state-dir/wI, announced as ROUTER_WORKER lines)",
    )
    parser.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="shared state directory: spawned workers checkpoint into "
        "DIR/wI, the router reads victim manifests from it on failover "
        "and writes its own router-manifest.json there "
        "(default $PYDCOP_TPU_STATE_DIR or .bench_state)",
    )
    parser.add_argument(
        "--port", type=int, default=9030,
        help="HTTP port of the router surface (default 9030; 0 = "
        "ephemeral, printed on stdout)",
    )
    parser.add_argument(
        "--host", default="127.0.0.1", help="bind address"
    )
    parser.add_argument(
        "--placement", choices=("affinity", "round_robin"),
        default="affinity",
        help="tenant placement: affinity (default) lays shape buckets "
        "onto workers via distribution/tpu_part so warm executables are "
        "shared; round_robin sprays (the A/B baseline)",
    )
    parser.add_argument(
        "--interval", type=float, default=0.5,
        help="control-loop tick + worker scrape interval (default 0.5s)",
    )
    parser.add_argument(
        "--stale-after", type=float, default=10.0,
        help="drop a dead worker's series after this many seconds "
        "without a successful scrape (default 10)",
    )
    parser.add_argument(
        "--slo", action="append", default=[], metavar="SPEC",
        help="fleet SLO objective (repeatable, serve --slo grammar), "
        "evaluated over the workers' federated slo.events; fast-burn "
        "alerts gate admission",
    )
    parser.add_argument(
        "--slo-file", default=None, metavar="FILE",
        help="YAML file of fleet objectives; composes with --slo",
    )
    parser.add_argument(
        "--router-slo", action="append", default=[], metavar="SPEC",
        help="router-local objective (repeatable, same grammar) "
        "classified over FORWARD outcomes — the burn signal a worker "
        "kill produces even when the dead worker can no longer report; "
        "fast-burn alerts gate admission too",
    )
    parser.add_argument(
        "--worker-slo", action="append", default=[], metavar="SPEC",
        help="objective handed to every SPAWNED worker's --slo",
    )
    parser.add_argument(
        "--retry-attempts", type=int, default=3,
        help="forward RetryPolicy attempts per worker (default 3)",
    )
    parser.add_argument(
        "--tenant-deadline", type=float, default=120.0,
        help="per-tenant deadline in seconds: retries, deferrals and "
        "failover must finish inside it (default 120)",
    )
    parser.add_argument(
        "--defer-max", type=float, default=15.0,
        help="longest a normal-priority tenant stays deferred under "
        "sustained burn before being released anyway (default 15s)",
    )
    parser.add_argument(
        "--window-ms", type=float, default=25.0,
        help="workers' base micro-batch window; the router widens it "
        "up to --window-max-factor x when queues idle (default 25)",
    )
    parser.add_argument(
        "--window-max-factor", type=float, default=4.0,
        help="idle-widening cap on the batch window (default 4x)",
    )
    parser.add_argument(
        "--duration", type=float, default=None,
        help="route for this many seconds, then drain and exit "
        "(default: until SIGINT/SIGTERM or POST /shutdown)",
    )


def _spawn_workers(
    args, state_dir: str
) -> Tuple[List[Any], List[Tuple[str, str]]]:
    """Start ``--spawn`` serve subprocesses; returns (procs, targets).
    Each worker checkpoints into ``state_dir/wI`` (the manifests the
    router adopts terminal results from on failover) and is announced
    as a ``ROUTER_WORKER name=.. pid=.. port=..`` line."""
    import subprocess
    import sys

    procs: List[Any] = []
    targets: List[Tuple[str, str]] = []
    for i in range(args.spawn):
        name = f"w{i}"
        ckpt = os.path.join(state_dir, name)
        cmd = [
            sys.executable, "-m", "pydcop_tpu", "serve",
            "--port", "0", "--host", args.host,
            "--window-ms", str(args.window_ms),
            "--checkpoint", ckpt,
        ]
        for spec in args.worker_slo:
            cmd += ["--slo", spec]
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        port = None
        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line and proc.poll() is not None:
                break
            if line.startswith("SERVE_PORT="):
                port = int(line.strip().split("=", 1)[1])
                break
        if port is None:
            proc.kill()
            raise RuntimeError(f"spawned worker {name} never announced")
        # keep the pipe drained so the worker's final report never blocks
        threading.Thread(
            target=lambda p=proc: [None for _ in p.stdout],
            daemon=True,
        ).start()
        url = f"http://{args.host}:{port}"
        print(
            f"ROUTER_WORKER name={name} pid={proc.pid} port={port}",
            flush=True,
        )
        procs.append(proc)
        targets.append((name, url))
    return procs, targets


def run_cmd(args, timeout: float = None) -> int:
    import sys

    if timeout and not args.duration:
        args.duration = max(1.0, timeout - 5.0)
    from ..infrastructure.retry import RetryPolicy
    from ..telemetry.federate import (
        FleetTarget,
        targets_from_args,
        targets_from_fleet_file,
        targets_from_manifest,
    )
    from ..telemetry.metrics import metrics_registry
    from ..telemetry.slo import load_slo_file, parse_objective

    metrics_registry.enabled = True
    state_dir = args.state_dir or (
        os.environ.get("PYDCOP_TPU_STATE_DIR") or ".bench_state"
    )
    procs: List[Any] = []
    try:
        targets = list(targets_from_args(args.targets))
        if args.fleet_file:
            targets += targets_from_fleet_file(args.fleet_file)
        if args.manifest:
            targets += targets_from_manifest(args.manifest)
        if args.spawn:
            os.makedirs(state_dir, exist_ok=True)
            procs, spawned = _spawn_workers(args, state_dir)
            targets += [FleetTarget(n, u) for n, u in spawned]
        if not targets:
            print(
                "error: no workers — give worker URLs, --fleet-file, "
                "--manifest or --spawn N", file=sys.stderr,
            )
            return 2
        objectives, options = (
            load_slo_file(args.slo_file) if args.slo_file else ([], {})
        )
        objectives += [parse_objective(s) for s in args.slo]
        options.pop("eval_interval_s", None)  # ticks ride the loop
        router_objectives = [
            parse_objective(s) for s in args.router_slo
        ]
        from ..serve.router import Router

        router = Router(
            targets,
            port=args.port,
            host=args.host,
            placement=args.placement,
            interval_s=args.interval,
            stale_after_s=args.stale_after,
            objectives=objectives,
            router_objectives=router_objectives,
            retry=RetryPolicy(
                max_attempts=max(1, args.retry_attempts),
                base_delay=0.05, max_delay=0.5, jitter="full",
            ),
            tenant_deadline_s=args.tenant_deadline,
            defer_max_s=args.defer_max,
            window_base_ms=args.window_ms,
            window_max_factor=args.window_max_factor,
            state_dir=state_dir,
            **options,
        )
    except (OSError, RuntimeError, ValueError) as e:
        for proc in procs:
            proc.kill()
        print(f"error: {e}", file=sys.stderr)
        return 2

    for o in objectives:
        logger.warning("fleet slo objective: %s = %s", o.name, o.describe())
    for o in router_objectives:
        logger.warning(
            "router slo objective: %s = %s", o.name, o.describe()
        )
    # machine-parseable like serve's SERVE_PORT= (tools/fleet_soak.py)
    print(f"ROUTER_PORT={router.http.port}", flush=True)
    logger.warning(
        "router on http://%s:%s (%d worker(s), %s placement)",
        args.host, router.http.port, len(targets), args.placement,
    )
    router.start()
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    deadline = (
        time.monotonic() + args.duration
        if args.duration is not None else None
    )
    while not stop.is_set():
        if deadline is not None and time.monotonic() >= deadline:
            break
        if router.status()["state"] != "serving":
            break  # POST /shutdown drains the router itself
        stop.wait(0.2)
    st_before = router.status()
    drained = (
        router.shutdown(drain=True)
        if st_before["state"] == "serving"
        else st_before["state"] == "drained"
    )
    # drain spawned workers AFTER the router: in-flight tenants finish
    import urllib.request

    for t, proc in zip(targets[-len(procs):] if procs else [], procs):
        try:
            req = urllib.request.Request(
                t.url + "/shutdown", data=b"{}", method="POST"
            )
            urllib.request.urlopen(req, timeout=10).read()
        except OSError:
            pass
    for proc in procs:
        try:
            proc.wait(timeout=120)
        except Exception:  # noqa: BLE001 — a stuck worker is killed
            proc.kill()
    final = router.status()
    payload: Dict[str, Any] = {
        "drained": bool(drained),
        "state": final["state"],
        "placement": final["placement"],
        "admission": final["admission"],
        "tenant_counts": final["tenant_counts"],
        "workers_up": final["workers_up"],
        "workers_total": final["workers_total"],
        "fleet": final["fleet"],
        "events": final["events"],
    }
    if "slo" in final:
        payload["slo"] = final["slo"]
    if "router_slo" in final:
        payload["router_slo"] = final["router_slo"]
        if router.engine is not None:
            payload["router_slo_transitions"] = router.engine.transitions
    write_output(args, payload)
    metrics_registry.enabled = False
    return 0 if drained else 1
