"""``pydcop consolidate``: aggregate campaign data into CSV tables.

Role parity with /root/reference/pydcop/commands/consolidate.py (run_cmd:129):
three modes —

* default: one CSV row per result JSON file, columns = union of scalar
  metric fields (a generalization of the reference's fixed-column extract);
* ``--solution`` (reference :135): the reference's exact solution-metrics
  columns, appended to ``--csv_output`` so repeated invocations build one
  table across a campaign (``--replace_output`` starts it over);
* ``--distribution_cost GLOB --algo ALGO`` (reference :149): cost /
  hosting / communication of each distribution file against the given DCOP
  under the named algorithm's footprint model.
"""

from __future__ import annotations

import csv
import glob
import json
import os
import sys
from typing import Any, Dict, List

SOLUTION_COLUMNS = ["time", "cost", "cycle", "msg_count", "msg_size", "status"]


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "consolidate", help="aggregate result files to csv"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "result_files", nargs="+",
        help="result json files (globs accepted); with "
        "--distribution_cost, the dcop yaml file(s)",
    )
    parser.add_argument(
        "-o", "--csv_output", default=None, help="csv file (default stdout)"
    )
    parser.add_argument(
        "--solution", action="store_true",
        help="extract the end-solution metric columns "
        f"({', '.join(SOLUTION_COLUMNS)}), appending to --csv_output",
    )
    parser.add_argument(
        "--replace_output", action="store_true",
        help="with --solution: restart --csv_output instead of appending",
    )
    parser.add_argument(
        "--distribution_cost", default=None, metavar="GLOB",
        help="distribution yaml file(s): report each one's "
        "cost/hosting/communication against the dcop",
    )
    parser.add_argument(
        "--algo", default=None,
        help="algorithm whose footprint/load model prices the "
        "distributions (required with --distribution_cost)",
    )


def run_cmd(args, timeout=None) -> int:
    if args.distribution_cost:
        return _distribution_costs_cmd(args)
    if args.solution:
        return _solution_cmd(args)
    return _table_cmd(args)


def _open_output(args, columns: List[str], append: bool):
    """(file object, writer, close?) honoring append/replace semantics."""
    if not args.csv_output:
        w = csv.writer(sys.stdout)
        w.writerow(columns)
        return sys.stdout, w, False
    if args.replace_output and os.path.exists(args.csv_output):
        os.remove(args.csv_output)
    fresh = not (append and os.path.exists(args.csv_output))
    f = open(
        args.csv_output, "a" if append else "w",
        newline="", encoding="utf-8",
    )
    w = csv.writer(f)
    if fresh:
        w.writerow(columns)
    return f, w, True


def _expand_patterns(patterns) -> List[str]:
    """Expand globs, warning once per pattern that matches nothing — the
    same handling in --solution and table modes (a typo'd glob used to
    yield a per-file 'skipping' error in one and silence in the other,
    ADVICE round 4)."""
    files: List[str] = []
    for pattern in patterns:
        matched = sorted(glob.glob(pattern))
        if not matched and os.path.exists(pattern):
            # a literal filename containing glob metacharacters
            # (e.g. 'res[1].json') must still be consumed
            matched = [pattern]
        if not matched:
            print(f"no files match {pattern!r}", file=sys.stderr)
        files.extend(matched)
    return files


def _solution_cmd(args) -> int:
    files = _expand_patterns(args.result_files)
    f, w, close = _open_output(args, SOLUTION_COLUMNS, append=True)
    try:
        for path in files:
            try:
                with open(path, encoding="utf-8") as fh:
                    data = json.load(fh)
                w.writerow([data.get(k) for k in SOLUTION_COLUMNS])
            except (OSError, json.JSONDecodeError) as e:
                print(f"skipping {path}: {e}", file=sys.stderr)
    finally:
        if close:
            f.close()
    return 0


def _distribution_costs_cmd(args) -> int:
    from ..dcop.yamldcop import load_dcop_from_file
    from ..distribution.yamlformat import load_dist_from_file
    from ._utils import load_distribution_module, load_graph_module

    if not args.algo:
        print("--distribution_cost requires --algo", file=sys.stderr)
        return 2
    from ..algorithms import load_algorithm_module

    algo_module = load_algorithm_module(args.algo)
    graph_module = load_graph_module(args.algo)
    dist_module = load_distribution_module("ilp_compref")
    dcop = load_dcop_from_file(args.result_files)
    cg = graph_module.build_computation_graph(dcop)

    dist_files = _expand_patterns(
        [os.path.expanduser(args.distribution_cost)]
    )
    columns = ["dcop", "distribution", "cost", "hosting", "communication"]
    f, w, close = _open_output(args, columns, append=True)
    try:
        for dist_file in dist_files:
            try:
                distribution = load_dist_from_file(dist_file)
                cost, comm, hosting = dist_module.distribution_cost(
                    distribution,
                    cg,
                    dcop.agents.values(),
                    computation_memory=algo_module.computation_memory,
                    communication_load=algo_module.communication_load,
                )
                w.writerow(
                    [args.result_files[0], dist_file, cost, hosting, comm]
                )
            except Exception as e:  # noqa: BLE001 — reference skips bad files
                print(f"skipping {dist_file}: {e}", file=sys.stderr)
    finally:
        if close:
            f.close()
    return 0


def _table_cmd(args) -> int:
    files = _expand_patterns(args.result_files)
    rows: List[Dict[str, Any]] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        row: Dict[str, Any] = {"file": path}
        for k, v in data.items():
            if isinstance(v, (int, float, str, bool)) or v is None:
                row[k] = v
        rows.append(row)
    if not rows:
        print("no results found", file=sys.stderr)
        return 1
    columns = ["file"] + sorted(
        {k for r in rows for k in r} - {"file"}
    )
    out = (
        open(args.csv_output, "w", newline="", encoding="utf-8")
        if args.csv_output
        else sys.stdout
    )
    try:
        w = csv.DictWriter(out, fieldnames=columns)
        w.writeheader()
        for r in rows:
            w.writerow(r)
    finally:
        if args.csv_output:
            out.close()
    return 0
