"""``pydcop consolidate``: aggregate result files into one CSV.

Role parity with /root/reference/pydcop/commands/consolidate.py: collect the
JSON result files of a batch campaign into a single CSV table (one row per
result file, columns = union of scalar metric fields).
"""

from __future__ import annotations

import csv
import glob
import json
import sys
from typing import Any, Dict, List


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "consolidate", help="aggregate result files to csv"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "result_files", nargs="+",
        help="result json files (globs accepted)",
    )
    parser.add_argument(
        "-o", "--csv_output", default=None, help="csv file (default stdout)"
    )


def run_cmd(args, timeout=None) -> int:
    files: List[str] = []
    for pattern in args.result_files:
        files.extend(sorted(glob.glob(pattern)))
    rows: List[Dict[str, Any]] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        row: Dict[str, Any] = {"file": path}
        for k, v in data.items():
            if isinstance(v, (int, float, str, bool)) or v is None:
                row[k] = v
        rows.append(row)
    if not rows:
        print("no results found", file=sys.stderr)
        return 1
    columns = ["file"] + sorted(
        {k for r in rows for k in r} - {"file"}
    )
    out = (
        open(args.csv_output, "w", newline="", encoding="utf-8")
        if args.csv_output
        else sys.stdout
    )
    try:
        w = csv.DictWriter(out, fieldnames=columns)
        w.writeheader()
        for r in rows:
            w.writerow(r)
    finally:
        if args.csv_output:
            out.close()
    return 0
