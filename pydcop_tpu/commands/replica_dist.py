"""``pydcop replica_dist``: offline replica placement.

Role parity with /root/reference/pydcop/commands/replica_dist.py: compute the
k-resilient replica placement for a DCOP + algorithm + distribution, using
the UCS cost model (route + hosting costs), and print {computation: [hosts]}.
"""

from __future__ import annotations

from typing import Any, Dict

from ..dcop.yamldcop import load_dcop_from_file
from ..replication import ucs_replica_hosts
from ._utils import (
    build_algo_def,
    load_distribution_module,
    load_graph_module,
    write_output,
)


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "replica_dist", help="compute replica placement (k-resilience)"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument("-k", "--ktarget", type=int, required=True)
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-d", "--distribution", default="oneagent")


def run_cmd(args, timeout=None) -> int:
    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(args.algo, None, mode=dcop.objective)
    graph_module = load_graph_module(algo_def.algo)
    cg = graph_module.build_computation_graph(dcop)
    from ..algorithms import load_algorithm_module

    algo_module = load_algorithm_module(algo_def.algo)
    dist_module = load_distribution_module(args.distribution)
    distribution = dist_module.distribute(
        cg,
        list(dcop.agents.values()),
        hints=getattr(dcop, "dist_hints", None),
        computation_memory=getattr(algo_module, "computation_memory", None),
        communication_load=getattr(
            algo_module, "communication_load", None
        ),
    )

    agent_defs = {a.name: a for a in dcop.agents.values()}
    agent_names = sorted(agent_defs)

    placement: Dict[str, Any] = {}
    for comp in distribution.computations:
        owner = distribution.agent_for(comp)

        def route_cost(a: str, b: str) -> float:
            return float(agent_defs[a].route(b))

        def hosting_cost(a: str, c: str = comp) -> float:
            return float(agent_defs[a].hosting_cost(c))

        placement[comp] = ucs_replica_hosts(
            owner, comp, args.ktarget, agent_names, route_cost,
            hosting_cost,
        )
    write_output(
        args, {"replica_dist": placement, "ktarget": args.ktarget}
    )
    return 0
