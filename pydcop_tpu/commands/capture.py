"""``pydcop_tpu capture``: graftcap — deterministic perf-capture bundles
and the per-op regression diff.

No reference counterpart.  Two modes behind one verb (the ``telemetry
stitch`` sentinel idiom):

- ``pydcop_tpu capture -o captures/tpu_r06`` runs the selected bench
  configs with EVERYTHING forced on — graftprof profiling, HLO dumps,
  kernelprof per-op attribution, the jit/readback census — and writes a
  self-describing bundle directory (manifest with device / backend /
  commit / clock provenance + the static dispatch-site census from
  tools/perf_budget.json, one record JSON per config, HLO dumps,
  profiler traces).  The next healthy TPU window is ONE command and
  nothing is forgotten or mis-ordered.
- ``pydcop_tpu capture diff A B`` attributes the wall delta between two
  comparands (bundle dir / BENCH_*.json file / BENCH history glob ->
  trajectory median) per-op and per-phase, with census, recompile and
  roofline flags (telemetry/perfdiff.py).  Host-only: never touches a
  device backend, so dcop_cli skips the accelerator probe for it.

Exit codes: capture -> 1 when any config errored or a KERNEL_CONFIGS
record lost its attribution block; diff -> 1 when significant deltas
exist, 2 when a comparand cannot be loaded.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Dict, List

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.capture")

#: repo root (bench_all.py lives there, outside the package)
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "capture",
        help="graftcap: one-command perf-capture bundle, or "
        "`capture diff A B` per-op regression attribution",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "spec", nargs="*", default=[],
        help="`diff BASE FRESH` compares two comparands (bundle dir, "
        "BENCH_*.json file, or a quoted BENCH-history glob -> "
        "trajectory median); empty runs a capture",
    )
    parser.add_argument(
        "-o", "--out-dir", default=None, metavar="DIR",
        help="capture mode: bundle output directory (required)",
    )
    parser.add_argument(
        "--configs", nargs="+", default=None, metavar="N",
        help="capture mode: bench_all config numbers "
        "(default: the bench_all DEFAULT_CONFIGS set)",
    )
    parser.add_argument(
        "--force", action="store_true",
        help="capture mode: write into a directory that already "
        "contains a bundle",
    )
    parser.add_argument(
        "--notes", default=None,
        help="capture mode: free-text note stored in the manifest",
    )
    parser.add_argument(
        "--no-profiler", action="store_true",
        help="capture mode: skip the jax.profiler trace session (HLO "
        "dumps + census stay on; traces are large and CPU smoke runs "
        "do not need them)",
    )
    parser.add_argument(
        "--json", default=None, metavar="FILE", dest="diff_json",
        help="diff mode: also write the machine-readable diff JSON",
    )
    parser.add_argument(
        "--all-metrics", action="store_true",
        help="diff mode: expand the per-op table for every metric, "
        "not just the significant ones",
    )
    parser.add_argument(
        "--device", default=None,
        help="diff mode: pin the device a trajectory-median comparand "
        "selects records for (default: majority device)",
    )


def is_diff_invocation(args) -> bool:
    """True for ``capture diff ...`` — host-only, so the CLI's
    accelerator auto-probe must not run for it."""
    spec = getattr(args, "spec", None) or []
    return bool(spec) and spec[0] == "diff"


def run_cmd(args, timeout: float = None) -> int:
    if is_diff_invocation(args):
        return _diff_cmd(args)
    if args.spec:
        logger.error(
            "unknown capture subcommand %r (only `diff` takes "
            "positionals; a capture is `capture -o DIR`)", args.spec[0]
        )
        return 2
    return _capture_cmd(args)


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------


def _budget_block() -> Dict:
    """Static dispatch/readback-site census + check at capture time, so
    bundle-vs-bundle diffs can flag *site-count* drift (graftperf)."""
    from ..analysis.budget import check_budget, load_manifest, static_census

    try:
        manifest = load_manifest()
        census = static_census(manifest, root=_REPO_ROOT)
        return {
            "census": census,
            "problems": check_budget(manifest, census, root=_REPO_ROOT),
        }
    except Exception as exc:  # noqa: BLE001 - provenance, not a gate
        return {"error": f"{type(exc).__name__}: {exc}"[:200]}


def _degraded_reasons() -> List[str]:
    """Label values of kernelprof.degraded accumulated by the config
    that just ran (bench_all resets the registry per config)."""
    from ..telemetry import metrics_registry

    metric = metrics_registry.get("kernelprof.degraded")
    if metric is None:
        return []
    reasons = []
    for entry in metric.snapshot().get("values", []):
        labels = dict(entry.get("labels") or {})
        if entry.get("value"):
            reasons.append(str(labels.get("reason", "unknown")))
    return reasons


def _capture_cmd(args) -> int:
    if not args.out_dir:
        logger.error("capture needs -o/--out-dir BUNDLE_DIR")
        return 2
    out = args.out_dir
    if os.path.exists(os.path.join(out, "manifest.json")) and not args.force:
        logger.error(
            "%s already holds a capture bundle (use --force to overwrite)",
            out,
        )
        return 2
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)
    import bench_all

    from ..telemetry import perfdiff
    from ..telemetry.profiling import start_profiling, stop_profiling

    wanted = [str(c) for c in (args.configs or bench_all.DEFAULT_CONFIGS)]
    unknown = [c for c in wanted if c not in bench_all.CONFIGS]
    if unknown:
        logger.error(
            "unknown config(s) %s (have: %s)",
            ",".join(unknown), ",".join(sorted(bench_all.CONFIGS)),
        )
        return 2

    import jax

    device = str(jax.devices()[0].platform)
    env = perfdiff.capture_environment(extra={
        "device": device,
        "device_count": len(jax.devices()),
        "backend": getattr(jax.devices()[0], "device_kind", None),
        "jax": jax.__version__,
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "state_dir": os.environ.get("PYDCOP_TPU_STATE_DIR"),
    })
    manifest = perfdiff.new_manifest(
        environment=env,
        created=time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        notes=args.notes,
    )
    manifest_path = os.path.join(out, "manifest.json")
    if os.path.exists(manifest_path):
        # --force resumes into an existing bundle (an interrupted TPU
        # window re-runs the missing configs): keep what was captured,
        # refresh provenance
        try:
            with open(manifest_path) as fh:
                prior = json.load(fh)
            manifest["configs"] = prior.get("configs", {})
            manifest["warnings"] = prior.get("warnings", [])
        except (OSError, ValueError):
            pass
    manifest["budget"] = _budget_block()
    perfdiff.write_manifest(out, manifest)
    logger.warning(
        "capture -> %s (device=%s, configs=%s)", out, device,
        ",".join(wanted),
    )

    failures = 0
    for key in wanted:
        hlo_dir = os.path.join(out, "hlo", f"config_{key}")
        profile_dir = (
            None if args.no_profiler
            else os.path.join(out, "profile", f"config_{key}")
        )
        os.makedirs(hlo_dir, exist_ok=True)
        start_profiling(profile_dir=profile_dir, hlo_dir=hlo_dir)
        try:
            record = bench_all.run_config(key)
        finally:
            stop_profiling()
        warnings = []
        if record.get("error"):
            failures += 1
            warnings.append(f"config {key}: ERRORED: {record['error']}")
        state = perfdiff.attribution_state(record)
        degraded = _degraded_reasons()
        if key in bench_all.KERNEL_CONFIGS and state != "ok":
            # the loud warning the satellite demands: a capture window
            # must never be silently under-instrumented
            failures += 1
            warnings.append(
                f"config {key} ({record.get('metric')}): per-op "
                f"attribution MISSING ({state}"
                + (f"; degraded: {','.join(degraded)}" if degraded else "")
                + ") — this bundle cannot explain a regression per-op"
            )
        perfdiff.append_record(out, record, manifest, warnings=warnings)
        for w in warnings:
            logger.error("capture: %s", w)
        logger.warning(
            "capture: config %s %s = %s %s (attribution: %s)",
            key, record.get("metric"), record.get("value"),
            record.get("unit", ""), state,
        )
    payload = {
        "bundle": out,
        "device": device,
        "configs": manifest["configs"],
        "warnings": manifest["warnings"],
        "budget_problems": (manifest["budget"] or {}).get("problems"),
    }
    write_output(args, payload)
    return 1 if failures else 0


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------


def _diff_cmd(args) -> int:
    from ..telemetry import perfdiff

    spec = args.spec[1:]
    if len(spec) != 2:
        logger.error("usage: pydcop_tpu capture diff BASE FRESH")
        return 2
    try:
        base = perfdiff.load_side(spec[0], device=args.device)
        fresh = perfdiff.load_side(spec[1], device=args.device)
    except (OSError, ValueError) as exc:
        logger.error("capture diff: %s", exc)
        return 2
    diff = perfdiff.diff_sides(base, fresh)
    print(perfdiff.format_diff(diff, all_metrics=args.all_metrics))
    if args.diff_json:
        with open(args.diff_json, "w") as fh:
            json.dump(diff, fh, indent=2, sort_keys=True)
            fh.write("\n")
        logger.warning("diff json -> %s", args.diff_json)
    return 1 if (diff["significant"] or diff["flags"]) else 0
