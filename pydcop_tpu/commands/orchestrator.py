"""``pydcop orchestrator``: standalone orchestrator for multi-machine runs.

Role parity with /root/reference/pydcop/commands/orchestrator.py: load a DCOP
(+ optional scenario), start an HTTP orchestrator, wait for remote agents
(started with ``pydcop agent``) to register, deploy, run, print the result
JSON and stop everyone.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from ..dcop.yamldcop import load_dcop_from_file, load_scenario_from_file
from ._utils import build_algo_def, write_output

logger = logging.getLogger("pydcop_tpu.cli.orchestrator")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "orchestrator", help="start a standalone orchestrator over HTTP"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument(
        "-p", "--algo_params", action="append", default=None
    )
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("-s", "--scenario", default=None)
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--address", default="0.0.0.0")
    parser.add_argument("-k", "--ktarget", type=int, default=None)
    parser.add_argument(
        "--replication-mode", choices=["distributed", "local"],
        default="distributed",
        help="replica placement: the graftucs negotiation protocol "
        "(distributed, default) or the centralized UCS oracle (local) — "
        "docs/resilience.md",
    )
    parser.add_argument("-n", "--n_cycles", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--register_timeout", type=float, default=120,
        help="how long to wait for agents to register",
    )


def run_cmd(args, timeout=None) -> int:
    import importlib

    from ..algorithms import load_algorithm_module
    from ..infrastructure.communication import HttpCommunicationLayer
    from ..infrastructure.orchestrator import Orchestrator

    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(
        args.algo, args.algo_params, mode=dcop.objective
    )
    algo_module = load_algorithm_module(algo_def.algo)
    graph_module = importlib.import_module(
        f"pydcop_tpu.computations_graph.{algo_module.GRAPH_TYPE}"
    )
    cg = graph_module.build_computation_graph(dcop)
    dist_module = importlib.import_module(
        f"pydcop_tpu.distribution.{args.distribution}"
    )
    distribution = dist_module.distribute(
        cg,
        list(dcop.agents.values()),
        hints=getattr(dcop, "dist_hints", None),
        computation_memory=getattr(algo_module, "computation_memory", None),
        communication_load=getattr(
            algo_module, "communication_load", None
        ),
    )
    scenario = (
        load_scenario_from_file(args.scenario) if args.scenario else None
    )

    comm = HttpCommunicationLayer((args.address, args.port))
    orchestrator = Orchestrator(
        algo_def,
        cg,
        list(dcop.agents.values()),
        dcop,
        distribution=distribution,
        comm=comm,
        n_cycles=args.n_cycles,
        seed=args.seed,
        replication_mode=args.replication_mode,
    )
    orchestrator.start()
    logger.info(
        "orchestrator on %s:%s, waiting for %d agents",
        args.address, args.port, len(dcop.agents),
    )
    try:
        orchestrator.deploy_computations(timeout=args.register_timeout)
        if args.ktarget:
            orchestrator.start_replication(args.ktarget)
        orchestrator.run(scenario=scenario, timeout=timeout)
        result: Dict[str, Any] = orchestrator.end_metrics()
        write_output(args, result)
        return 0 if result.get("status") in ("FINISHED", "TIMEOUT") else 1
    finally:
        try:
            orchestrator.stop_agents(timeout=10)
        finally:
            orchestrator.stop()
