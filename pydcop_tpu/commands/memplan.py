"""``pydcop_tpu memplan``: device-free HBM capacity planning.

No reference counterpart — the graftmem front-end (docs/observability.md).
The analytic model in ``telemetry/memplane.py`` predicts the per-device
bytes a solve holds (DeviceDCOP/ELL pytree, message planes, scan carries,
workspace), so the capacity questions ROADMAP items 1–2 keep asking get
answered from headline numbers alone, no accelerator required:

- ``memplan --algo maxsum --n-vars 100000 --domain 3 --degree 4
  --device v5e`` — the per-component byte breakdown and a FITS/REFUSE
  verdict against that generation's HBM minus the reserve;
- ``memplan problem.yaml -a mgm2`` — same, from the exact compiled
  shape of a real problem file;
- ``--max-vars`` — largest n_vars per device for the algo at this
  domain/degree; ``--max-batch-k`` — largest serve micro-batch K whose
  bucket still fits.

Host-only: never touches a device backend (the model is arithmetic over
shape metadata; ``--device`` reads the per-generation table that also
feeds ``kernelprof.hbm_peak_gbps``).
"""

from __future__ import annotations

import logging
import sys

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.memplan")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "memplan",
        help="graftmem: predict device memory for a solve, plan capacity",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "dcop_files", nargs="*", default=[],
        help="dcop yaml file(s): compile for the exact problem shape "
        "(omit to describe the shape with --n-vars/--domain/--degree)",
    )
    parser.add_argument(
        "-a", "--algo", default="maxsum", help="algorithm name"
    )
    parser.add_argument(
        "-p", "--algo_params", action="append", default=None,
        help="algorithm parameter as name:value (repeatable); "
        "layout:ell forces the ELL maxsum path",
    )
    parser.add_argument(
        "--n-vars", type=int, default=None,
        help="synthetic shape: number of variables",
    )
    parser.add_argument(
        "--domain", type=int, default=None,
        help="synthetic shape: domain size D",
    )
    parser.add_argument(
        "--degree", type=float, default=4.0,
        help="synthetic shape: mean constraint degree (default 4)",
    )
    parser.add_argument(
        "--float-bytes", type=int, default=4, choices=(2, 4, 8),
        help="bytes per table/message element (default 4 = float32)",
    )
    parser.add_argument(
        "--mesh", type=int, default=1,
        help="devices the problem plane shards across (default 1)",
    )
    parser.add_argument(
        "--batch-k", type=int, default=1,
        help="serve micro-batch size sharing one executable (default 1)",
    )
    parser.add_argument(
        "--n-cycles", type=int, default=64,
        help="cycles (sizes the pulse/curve carries; default 64)",
    )
    parser.add_argument(
        "--device", default=None, metavar="KIND",
        help="TPU generation to budget against (v2..v6e — the same "
        "table kernelprof reads); default: no limit, breakdown only",
    )
    parser.add_argument(
        "--limit-bytes", type=int, default=None,
        help="explicit per-device byte limit (overrides --device)",
    )
    parser.add_argument(
        "--reserve-pct", type=float, default=10.0,
        help="fraction of the limit kept free for XLA workspace "
        "(default 10)",
    )
    parser.add_argument(
        "--serve-bucket", action="store_true",
        help="budget the pow2 serve bucket this shape lands in, not "
        "the exact shape (what the serve admission guard charges)",
    )
    parser.add_argument(
        "--max-vars", action="store_true",
        help="answer: largest n_vars per device for this algo at "
        "--domain/--degree under the limit (needs --device or "
        "--limit-bytes)",
    )
    parser.add_argument(
        "--max-batch-k", action="store_true",
        help="answer: largest serve batch K of this shape's bucket "
        "under the limit (needs --device or --limit-bytes)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the plan as JSON instead of a table",
    )


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024.0
    return f"{n:.2f} GiB"


def _resolve_limit(args) -> tuple:
    """(limit_bytes, label) from --limit-bytes / --device, or (None, None)."""
    from ..telemetry.memplane import device_generation

    if args.limit_bytes is not None:
        return int(args.limit_bytes), "explicit"
    if args.device:
        row = device_generation(args.device)
        if row is None:
            print(
                f"error: unknown device generation {args.device!r} "
                "(known: v2 v3 v4 v5e v5p v6e)", file=sys.stderr,
            )
            return None, "unknown"
        return row[2], row[0]
    return None, None


def run_cmd(args, timeout: float = None) -> int:
    from ..telemetry.memplane import (
        max_batch_k,
        max_vars_per_device,
        predict_solve_bytes,
        shape_of,
        synthetic_shape,
    )
    from ._utils import build_algo_def

    params = {}
    if args.algo_params:
        algo_def = build_algo_def(args.algo, args.algo_params, mode="min")
        params = dict(algo_def.params or {})

    limit, limit_label = _resolve_limit(args)
    if limit is None and limit_label == "unknown":
        return 2

    # --- shape: exact (compiled file) or synthetic (headline numbers)
    compiled = None
    shape = None
    if args.dcop_files:
        from ..compile import compile_dcop
        from ..dcop.yamldcop import load_dcop_from_file

        dcop = load_dcop_from_file(args.dcop_files)
        compiled = compile_dcop(dcop)
        shape = shape_of(compiled)
    elif args.n_vars is not None and args.domain is not None:
        shape = synthetic_shape(
            args.n_vars, args.domain, degree=args.degree,
            float_bytes=args.float_bytes,
        )
    elif not (args.max_vars or args.max_batch_k):
        print(
            "error: describe the problem — dcop yaml file(s), or "
            "--n-vars with --domain", file=sys.stderr,
        )
        return 2

    out = {
        "algo": args.algo,
        "limit_bytes": limit,
        "device": limit_label,
        "reserve_pct": args.reserve_pct,
    }
    pred = None
    if shape is not None:
        pred = predict_solve_bytes(
            compiled, args.algo, params, shape=shape,
            mesh=args.mesh, batch_k=args.batch_k, n_cycles=args.n_cycles,
            serve_bucket=args.serve_bucket,
        )
        out["plan"] = pred
        if limit is not None:
            budget = limit * (1.0 - args.reserve_pct / 100.0)
            fits = pred["per_device_bytes"] <= budget
            out["budget_bytes"] = int(budget)
            out["fits"] = fits
            out["headroom_pct"] = round(
                100.0 * (1.0 - pred["per_device_bytes"] / limit), 2
            )

    # --- the two capacity-planning answers (need a limit)
    if args.max_vars or args.max_batch_k:
        if limit is None:
            print(
                "error: --max-vars/--max-batch-k need --device or "
                "--limit-bytes", file=sys.stderr,
            )
            return 2
        if args.domain is None:
            print(
                "error: --max-vars/--max-batch-k need --domain",
                file=sys.stderr,
            )
            return 2
        if args.max_vars:
            out["max_vars_per_device"] = max_vars_per_device(
                args.algo, args.domain, args.degree, limit,
                reserve_pct=args.reserve_pct, params=params,
                float_bytes=args.float_bytes,
            )
        if args.max_batch_k:
            if args.n_vars is None:
                print(
                    "error: --max-batch-k needs --n-vars (the "
                    "per-tenant shape)", file=sys.stderr,
                )
                return 2
            out["max_batch_k"] = max_batch_k(
                args.algo, args.domain, args.n_vars, args.degree, limit,
                reserve_pct=args.reserve_pct, params=params,
                float_bytes=args.float_bytes,
            )

    if args.as_json:
        write_output(args, out)
        return 0

    # --- table rendering (pinned by tests/test_memplane.py)
    if pred is not None:
        s = pred["shape"]
        print(
            f"graftmem memplan — algo {pred['algo']} "
            f"(family {pred['family']}, layout {pred['layout']})"
        )
        print(
            f"shape: {s['n_vars']} vars, domain {s['max_domain']}, "
            f"{s['n_edges']} edges, {s['n_constraints']} constraints"
        )
        if args.mesh != 1 or args.batch_k != 1:
            print(f"mesh: {args.mesh} devices, batch K {args.batch_k}")
        print(f"\n{'component':<16} {'bytes':>16} {'human':>12}")
        for name, b in sorted(
            pred["components"].items(), key=lambda kv: -kv[1]
        ):
            if not b:
                continue
            print(f"{name:<16} {b:>16d} {_fmt_bytes(b):>12}")
        print(
            f"{'per-device':<16} {pred['per_device_bytes']:>16d} "
            f"{_fmt_bytes(pred['per_device_bytes']):>12}"
        )
        print(f"dominant component: {pred['dominant']}")
        if limit is not None:
            print(
                f"\ndevice {limit_label}: limit {_fmt_bytes(limit)}, "
                f"reserve {args.reserve_pct:g}% -> budget "
                f"{_fmt_bytes(out['budget_bytes'])}"
            )
            verdict = "FITS" if out["fits"] else "REFUSE"
            print(
                f"verdict: {verdict} (headroom {out['headroom_pct']:g}%)"
            )
    if "max_vars_per_device" in out:
        print(
            f"max vars/device ({args.algo}, D={args.domain}, "
            f"degree {args.degree:g}): {out['max_vars_per_device']}"
        )
    if "max_batch_k" in out:
        print(
            f"max batch-K ({args.algo}, D={args.domain}, "
            f"{args.n_vars} vars): {out['max_batch_k']}"
        )
    return 0
