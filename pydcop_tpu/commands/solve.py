"""``pydcop solve``: end-to-end single-machine solve.

Role parity with /root/reference/pydcop/commands/solve.py (parser :226,
run_cmd:443, result JSON ``_results``:611 — statuses FINISHED / TIMEOUT /
STOPPED / ERROR, fields assignment/cost/violation/msg_count/msg_size/time/
cycle).

TPU-first default: ``--mode direct`` (new) compiles the DCOP and runs the
scan on device with no control plane at all — the benchmark path.  ``--mode
thread`` / ``--mode process`` run the full runtime (orchestrator + agents)
like the reference's two modes.
"""

from __future__ import annotations

import csv
import logging
import time
from typing import Any, Dict

from ..dcop.yamldcop import load_dcop_from_file
from ._utils import (
    add_chaos_arguments,
    add_csvio_arguments,
    add_durability_arguments,
    add_memguard_arguments,
    add_runtime_arguments,
    add_telemetry_arguments,
    build_algo_def,
    configure_memguard,
    build_chaos_controller,
    chaos_report,
    finish_durability,
    finish_telemetry,
    load_distribution_module,
    load_graph_module,
    start_durability,
    start_telemetry,
    write_output,
)

logger = logging.getLogger("pydcop_tpu.cli.solve")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "solve", help="solve a static DCOP on device"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument(
        "-a", "--algo", required=True, help="algorithm name"
    )
    parser.add_argument(
        "-p",
        "--algo_params",
        action="append",
        default=None,
        help="algorithm parameter as name:value (repeatable)",
    )
    parser.add_argument(
        "-d",
        "--distribution",
        default="oneagent",
        help="distribution method or distribution yaml file",
    )
    parser.add_argument(
        "-m",
        "--mode",
        choices=["direct", "thread", "process"],
        default="direct",
        help="direct = compiled device solve (fastest); thread/process = "
        "full runtime like the reference",
    )
    parser.add_argument(
        "-c",
        "--collect_on",
        choices=["value_change", "cycle_change", "period"],
        default="value_change",
    )
    parser.add_argument(
        "--period", type=float, default=None, help="for --collect_on period"
    )
    parser.add_argument(
        "-n", "--n_cycles", type=int, default=100,
        help="number of synchronous cycles to run",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--collect_curve", action="store_true",
        help="include the per-cycle cost curve in the result",
    )
    parser.add_argument(
        "--profile", default=None, metavar="DIR",
        help="legacy alias: bare jax profiler trace of the solve to DIR "
        "(view with tensorboard / xprof); prefer --profile-out, which "
        "adds per-phase annotations, compile.* metrics and the "
        "no-profiler fallback (docs/observability.md, graftprof)",
    )
    add_csvio_arguments(parser)
    add_runtime_arguments(parser)
    add_telemetry_arguments(parser)
    add_memguard_arguments(parser)
    add_chaos_arguments(parser)
    add_durability_arguments(parser)


def _dump_run_metrics(path: str, curve, offset: int = 0) -> None:
    """Per-cycle cost CSV; ``offset`` is the absolute cycle the curve
    starts after (nonzero for --resume runs, whose curve covers only the
    resumed cycles)."""
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(["cycle", "cost"])
        for i, c in enumerate(curve or []):
            w.writerow([offset + i + 1, c])


def run_cmd(args, timeout: float = None) -> int:
    bridge = start_telemetry(args)
    manager = start_durability(args)
    configure_memguard(args)
    try:
        return _run_cmd(args, timeout)
    finally:
        # a failed or timed-out solve still dumps the telemetry gathered
        # (and keeps whatever checkpoints it wrote — that is the point)
        finish_durability(args, manager)
        finish_telemetry(args, bridge)


def _run_cmd(args, timeout: float = None) -> int:
    t_load = time.perf_counter()
    dcop = load_dcop_from_file(args.dcop_files)
    logger.info(
        "loaded %s in %.3fs", args.dcop_files,
        time.perf_counter() - t_load,
    )
    algo_def = build_algo_def(
        args.algo, args.algo_params, mode=dcop.objective
    )

    import contextlib

    profile_ctx = contextlib.nullcontext()
    if args.mode == "process" and (
        getattr(args, "profile_out", None) or getattr(args, "dump_hlo", None)
    ):
        logger.warning(
            "--profile-out/--dump-hlo instrument this process; --mode "
            "process solves in child processes, so the device timeline "
            "and solver compile metrics will be empty (use direct or "
            "thread mode)"
        )
    if getattr(args, "profile", None) and getattr(args, "profile_out", None):
        # start_telemetry already opened the profiler session; a second
        # start_trace would raise mid-solve
        logger.warning(
            "--profile ignored: --profile-out is already recording a "
            "device timeline to %s", args.profile_out,
        )
    elif getattr(args, "profile", None):
        if args.mode == "process":
            logger.warning(
                "--profile only instruments this process; --mode process "
                "solves in child processes, so the trace will not contain "
                "solver activity (use direct or thread mode)"
            )
        import jax

        profile_ctx = jax.profiler.trace(args.profile)

    with profile_ctx:
        if args.mode == "direct":
            from ..api import solve_result

            if args.delay is not None or args.uiport is not None:
                logger.warning(
                    "--delay/--uiport shape the agent runtime; direct "
                    "mode has no agents — use --mode thread to observe "
                    "a run through the UI"
                )
            chaos = None
            if args.fault_schedule:
                chaos = build_chaos_controller(args)
                sched = chaos.schedule
                if (
                    sched.kills or sched.rules or sched.device_faults
                ):
                    logger.warning(
                        "--fault-schedule: agent kills / message rules / "
                        "device faults need the agent runtime; direct "
                        "mode ignores them — use --mode thread (or the "
                        "chaos verb)"
                    )
                if sched.process_kills:
                    # whole-process kills (graftdur's crash model) need
                    # no agents: arm the timeline around the device solve
                    chaos.start(None)
                else:
                    chaos = None
            if args.metrics_port is not None:
                logger.warning(
                    "--metrics-port serves the orchestrator's live "
                    "surface; direct mode has no orchestrator — use "
                    "--mode thread (metrics are still collected and "
                    "dumped via --metrics-out)"
                )
            distribution = (
                args.distribution
                if isinstance(args.distribution, str)
                else None
            )
            from ..telemetry.memplane import MemoryBudgetExceeded

            try:
                result = solve_result(
                    dcop,
                    algo_def,
                    distribution=distribution,
                    n_cycles=args.n_cycles,
                    seed=args.seed,
                    collect_curve=bool(
                        args.collect_curve or args.run_metrics
                    ),
                    timeout=timeout,
                    infinity=args.infinity,
                )
            except MemoryBudgetExceeded as e:
                # the guard's point: a named refusal BEFORE dispatch,
                # with the breach numbers in the result body, instead
                # of an XLA RESOURCE_EXHAUSTED traceback mid-solve
                logger.error("%s", e)
                result = {"status": "ERROR", "error": str(e),
                          "mem": e.breach}
            if chaos is not None:
                # the fault timeline is part of the run (chaos.md): a
                # process kill due at t fires even when the solve
                # returned early — otherwise the same schedule would
                # exercise different faults depending on machine speed
                pending = max(
                    (k.at for k in chaos.schedule.process_kills),
                    default=0.0,
                )
                chaos.wait_timeline(timeout=pending + 10.0)
                chaos.stop()
        else:
            result = _runtime_solve(args, dcop, algo_def, timeout)

    if args.run_metrics:
        offset = 0
        if getattr(args, "resume", None):
            # a resumed solve's curve starts at the checkpoint's cycle;
            # label the CSV in absolute cycles (run_cycles' curve_offset
            # contract)
            from ..durability import durability

            offset = int(
                (durability.last_resume or {}).get("cycle") or 0
            )
        _dump_run_metrics(
            args.run_metrics, result.get("cost_curve"), offset
        )
    if not args.collect_curve:
        result.pop("cost_curve", None)
    if args.end_metrics:
        import os

        exists = os.path.exists(args.end_metrics)
        with open(args.end_metrics, "a", newline="", encoding="utf-8") as f:
            w = csv.writer(f)
            if not exists:
                w.writerow(
                    ["time", "status", "cost", "violation", "cycle",
                     "msg_count", "msg_size"]
                )
            w.writerow(
                [result.get(k) for k in
                 ("time", "status", "cost", "violation", "cycle",
                  "msg_count", "msg_size")]
            )
    write_output(args, result)
    # TIMEOUT exits 0 deliberately (reference anytime semantics): it covers
    # both wall-clock expiry and a complete solver's max_iters cap — the
    # anytime incumbent is a usable result; scripts needing proven
    # optimality must check the status field, not the exit code
    return 0 if result.get("status") in ("FINISHED", "TIMEOUT") else 1


def _runtime_solve(args, dcop, algo_def, timeout) -> Dict[str, Any]:
    from ..infrastructure.run import (
        run_local_process_dcop,
        run_local_thread_dcop,
    )

    extra = {}
    chaos = None
    if args.metrics_port is not None:
        extra["metrics_port"] = args.metrics_port
    if args.mode == "thread":
        runner = run_local_thread_dcop
        if args.uiport is not None:
            extra["ui_port"] = args.uiport
        if args.delay is not None:
            extra["delay"] = args.delay
        chaos = build_chaos_controller(args)
        if chaos is not None:
            extra["chaos"] = chaos
    else:
        runner = run_local_process_dcop
        if args.delay is not None or args.uiport is not None:
            logger.warning(
                "--delay/--uiport are thread-mode options; process-mode "
                "agents ignore them"
            )
        if args.fault_schedule:
            logger.warning(
                "--fault-schedule requires in-process agents; "
                "process-mode runs ignore it (use --mode thread)"
            )
        if args.trace_out:
            # one trace per process: the parent keeps --trace-out, each
            # agent process writes <trace_out>.<agent>.json; merge with
            # `pydcop_tpu telemetry stitch` (docs/observability.md)
            extra["trace_out"] = args.trace_out
    orchestrator = runner(
        algo_def,
        dcop,
        args.distribution,
        n_cycles=args.n_cycles,
        seed=args.seed,
        collect_moment=args.collect_on,
        collect_period=args.period,
        infinity=args.infinity,
        **extra,
    )
    try:
        # process-mode agents are spawned OS processes whose interpreters
        # import jax (via the site plugin) before the agent loop runs —
        # several seconds each, concurrently — so the 10 s registration
        # default loses races on loaded machines; scale with agent count
        register_s = 10.0
        if args.mode == "process":
            register_s = max(60.0, 5.0 * len(dcop.agents))
            if timeout:
                register_s = min(register_s, timeout)
        t_reg = time.perf_counter()
        orchestrator.deploy_computations(timeout=register_s)
        # --timeout is a wall-clock bound on the whole command:
        # registration spends from the same budget the run gets
        remaining = (
            None if timeout is None
            else max(1.0, timeout - (time.perf_counter() - t_reg))
        )
        orchestrator.run(timeout=remaining)
        metrics = orchestrator.end_metrics()
        metrics.pop("repair_metrics", None)
        if chaos is not None:
            metrics["chaos"] = chaos_report(chaos, orchestrator)
        agent_traces = getattr(orchestrator, "_agent_trace_files", None)
        if agent_traces:
            # surface the per-process trace files so the stitch step is
            # discoverable from the result itself
            metrics["agent_trace_files"] = agent_traces
        return metrics
    finally:
        try:
            orchestrator.stop_agents()
        finally:
            orchestrator.stop()
            # process mode: wait for the (daemon) agent processes to
            # flush their per-agent trace files before this process
            # exits — a child still alive after the grace period will be
            # killed mid-export, so say WHICH trace is suspect instead
            # of letting a later stitch fail on truncated JSON
            stragglers = []
            for p in getattr(orchestrator, "_agent_processes", []):
                p.join(timeout=5.0)
                if p.is_alive():
                    stragglers.append(p.name)
            if stragglers:
                logger.warning(
                    "agent process(es) %s still running at exit; their "
                    "per-agent trace files may be truncated or missing",
                    stragglers,
                )
            agent_traces = getattr(
                orchestrator, "_agent_trace_files", None
            )
            if agent_traces:
                logger.info(
                    "per-agent traces written; merge with: pydcop_tpu "
                    "telemetry stitch %s %s -o merged.json",
                    args.trace_out, " ".join(agent_traces),
                )
