"""``pydcop agent``: standalone agents joining a remote orchestrator.

Role parity with /root/reference/pydcop/commands/agent.py (run_cmd:164):
start ``--names`` agents in this process, each with its own HTTP port
(incrementing from ``--port``), connected to the orchestrator at
``--orchestrator ip:port``; optional ``--restart`` daemon loop and
``--capacity``.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("pydcop_tpu.cli.agent")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "agent", help="start standalone agents over HTTP"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "-n", "--names", nargs="+", required=True, help="agent names"
    )
    parser.add_argument("-p", "--port", type=int, default=9001)
    parser.add_argument(
        "-o", "--orchestrator", required=True, help="orchestrator ip:port"
    )
    parser.add_argument("--capacity", type=int, default=100)
    parser.add_argument(
        "--restart", action="store_true",
        help="restart agents when they stop (daemon mode)",
    )
    parser.add_argument(
        "--ui_port", type=int, default=None,
        help="first websocket UI port (one per agent, incrementing)",
    )


def _start_agents(args):
    from ..dcop.objects import AgentDef
    from ..infrastructure.communication import HttpCommunicationLayer
    from ..infrastructure.orchestratedagents import OrchestratedAgent

    host, port_s = args.orchestrator.split(":")
    orchestrator_address = (host, int(port_s))
    agents = []
    for i, name in enumerate(args.names):
        comm = HttpCommunicationLayer(("0.0.0.0", args.port + i))
        agent = OrchestratedAgent(
            name,
            comm,
            orchestrator_address,
            agent_def=AgentDef(name, capacity=args.capacity),
            ui_port=(args.ui_port + i) if args.ui_port else None,
        )
        agent.start()
        logger.info("agent %s started on port %s", name, args.port + i)
        agents.append(agent)
    return agents


def run_cmd(args, timeout=None) -> int:
    while True:
        agents = _start_agents(args)
        while any(a.is_running for a in agents):
            time.sleep(0.2)
        if not args.restart:
            return 0
        logger.info("agents stopped; restarting (--restart)")
        time.sleep(1.0)
