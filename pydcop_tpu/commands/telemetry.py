"""``pydcop telemetry``: summarize / validate / stitch traces, convert
metrics.

New verb (no reference counterpart): a one-command answer to "where did
the wall-clock go?" over a trace produced by ``solve --trace-out`` or
``run --trace-out`` — per-span-name count / total / mean / max durations,
instant-event and message-flow counts, plus Chrome trace-event schema
validation (``--validate`` gates ``make trace-smoke``).

graftwatch additions:

- ``telemetry stitch -o merged.json a.json b.json ...`` merges the
  per-process trace files of a multi-process run into one
  Perfetto-loadable timeline (wall-clock epoch alignment + handshake
  clock-offset estimation, ``telemetry/stitch.py``); a directory
  argument globs its per-agent trace files, skipping (and naming)
  unreadable ones;
- ``telemetry --prom snapshot.json`` converts a ``--metrics-out``
  snapshot to Prometheus text format — the same formatter the live
  ``/metrics`` endpoint serves.

Host-only: never touches a device backend.
"""

from __future__ import annotations

import logging
import sys

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.telemetry")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "telemetry",
        help="summarize, validate or stitch traces; convert metrics",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "trace_file", nargs="*", default=[],
        help="Chrome trace-event JSON or JSONL file (from --trace-out); "
        "or `stitch FILE... -o merged.json` to merge per-process trace "
        "files into one timeline (list the files before -o; a directory "
        "expands to its *.json/*.jsonl files, unreadable ones skipped)",
    )
    parser.add_argument(
        "-o", "--out", default=None, metavar="FILE",
        help="output file: the stitched trace (stitch mode) or the "
        "Prometheus text (--prom); stdout otherwise",
    )
    parser.add_argument(
        "--prom", default=None, metavar="FILE",
        help="convert a --metrics-out JSON snapshot to Prometheus text "
        "format (written to -o/--out or stdout)",
    )
    parser.add_argument(
        "--openmetrics", action="store_true",
        help="with --prom: emit OpenMetrics 1.0 (exemplars, # EOF "
        "terminator) instead of classic text 0.0.4",
    )
    parser.add_argument(
        "--metrics", default=None, metavar="FILE",
        help="metrics snapshot JSON (from --metrics-out): prints a "
        "reliability section (send failures, retries, dead letters, "
        "injected chaos events), a graftprof compile section "
        "(XLA compiles, cache hits, flops/bytes, device windows) and a "
        "graftmem memory section (live gauges, predicted bytes, "
        "refusal counters)",
    )
    parser.add_argument(
        "--top", type=int, default=20,
        help="how many span names to list (heaviest first)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of a table",
    )
    parser.add_argument(
        "--validate", action="store_true",
        help="exit non-zero when the trace fails schema validation",
    )


#: metrics whose non-zero values mean messages were lost, retried or
#: injected — the counters an operator checks after a bad run
RELIABILITY_METRICS = (
    "comms.send_failures",
    "comms.retry_attempts",
    "comms.dead_letters",
    "comms.parked_depth",
    "chaos.events",
)


def _load_snapshot(metrics_file: str) -> dict:
    import json

    with open(metrics_file, "r", encoding="utf-8") as f:
        return json.load(f)


def _label_join(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def _compile_summary(snapshot: dict):
    """graftprof rows from a --metrics-out snapshot: every ``compile.*``
    and ``device.*`` series (counters/gauges as-is; histograms as count
    plus total), so "what did XLA build and where did device time go?"
    reads straight off the summary."""
    rows = []
    for name in sorted(snapshot.get("metrics", {})):
        if not name.startswith(("compile.", "device.", "mesh.")):
            continue
        m = snapshot["metrics"][name]
        for entry in m.get("values", []):
            labels = _label_join(entry.get("labels", {}))
            v = entry.get("value")
            if m.get("kind") == "histogram" and isinstance(v, dict):
                rows.append({
                    "metric": name, "labels": labels,
                    "value": int(v.get("count", 0)),
                    "total": round(float(v.get("sum", 0.0)), 6),
                })
            else:
                rows.append(
                    {"metric": name, "labels": labels, "value": v}
                )
    return rows


def _slo_summary(snapshot: dict):
    """graftslo rows from a --metrics-out snapshot: every ``slo.*``
    series plus the serve saturation gauges, so budget/burn/alert state
    reads straight off a dumped snapshot."""
    rows = []
    for name in sorted(snapshot.get("metrics", {})):
        if not name.startswith(("slo.", "serve.")):
            continue
        m = snapshot["metrics"][name]
        for entry in m.get("values", []):
            labels = _label_join(entry.get("labels", {}))
            v = entry.get("value")
            if m.get("kind") == "histogram" and isinstance(v, dict):
                rows.append({
                    "metric": name, "labels": labels,
                    "value": int(v.get("count", 0)),
                    "total": round(float(v.get("sum", 0.0)), 6),
                })
            else:
                rows.append(
                    {"metric": name, "labels": labels, "value": v}
                )
    return rows


def _memory_summary(snapshot: dict):
    """graftmem rows from a --metrics-out snapshot: every ``mem.*``
    series (live-plane gauges, predicted bytes, refusal / degradation
    counters), so "did it fit, and who got refused?" reads straight off
    the summary."""
    rows = []
    for name in sorted(snapshot.get("metrics", {})):
        if not name.startswith("mem."):
            continue
        m = snapshot["metrics"][name]
        for entry in m.get("values", []):
            labels = _label_join(entry.get("labels", {}))
            v = entry.get("value")
            if m.get("kind") == "histogram" and isinstance(v, dict):
                rows.append({
                    "metric": name, "labels": labels,
                    "value": int(v.get("count", 0)),
                    "total": round(float(v.get("sum", 0.0)), 6),
                })
            else:
                rows.append(
                    {"metric": name, "labels": labels, "value": v}
                )
    return rows


def _reliability_summary(snapshot: dict):
    """(rows, total_failures) from a --metrics-out snapshot: one row per
    (metric, labels) of the reliability set."""
    metrics = snapshot.get("metrics", {})
    rows = []
    failures = 0
    for name in RELIABILITY_METRICS:
        m = metrics.get(name)
        if not m:
            continue
        for entry in m.get("values", []):
            labels = ",".join(
                f"{k}={v}" for k, v in sorted(entry["labels"].items())
            )
            rows.append(
                {"metric": name, "labels": labels, "value": entry["value"]}
            )
            if name in ("comms.send_failures", "comms.dead_letters"):
                failures += int(entry["value"])
    return rows, failures


def _stitch_cmd(args) -> int:
    """``telemetry stitch -o OUT file-or-dir...``: merge per-process
    traces.  A directory argument expands to its trace files (sorted
    ``*.json`` + ``*.jsonl`` — the per-agent ``trace.json.<agent>.json``
    family a multi-process run leaves behind); unreadable files are
    skipped and reported rather than aborting the stitch."""
    import glob as _glob
    import json
    import os

    from ..telemetry.stitch import stitch_traces

    inputs = []
    for p in args.trace_file[1:]:
        if os.path.isdir(p):
            found = sorted(
                _glob.glob(os.path.join(p, "*.json"))
                + _glob.glob(os.path.join(p, "*.jsonl"))
            )
            if not found:
                print(
                    f"error: no *.json / *.jsonl trace files in {p}",
                    file=sys.stderr,
                )
                return 2
            inputs += found
        else:
            inputs.append(p)
    if not inputs:
        print("error: stitch needs at least one trace file", file=sys.stderr)
        return 2
    if not args.out:
        print(
            "error: stitch needs -o/--out for the merged trace",
            file=sys.stderr,
        )
        return 2
    try:
        trace, report = stitch_traces(inputs, skip_unreadable=True)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    for s in report.get("skipped", []):
        print(f"skipped {s['path']}: {s['error']}", file=sys.stderr)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    if args.as_json:
        write_output(args, report)
    else:
        for entry in report["files"]:
            print(
                f"{entry['path']}: {entry['events']} events"
                f"{' (' + entry['service'] + ')' if entry['service'] else ''}"
                f", epoch shift {entry['epoch_shift_us']:.0f} us"
                f", clock offset {entry['clock_offset_us']:.0f} us"
            )
        flows = report["flows"]
        pct = flows["match_pct"]
        print(
            f"flows: {flows['sends']} sends, {flows['matched']} matched"
            + (f" ({pct:.1f}%)" if pct is not None else "")
        )
        print(f"stitched trace -> {args.out}")
    return 0


def _prom_cmd(args) -> int:
    """``telemetry --prom FILE``: metrics snapshot -> Prometheus text."""
    import json

    from ..telemetry.prom import render_prometheus

    try:
        with open(args.prom, "r", encoding="utf-8") as f:
            snapshot = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    text = render_prometheus(snapshot, openmetrics=args.openmetrics)
    # -o/--out (subparser) or the global --output both name a file;
    # stdout otherwise
    output = args.out or getattr(args, "output", None)
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def run_cmd(args, timeout: float = None) -> int:
    from ..telemetry import format_summary, summarize_trace

    if args.trace_file and args.trace_file[0] == "stitch":
        return _stitch_cmd(args)
    if args.prom is not None:
        return _prom_cmd(args)
    if len(args.trace_file) > 1:
        print(
            "error: one trace file at a time (use `telemetry stitch` to "
            "merge several)", file=sys.stderr,
        )
        return 2
    trace_file = args.trace_file[0] if args.trace_file else None
    if trace_file is None and args.metrics is None:
        print(
            "error: nothing to summarize — give a trace file and/or "
            "--metrics FILE", file=sys.stderr,
        )
        return 2
    if args.validate and trace_file is None:
        print(
            "error: --validate needs a trace file to validate",
            file=sys.stderr,
        )
        return 2

    out = {}
    rc = 0
    if args.metrics is not None:
        try:
            snapshot = _load_snapshot(args.metrics)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        rows, failures = _reliability_summary(snapshot)
        out["reliability"] = {"rows": rows, "message_failures": failures}
        out["compile"] = _compile_summary(snapshot)
        slo_rows = _slo_summary(snapshot)
        if slo_rows:
            out["slo"] = slo_rows
        mem_rows = _memory_summary(snapshot)
        if mem_rows:
            out["memory"] = mem_rows

    summary = errors = None
    if trace_file is not None:
        try:
            summary, errors = summarize_trace(trace_file)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        out["summary"] = summary
        out["schema_errors"] = errors

    if args.as_json:
        write_output(args, out)
    else:
        if summary is not None:
            print(format_summary(summary, top=args.top))
            if errors:
                print(f"\nschema errors ({len(errors)}):", file=sys.stderr)
                for err in errors[:10]:
                    print(f"  {err}", file=sys.stderr)
        if "reliability" in out:
            rel = out["reliability"]
            print(f"\n{'reliability metric':<40} {'value':>10}")
            for row in rel["rows"]:
                label = f"{row['metric']}{{{row['labels']}}}"
                print(f"{label:<40} {row['value']:>10g}")
            if not rel["rows"]:
                print("  (no reliability metrics recorded)")
            print(f"message failures (lost/abandoned): {rel['message_failures']}")
        if "compile" in out:
            print(f"\n{'compile/device metric':<56} {'value':>12}")
            for row in out["compile"]:
                label = row["metric"]
                if row["labels"]:
                    label += "{" + row["labels"] + "}"
                extra = (
                    f"  (total {row['total']:g})" if "total" in row else ""
                )
                print(f"{label:<56} {row['value']:>12g}{extra}")
            if not out["compile"]:
                print("  (no compile/device metrics recorded — "
                      "produce the snapshot with --metrics-out, adding "
                      "--profile-out for the full graftprof set)")
        if out.get("slo"):
            print(f"\n{'slo/serve metric':<56} {'value':>12}")
            for row in out["slo"]:
                label = row["metric"]
                if row["labels"]:
                    label += "{" + row["labels"] + "}"
                extra = (
                    f"  (total {row['total']:g})" if "total" in row else ""
                )
                print(f"{label:<56} {row['value']:>12g}{extra}")
        if out.get("memory"):
            print(f"\n{'memory metric':<56} {'value':>12}")
            for row in out["memory"]:
                label = row["metric"]
                if row["labels"]:
                    label += "{" + row["labels"] + "}"
                extra = (
                    f"  (total {row['total']:g})" if "total" in row else ""
                )
                print(f"{label:<56} {row['value']:>12g}{extra}")
    if args.validate and errors:
        rc = 1
    return rc
