"""``pydcop telemetry``: summarize / validate a trace file.

New verb (no reference counterpart): a one-command answer to "where did
the wall-clock go?" over a trace produced by ``solve --trace-out`` or
``run --trace-out`` — per-span-name count / total / mean / max durations
and instant-event counts, plus Chrome trace-event schema validation
(``--validate`` gates ``make trace-smoke``).  Host-only: never touches a
device backend.
"""

from __future__ import annotations

import logging
import sys

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.telemetry")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "telemetry", help="summarize or validate a span-trace file"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "trace_file", nargs="?", default=None,
        help="Chrome trace-event JSON or JSONL file (from --trace-out)",
    )
    parser.add_argument(
        "--metrics", default=None, metavar="FILE",
        help="metrics snapshot JSON (from --metrics-out): prints a "
        "reliability section — send failures, retries, dead letters, "
        "injected chaos events",
    )
    parser.add_argument(
        "--top", type=int, default=20,
        help="how many span names to list (heaviest first)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of a table",
    )
    parser.add_argument(
        "--validate", action="store_true",
        help="exit non-zero when the trace fails schema validation",
    )


#: metrics whose non-zero values mean messages were lost, retried or
#: injected — the counters an operator checks after a bad run
RELIABILITY_METRICS = (
    "comms.send_failures",
    "comms.retry_attempts",
    "comms.dead_letters",
    "comms.parked_depth",
    "chaos.events",
)


def _reliability_summary(metrics_file: str):
    """(rows, total_failures) from a --metrics-out snapshot: one row per
    (metric, labels) of the reliability set."""
    import json

    with open(metrics_file, "r", encoding="utf-8") as f:
        snapshot = json.load(f)
    metrics = snapshot.get("metrics", {})
    rows = []
    failures = 0
    for name in RELIABILITY_METRICS:
        m = metrics.get(name)
        if not m:
            continue
        for entry in m.get("values", []):
            labels = ",".join(
                f"{k}={v}" for k, v in sorted(entry["labels"].items())
            )
            rows.append(
                {"metric": name, "labels": labels, "value": entry["value"]}
            )
            if name in ("comms.send_failures", "comms.dead_letters"):
                failures += int(entry["value"])
    return rows, failures


def run_cmd(args, timeout: float = None) -> int:
    from ..telemetry import format_summary, summarize_trace

    if args.trace_file is None and args.metrics is None:
        print(
            "error: nothing to summarize — give a trace file and/or "
            "--metrics FILE", file=sys.stderr,
        )
        return 2
    if args.validate and args.trace_file is None:
        print(
            "error: --validate needs a trace file to validate",
            file=sys.stderr,
        )
        return 2

    out = {}
    rc = 0
    if args.metrics is not None:
        try:
            rows, failures = _reliability_summary(args.metrics)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        out["reliability"] = {"rows": rows, "message_failures": failures}

    summary = errors = None
    if args.trace_file is not None:
        try:
            summary, errors = summarize_trace(args.trace_file)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        out["summary"] = summary
        out["schema_errors"] = errors

    if args.as_json:
        write_output(args, out)
    else:
        if summary is not None:
            print(format_summary(summary, top=args.top))
            if errors:
                print(f"\nschema errors ({len(errors)}):", file=sys.stderr)
                for err in errors[:10]:
                    print(f"  {err}", file=sys.stderr)
        if "reliability" in out:
            rel = out["reliability"]
            print(f"\n{'reliability metric':<40} {'value':>10}")
            for row in rel["rows"]:
                label = f"{row['metric']}{{{row['labels']}}}"
                print(f"{label:<40} {row['value']:>10g}")
            if not rel["rows"]:
                print("  (no reliability metrics recorded)")
            print(f"message failures (lost/abandoned): {rel['message_failures']}")
    if args.validate and errors:
        rc = 1
    return rc
