"""``pydcop telemetry``: summarize / validate a trace file.

New verb (no reference counterpart): a one-command answer to "where did
the wall-clock go?" over a trace produced by ``solve --trace-out`` or
``run --trace-out`` — per-span-name count / total / mean / max durations
and instant-event counts, plus Chrome trace-event schema validation
(``--validate`` gates ``make trace-smoke``).  Host-only: never touches a
device backend.
"""

from __future__ import annotations

import logging
import sys

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.telemetry")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "telemetry", help="summarize or validate a span-trace file"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "trace_file",
        help="Chrome trace-event JSON or JSONL file (from --trace-out)",
    )
    parser.add_argument(
        "--top", type=int, default=20,
        help="how many span names to list (heaviest first)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of a table",
    )
    parser.add_argument(
        "--validate", action="store_true",
        help="exit non-zero when the trace fails schema validation",
    )


def run_cmd(args, timeout: float = None) -> int:
    from ..telemetry import format_summary, summarize_trace

    try:
        summary, errors = summarize_trace(args.trace_file)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.as_json:
        write_output(
            args, {"summary": summary, "schema_errors": errors}
        )
    else:
        print(format_summary(summary, top=args.top))
        if errors:
            print(f"\nschema errors ({len(errors)}):", file=sys.stderr)
            for err in errors[:10]:
                print(f"  {err}", file=sys.stderr)
    if args.validate and errors:
        return 1
    return 0
