"""``pydcop generate``: benchmark problem generation.

Role parity with /root/reference/pydcop/commands/generate.py (graph coloring
:367, ising :838, and the generator modules in commands/generators/): every
workload family from the reference — graph_coloring, ising,
meeting_scheduling, secp, iot, small_world, agents, scenario — emitted as
YAML to stdout or ``--output``.
"""

from __future__ import annotations

import os
import sys
from typing import Any

from ..dcop.yamldcop import dcop_yaml, load_dcop_from_file, yaml_agents, yaml_scenario


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "generate", help="generate benchmark problems"
    )
    parser.set_defaults(
        func=lambda args, timeout=None: (parser.print_help(), 2)[1]
    )
    sub = parser.add_subparsers(dest="problem")

    gc = sub.add_parser("graph_coloring", help="graph coloring problems")
    gc.set_defaults(func=_gen_graph_coloring)
    gc.add_argument("-v", "--variables_count", type=int, required=True)
    gc.add_argument("-c", "--colors_count", type=int, default=3)
    gc.add_argument(
        "-g", "--graph", choices=["random", "scalefree", "grid"],
        default="random",
    )
    gc.add_argument("--p_edge", type=float, default=None)
    gc.add_argument("--m_edge", type=int, default=None)
    gc.add_argument("--soft", action="store_true")
    gc.add_argument("--extensive", action="store_true")
    gc.add_argument("--noise_level", type=float, default=0.02)
    gc.add_argument("--allow_subgraph", action="store_true")
    gc.add_argument("--seed", type=int, default=None)
    _add_output(gc)

    is_ = sub.add_parser("ising", help="ising model problems")
    is_.set_defaults(func=_gen_ising)
    is_.add_argument("--row_count", type=int, required=True)
    is_.add_argument("--col_count", type=int, default=None)
    is_.add_argument("--bin_range", type=float, default=1.6)
    is_.add_argument("--un_range", type=float, default=0.05)
    is_.add_argument("--intentional", action="store_true")
    is_.add_argument("--no_agents", action="store_true")
    is_.add_argument("--seed", type=int, default=None)
    _add_output(is_)

    ms = sub.add_parser(
        "meeting_scheduling", help="PEAV meeting scheduling problems"
    )
    ms.set_defaults(func=_gen_meetings)
    ms.add_argument("--slots_count", type=int, default=5)
    ms.add_argument("--resources_count", type=int, default=3)
    ms.add_argument("--max_resource_value", type=int, default=10)
    ms.add_argument("--events_count", type=int, default=3)
    ms.add_argument("--max_length_event", type=int, default=2)
    ms.add_argument("--max_resources_event", type=int, default=2)
    ms.add_argument("--penalty", type=int, default=100)
    ms.add_argument("--seed", type=int, default=0)
    _add_output(ms)

    secp = sub.add_parser("secp", help="smart environment problems")
    secp.set_defaults(func=_gen_secp)
    secp.add_argument("-l", "--lights", type=int, default=3)
    secp.add_argument("-m", "--models", type=int, default=2)
    secp.add_argument("-r", "--rules", type=int, default=2)
    secp.add_argument("-c", "--capacity", type=int, default=100)
    secp.add_argument("--max_model_size", type=int, default=3)
    secp.add_argument("--max_rule_size", type=int, default=2)
    secp.add_argument("--seed", type=int, default=0)
    _add_output(secp)

    mx = sub.add_parser(
        "mixed_problem", help="mixed hard/soft constraint problems"
    )
    mx.set_defaults(func=_gen_mixed)
    mx.add_argument("-v", "--variable_count", type=int, required=True)
    mx.add_argument("-c", "--constraint_count", type=int, required=True)
    mx.add_argument(
        "-H", "--hard_constraint", type=float, required=True,
        help="proportion of hard constraints, in [0, 1]",
    )
    mx.add_argument("-A", "--arity", type=int, default=2)
    mx.add_argument(
        "-r", "--range", type=int, required=True, dest="domain_range",
        help="variables take values 0, 1, ..., r-1",
    )
    mx.add_argument("-d", "--density", type=float, required=True)
    mx.add_argument("-a", "--agents", type=int, default=None)
    mx.add_argument("--capacity", type=int, default=0)
    mx.add_argument("--seed", type=int, default=None)
    _add_output(mx)

    iot = sub.add_parser("iot", help="IoT powerlaw problems")
    iot.set_defaults(func=_gen_iot)
    iot.add_argument("-n", "--num", type=int, default=30)
    iot.add_argument("-d", "--domain", type=int, default=10)
    iot.add_argument("-r", "--range", type=int, default=100)
    iot.add_argument("--seed", type=int, default=0)
    _add_output(iot)

    sw = sub.add_parser("small_world", help="small-world problems")
    sw.set_defaults(func=_gen_smallworld)
    sw.add_argument("-n", "--num", type=int, default=20)
    sw.add_argument("-k", "--degree", type=int, default=4)
    sw.add_argument("-p", "--rewire", type=float, default=0.1)
    sw.add_argument("-d", "--domain", type=int, default=5)
    sw.add_argument("-r", "--range", type=int, default=10)
    sw.add_argument("--seed", type=int, default=None)
    _add_output(sw)

    ag = sub.add_parser("agents", help="agent definitions for a dcop")
    ag.set_defaults(func=_gen_agents)
    ag.add_argument("--dcop_files", nargs="+", default=None)
    ag.add_argument("--count", type=int, default=None)
    ag.add_argument("--agent_prefix", default="a")
    ag.add_argument("--capacity", type=int, default=None)
    ag.add_argument(
        "--hosting", choices=["None", "name_mapping"], default="None"
    )
    ag.add_argument("--hosting_default", type=float, default=0)
    ag.add_argument("--routes_default", type=float, default=1)
    ag.add_argument("--routes_range", type=float, default=None)
    ag.add_argument("--seed", type=int, default=0)
    _add_output(ag)

    sc = sub.add_parser("scenario", help="agent-removal scenarios")
    sc.set_defaults(func=_gen_scenario)
    sc.add_argument("--evts_count", type=int, required=True)
    sc.add_argument("--actions_count", type=int, default=1)
    sc.add_argument("--delay", type=float, default=10)
    sc.add_argument("--initial_delay", type=float, default=5)
    sc.add_argument("--end_delay", type=float, default=5)
    sc.add_argument("--dcop_files", nargs="+", default=None)
    sc.add_argument("--agents", nargs="+", default=None)
    sc.add_argument("--seed", type=int, default=0)
    _add_output(sc)


def _add_output(parser) -> None:
    parser.add_argument("-o", "--output", default=None)


def _emit(args, text: str) -> int:
    if getattr(args, "output", None):
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def _gen_graph_coloring(args, timeout=None) -> int:
    from .generators.graphcoloring import generate_graph_coloring

    dcop = generate_graph_coloring(
        args.variables_count,
        args.colors_count,
        graph=args.graph,
        p_edge=args.p_edge,
        m_edge=args.m_edge,
        soft=args.soft,
        extensive=args.extensive,
        noise_level=args.noise_level,
        seed=args.seed,
        allow_subgraph=args.allow_subgraph,
    )
    return _emit(args, dcop_yaml(dcop))


def _gen_ising(args, timeout=None) -> int:
    from .generators.ising import generate_ising

    dcop = generate_ising(
        args.row_count,
        args.col_count or args.row_count,
        bin_range=args.bin_range,
        un_range=args.un_range,
        extensive=not args.intentional,
        no_agents=args.no_agents,
        seed=args.seed,
    )
    return _emit(args, dcop_yaml(dcop))


def _gen_meetings(args, timeout=None) -> int:
    from .generators.meetingscheduling import generate_meeting_scheduling

    dcop = generate_meeting_scheduling(
        slots_count=args.slots_count,
        resources_count=args.resources_count,
        max_resource_value=args.max_resource_value,
        events_count=args.events_count,
        max_length_event=args.max_length_event,
        max_resources_event=args.max_resources_event,
        penalty=args.penalty,
        seed=args.seed,
    )
    return _emit(args, dcop_yaml(dcop))


def _gen_secp(args, timeout=None) -> int:
    from .generators.secp import generate_secp

    dcop = generate_secp(
        lights=args.lights,
        models=args.models,
        rules=args.rules,
        capacity=args.capacity,
        max_model_size=args.max_model_size,
        max_rule_size=args.max_rule_size,
        seed=args.seed,
    )
    return _emit(args, dcop_yaml(dcop))


def _gen_mixed(args, timeout=None) -> int:
    from .generators.mixedproblem import generate_mixed_problem

    dcop = generate_mixed_problem(
        args.variable_count,
        args.constraint_count,
        args.hard_constraint,
        arity=args.arity,
        domain_range=args.domain_range,
        density=args.density,
        agents=args.agents,
        capacity=args.capacity,
        seed=args.seed,
    )
    return _emit(args, dcop_yaml(dcop))


def _gen_iot(args, timeout=None) -> int:
    import yaml as _yaml

    from .generators.iot import generate_iot

    dcop, mapping = generate_iot(
        num=args.num,
        domain_size=args.domain,
        constraint_range=args.range,
        seed=args.seed,
    )
    out = dcop_yaml(dcop)
    if args.output:
        _emit(args, out)
        dirname, basename = os.path.split(args.output)
        dist_path = os.path.join(dirname, f"dist_{basename}")
        with open(dist_path, "w", encoding="utf-8") as f:
            f.write(_yaml.dump({"distribution": mapping}))
        return 0
    return _emit(args, out)


def _gen_smallworld(args, timeout=None) -> int:
    from .generators.smallworld import generate_small_world

    dcop = generate_small_world(
        n=args.num,
        k=args.degree,
        p=args.rewire,
        domain_size=args.domain,
        cost_range=args.range,
        seed=args.seed,
    )
    return _emit(args, dcop_yaml(dcop))


def _gen_agents(args, timeout=None) -> int:
    from .generators.agents import (
        generate_agent_defs,
        generate_agents_from_count,
        generate_agents_from_variables,
    )

    computations: Any = []
    if args.dcop_files:
        dcop = load_dcop_from_file(args.dcop_files)
        computations = sorted(dcop.variables)
        names = generate_agents_from_variables(
            computations, args.agent_prefix
        )
    elif args.count:
        names = generate_agents_from_count(args.count, args.agent_prefix)
    else:
        raise ValueError("one of --dcop_files / --count is required")
    agents = generate_agent_defs(
        names,
        capacity=args.capacity,
        hosting_mode=None if args.hosting == "None" else args.hosting,
        computations=computations,
        default_hosting_cost=args.hosting_default,
        default_route=args.routes_default,
        routes_range=args.routes_range,
        seed=args.seed,
    )
    return _emit(args, yaml_agents(agents))


def _gen_scenario(args, timeout=None) -> int:
    from .generators.scenario import generate_scenario

    if args.agents:
        agents = args.agents
    elif args.dcop_files:
        dcop = load_dcop_from_file(args.dcop_files)
        agents = sorted(dcop.agents)
    else:
        raise ValueError("one of --agents / --dcop_files is required")
    scenario = generate_scenario(
        args.evts_count,
        args.actions_count,
        args.delay,
        args.initial_delay,
        args.end_delay,
        agents,
        seed=args.seed,
    )
    return _emit(args, yaml_scenario(scenario))
