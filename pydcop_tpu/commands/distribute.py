"""``pydcop distribute``: offline computation-to-agent placement.

Role parity with /root/reference/pydcop/commands/distribute.py: compute a
distribution for a DCOP with a given method (optionally priced with an
algorithm's footprint/communication models), output mapping + cost as YAML.
"""

from __future__ import annotations

import time
from typing import Any, Dict

from ..dcop.yamldcop import load_dcop_from_file
from ._utils import (
    load_distribution_module,
    load_graph_module,
    write_output,
)


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "distribute", help="compute a computation distribution"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument(
        "-d", "--distribution", required=True, help="distribution method"
    )
    parser.add_argument(
        "-g", "--graph", default=None,
        help="graph model (required unless --algo is given)",
    )
    parser.add_argument(
        "-a", "--algo", default=None,
        help="algorithm whose cost models should drive the distribution",
    )


def run_cmd(args, timeout=None) -> int:
    dcop = load_dcop_from_file(args.dcop_files)
    if args.algo is None and args.graph is None:
        raise ValueError("one of --algo / --graph is required")
    graph_module = load_graph_module(args.algo or args.graph)
    cg = graph_module.build_computation_graph(dcop)

    computation_memory = None
    communication_load = None
    if args.algo:
        from ..algorithms import load_algorithm_module

        algo_module = load_algorithm_module(args.algo)
        computation_memory = getattr(
            algo_module, "computation_memory", None
        )
        communication_load = getattr(
            algo_module, "communication_load", None
        )

    dist_module = load_distribution_module(args.distribution)
    t0 = time.perf_counter()
    distribution = dist_module.distribute(
        cg,
        list(dcop.agents.values()),
        hints=getattr(dcop, "dist_hints", None),
        computation_memory=computation_memory,
        communication_load=communication_load,
    )
    duration = time.perf_counter() - t0

    result: Dict[str, Any] = {
        "distribution": distribution.mapping,
        "duration": duration,
        "status": "OK",
    }
    cost_fn = getattr(dist_module, "distribution_cost", None)
    if cost_fn is not None and computation_memory is not None:
        try:
            result["cost"] = cost_fn(
                distribution,
                cg,
                list(dcop.agents.values()),
                computation_memory=computation_memory,
                communication_load=communication_load,
            )
        except (NotImplementedError, TypeError):
            result["cost"] = None
    write_output(args, result)
    return 0
