"""Shared helpers for CLI commands.

Role parity with /root/reference/pydcop/commands/_utils.py
(build_algo_def:48, module loading): parse ``--algo_params name:value`` pairs
into a validated AlgorithmDef, resolve graph/distribution modules, and write
results."""

from __future__ import annotations

import importlib
import json
import sys
from typing import Any, Dict, List, Optional

from ..algorithms import AlgorithmDef, load_algorithm_module

__all__ = [
    "build_algo_def",
    "load_graph_module",
    "load_distribution_module",
    "parse_params",
    "write_output",
    "add_csvio_arguments",
    "add_runtime_arguments",
    "add_telemetry_arguments",
    "add_chaos_arguments",
    "add_durability_arguments",
    "build_chaos_controller",
    "chaos_report",
    "start_telemetry",
    "finish_telemetry",
    "start_durability",
    "finish_durability",
]


def parse_params(param_strs: Optional[List[str]]) -> Dict[str, str]:
    """``name:value`` pairs -> dict (reference _utils.py:48)."""
    out: Dict[str, str] = {}
    for p in param_strs or []:
        if ":" not in p:
            raise ValueError(
                f"invalid algo parameter {p!r}: expected name:value"
            )
        name, value = p.split(":", 1)
        out[name.strip()] = value.strip()
    return out


def build_algo_def(
    algo_name: str,
    param_strs: Optional[List[str]] = None,
    mode: str = "min",
) -> AlgorithmDef:
    params = parse_params(param_strs)
    return AlgorithmDef.build_with_default_param(
        algo_name, params, mode=mode
    )


def load_graph_module(algo_name_or_graph: str):
    """Graph module from an algorithm name (via its GRAPH_TYPE) or a graph
    model name."""
    try:
        mod = load_algorithm_module(algo_name_or_graph)
        graph_type = mod.GRAPH_TYPE
    except ImportError:
        graph_type = algo_name_or_graph
    return importlib.import_module(
        f"pydcop_tpu.computations_graph.{graph_type}"
    )


def load_distribution_module(name: str):
    return importlib.import_module(f"pydcop_tpu.distribution.{name}")


def write_output(args, payload: Dict[str, Any]) -> None:
    """JSON result to --output file or stdout (reference solve.py:611)."""
    text = json.dumps(payload, indent=2, default=str, sort_keys=True)
    output = getattr(args, "output", None)
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    else:
        print(text)


def add_csvio_arguments(parser) -> None:
    parser.add_argument(
        "--run_metrics",
        default=None,
        help="CSV file for run-time metrics",
    )
    parser.add_argument(
        "--end_metrics",
        default=None,
        help="CSV file to append end-of-run metrics to",
    )


def add_telemetry_arguments(parser) -> None:
    """--trace-out / --metrics-out: the graftscope telemetry flags shared
    by ``solve`` and ``run`` (docs/observability.md)."""
    parser.add_argument(
        "--trace-out", default=None, metavar="FILE",
        help="enable span tracing and write a Chrome trace-event JSON "
        "(open in Perfetto / chrome://tracing); a .jsonl extension "
        "writes one event per line instead",
    )
    parser.add_argument(
        "--metrics-out", default=None, metavar="FILE",
        help="enable the metrics registry (+ event-bus bridge) and write "
        "a JSON snapshot of all counters/gauges/histograms at exit",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="enable the metrics registry and serve the live graftwatch "
        "surface from the orchestrator: /metrics (Prometheus text), "
        "/metrics.json and /status — poll it with `pydcop_tpu watch` "
        "(0 = pick an ephemeral port; thread/process runtime modes)",
    )
    parser.add_argument(
        "--profile-out", default=None, metavar="DIR",
        help="graftprof: record a jax.profiler device timeline of the "
        "solve into DIR (view in Perfetto / tensorboard), with "
        "TraceAnnotation markers per algorithm phase and timeout chunk; "
        "implies the metrics registry, and degrades to the host-clock "
        "device.chunk_ms fallback on backends without the profiler",
    )
    parser.add_argument(
        "--dump-hlo", default=None, metavar="DIR",
        help="graftprof: save the lowered HLO text of every fresh XLA "
        "compile into DIR (one file per jit entry point and shape "
        "bucket); implies the metrics registry",
    )
    parser.add_argument(
        "--pulse-out", default=None, metavar="FILE",
        help="graftpulse: enable per-cycle solver-health telemetry and "
        "stream one JSON line per cycle (flip counts, churn, message "
        "residual, violations) plus the final diagnosis to FILE; arms "
        "the postmortem flight recorder (docs/observability.md).  "
        "--metrics-port also enables pulse so `watch` can render the "
        "live churn/diagnosis block",
    )


def add_memguard_arguments(parser) -> None:
    """--mem-guard family: the graftmem OOM-guard flags shared by
    ``solve`` and ``serve`` (docs/observability.md, graftmem)."""
    parser.add_argument(
        "--mem-guard", action="store_true",
        help="graftmem: refuse a solve/admission whose predicted device "
        "bytes exceed the HBM limit minus the reserve — a loud named "
        "refusal (predicted vs capacity, dominant component) instead of "
        "an XLA RESOURCE_EXHAUSTED crash mid-dispatch",
    )
    parser.add_argument(
        "--mem-reserve-pct", type=float, default=None, metavar="PCT",
        help="fraction of the device limit the guard keeps free for XLA "
        "workspace/fragmentation (default 10); implies --mem-guard",
    )
    parser.add_argument(
        "--mem-limit-bytes", type=int, default=None, metavar="BYTES",
        help="override the device memory limit the guard budgets "
        "against (default: device.memory_stats() / the per-generation "
        "HBM table); implies --mem-guard",
    )


def configure_memguard(args) -> bool:
    """Arm the graftmem guard singleton per the CLI flags.  Any of the
    three flags arms it; returns True when armed."""
    if not (
        getattr(args, "mem_guard", False)
        or getattr(args, "mem_reserve_pct", None) is not None
        or getattr(args, "mem_limit_bytes", None) is not None
    ):
        return False
    from ..telemetry.memplane import memguard

    memguard.configure(
        enabled=True,
        reserve_pct=getattr(args, "mem_reserve_pct", None),
        limit_bytes=getattr(args, "mem_limit_bytes", None),
    )
    return True


def add_durability_arguments(parser) -> None:
    """--checkpoint/--resume: the graftdur durability flags shared by
    ``solve`` and ``run`` (docs/durability.md)."""
    parser.add_argument(
        "--checkpoint", nargs="?", const="", default=None, metavar="DIR",
        help="graftdur: periodically checkpoint the solver carry to DIR "
        "(atomic npz + manifest; default DIR = "
        "$PYDCOP_TPU_STATE_DIR/checkpoints).  Snapshots ride the cycle "
        "loop's chunk boundaries; a killed run resumes with --resume to "
        "the bit-identical trajectory of the uninterrupted run",
    )
    parser.add_argument(
        "--checkpoint-every", type=int, default=None, metavar="K",
        help="checkpoint cadence in cycles (default 64); combines with "
        "--checkpoint-every-seconds (whichever is due first)",
    )
    parser.add_argument(
        "--checkpoint-every-seconds", type=float, default=None,
        metavar="T",
        help="checkpoint cadence in wall seconds (checked at chunk "
        "boundaries)",
    )
    parser.add_argument(
        "--checkpoint-keep", type=int, default=None, metavar="N",
        help="rotation: keep the last N checkpoints (default 3)",
    )
    parser.add_argument(
        "--resume", default=None, metavar="PATH",
        help="resume a killed solve from a checkpoint file (or the "
        "newest one in a directory); the manifest must match this "
        "problem/algorithm/seed or the resume refuses loudly",
    )


def start_durability(args):
    """Configure the graftdur singleton per the CLI flags.  Returns the
    manager (or None) for ``finish_durability``.  Resolves --resume
    BEFORE the solve so a missing/mismatched path fails fast."""
    ckpt_dir = getattr(args, "checkpoint", None)
    resume = getattr(args, "resume", None)
    if ckpt_dir is None and resume is None:
        for flag in (
            "checkpoint_every", "checkpoint_every_seconds",
            "checkpoint_keep",
        ):
            if getattr(args, flag, None) is not None:
                import logging

                logging.getLogger("pydcop_tpu.durability").warning(
                    "--%s has no effect without --checkpoint",
                    flag.replace("_", "-"),
                )
        return None
    from ..durability import (
        DEFAULT_KEEP,
        CheckpointManager,
        durability,
        resolve_checkpoint_path,
    )

    manager = None
    if ckpt_dir is not None:
        keep = getattr(args, "checkpoint_keep", None)
        manager = CheckpointManager(
            ckpt_dir or None,
            every_cycles=getattr(args, "checkpoint_every", None),
            every_seconds=getattr(args, "checkpoint_every_seconds", None),
            keep=DEFAULT_KEEP if keep is None else keep,
        )
    if resume is not None:
        resume = resolve_checkpoint_path(resume)
    durability.configure(manager=manager, resume=resume)
    return manager


def finish_durability(args, manager) -> None:
    """Report what durability did and switch the singleton back off.
    Runs in a ``finally`` next to finish_telemetry."""
    if (
        getattr(args, "checkpoint", None) is None
        and getattr(args, "resume", None) is None
    ):
        return
    import logging

    logger = logging.getLogger("pydcop_tpu.durability")
    from ..durability import durability

    if manager is not None:
        if manager.saved_paths:
            logger.info(
                "%d checkpoint(s) in %s (newest: %s)",
                len(manager.saved_paths), manager.directory,
                manager.saved_paths[-1],
            )
        elif not manager.bound:
            logger.warning(
                "--checkpoint: no checkpoints written — the algorithm "
                "never entered the cycle loop (one-shot solvers like "
                "dpop have no checkpointable carry)"
            )
        else:
            logger.warning(
                "--checkpoint: solve finished before the first cadence "
                "boundary (every %s cycles / %s s) — nothing written",
                manager.every_cycles, manager.every_seconds,
            )
    durability.reset()


def add_chaos_arguments(parser) -> None:
    """--fault-schedule: the graftchaos flag shared by ``solve``, ``run``
    and the ``chaos`` verb (docs/chaos.md)."""
    parser.add_argument(
        "--fault-schedule", default=None, metavar="FILE",
        help="YAML fault schedule (seeded kills / message faults / device "
        "faults) injected into the run; requires the thread-mode agent "
        "runtime (see docs/chaos.md)",
    )


def build_chaos_controller(args):
    """A ChaosController from --fault-schedule, or None when unset."""
    path = getattr(args, "fault_schedule", None)
    if not path:
        return None
    from ..chaos import ChaosController, load_fault_schedule

    return ChaosController(load_fault_schedule(path))


def chaos_report(controller, orchestrator) -> Dict[str, Any]:
    """The ``chaos`` block attached to results of fault-injected runs:
    the deterministic event log, per-action counts, and the dead-letter
    total across the orchestrator and every local agent."""
    return {
        "seed": controller.seed,
        "events": controller.event_log(),
        "counts": controller.action_counts(),
        "dead_letters": orchestrator.dead_letter_total(),
    }


def start_telemetry(args):
    """Enable the telemetry singletons per the CLI flags.  Returns the
    attached event-bus bridge (or None) for ``finish_telemetry``."""
    from ..telemetry import attach_event_bridge, metrics_registry, tracer

    bridge = None
    if getattr(args, "trace_out", None):
        tracer.service = "orchestrator"
        tracer.reset()
        tracer.enabled = True
    profile_out = getattr(args, "profile_out", None)
    dump_hlo = getattr(args, "dump_hlo", None)
    if (
        getattr(args, "metrics_out", None)
        or getattr(args, "metrics_port", None) is not None
        or profile_out
        or dump_hlo
    ):
        # --metrics-port needs the registry live exactly like
        # --metrics-out does; the two compose (scrape live, dump at
        # exit).  The graftprof flags imply it too: compile.*/device.*
        # observations land in the registry
        metrics_registry.reset()
        metrics_registry.enabled = True
        # bus topics -> metrics, so per-computation counters ride along
        bridge = attach_event_bridge()
    if profile_out or dump_hlo:
        # imports jax lazily; solve/run are committed to a backend anyway
        from ..telemetry import start_profiling

        start_profiling(profile_dir=profile_out, hlo_dir=dump_hlo)
    pulse_out = getattr(args, "pulse_out", None)
    if pulse_out or getattr(args, "metrics_port", None) is not None:
        # graftpulse: per-cycle health vectors compiled into the device
        # loop + the postmortem flight recorder.  A live-watched run
        # (--metrics-port) gets it implicitly so /status carries the
        # pulse block; plain --metrics-out does NOT (bench timings must
        # not silently grow device work)
        from ..telemetry.pulse import pulse

        pulse.reset()
        pulse.enabled = True
        if pulse_out:
            pulse.stream_open(pulse_out)
    return bridge


def finish_telemetry(args, bridge) -> None:
    """Export per the CLI flags and switch telemetry back off.  Runs in a
    ``finally`` so a failed solve still dumps what it gathered; the two
    exports are independent — a broken trace path must not discard the
    metrics snapshot (or vice versa), nor clobber the command's exit
    code, so export errors are reported on stderr instead of raised."""
    from ..telemetry import metrics_registry, tracer

    if bridge is not None:
        bridge.detach()
    if (
        getattr(args, "pulse_out", None)
        or getattr(args, "metrics_port", None) is not None
    ):
        from ..telemetry.pulse import pulse

        pulse.enabled = False
        pulse.stream_close()
    if getattr(args, "profile_out", None) or getattr(args, "dump_hlo", None):
        from ..telemetry import profiling, stop_profiling

        stop_profiling()
        if profiling.profiler_error:
            if profiling.profiler_error.startswith("stop_trace failed"):
                # the profiler ran; only the trace export failed
                print(
                    f"warning: device profiler trace export failed "
                    f"({profiling.profiler_error})",
                    file=sys.stderr,
                )
            else:
                print(
                    f"warning: device profiler unavailable "
                    f"({profiling.profiler_error}); the host-clock "
                    f"device.chunk_ms fallback was recorded instead",
                    file=sys.stderr,
                )
        metrics_registry.enabled = False
    if getattr(args, "metrics_port", None) is not None:
        metrics_registry.enabled = False
    if getattr(args, "metrics_out", None):
        metrics_registry.enabled = False
        try:
            metrics_registry.dump(args.metrics_out)
        except OSError as e:
            print(
                f"warning: could not write --metrics-out "
                f"{args.metrics_out}: {e}",
                file=sys.stderr,
            )
    if getattr(args, "trace_out", None):
        tracer.enabled = False
        try:
            if args.trace_out.endswith(".jsonl"):
                tracer.export_jsonl(args.trace_out)
            else:
                tracer.export_chrome(args.trace_out)
        except OSError as e:
            print(
                f"warning: could not write --trace-out "
                f"{args.trace_out}: {e}",
                file=sys.stderr,
            )


def add_runtime_arguments(parser) -> None:
    """The reference solve/run options that shape the agent runtime and
    cost reporting (reference commands/solve.py:286-341)."""
    # jax-free single source for the default threshold (api.py re-exports
    # it); importing ..api here would pull jax + every algorithm module
    # into parser construction, i.e. into --help and host-only verbs
    from ..constants import INFINITY

    parser.add_argument(
        "-i", "--infinity", type=float, default=INFINITY,
        help="value standing in for symbolic infinity when reporting "
        f"hard-constraint costs (default {INFINITY}, like the reference)",
    )
    parser.add_argument(
        "--delay", type=float, default=None,
        help="artificial delay (seconds) between algorithm message "
        "deliveries — for observing a run through the UI; thread mode "
        "only",
    )
    parser.add_argument(
        "--uiport", type=int, default=None,
        help="base port for the per-agent websocket UI servers; thread "
        "mode only (agents get uiport, uiport+1, ...)",
    )
