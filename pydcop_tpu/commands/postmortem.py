"""``pydcop_tpu postmortem``: render a graftpulse flight-recorder dump.

A solve with pulse enabled (``--pulse-out`` / ``--metrics-port``) arms the
flight recorder — a bounded ring of the last K per-cycle health vectors
plus the run's config fingerprint — and auto-dumps ``postmortem.json``
when the run dies badly: chaos divergence, solve timeout, or an
``Agent.crash()``.  This verb prints the diagnosis timeline of such a
dump: per-window diagnoses (converged / stalled-plateau /
oscillating(period=k) / still-improving), the overall verdict, and the
frozen-vs-churning variable summary.  Host-only — no jax import, safe on
any machine (docs/observability.md, graftpulse).
"""

from __future__ import annotations

import logging
import sys

from ..telemetry.pulse import load_postmortem, render_postmortem
from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.postmortem")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "postmortem",
        help="render a graftpulse postmortem.json diagnosis timeline",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "file", help="postmortem.json written by the flight recorder"
    )
    parser.add_argument(
        "--window", type=int, default=16,
        help="cycles per diagnosis-timeline row (default 16)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the parsed document (with its diagnosis) as JSON "
        "instead of the rendered timeline",
    )
    parser.add_argument(
        "--output", default=None, help="write to this file instead of stdout"
    )


def run_cmd(args, timeout: float = None) -> int:
    try:
        doc = load_postmortem(args.file)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.as_json:
        write_output(args, doc)
        return 0
    text = render_postmortem(doc, window=max(1, args.window))
    output = getattr(args, "output", None)
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    else:
        print(text)
    return 0
