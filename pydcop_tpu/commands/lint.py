"""``pydcop lint``: run graftlint, the repo's static-analysis passes.

No reference-CLI counterpart: the thread-per-agent reference had no
machine-checked concurrency or tracing discipline.  This wraps
:mod:`pydcop_tpu.analysis` (lock discipline, JAX tracing hazards,
message-protocol consistency, the graftflow abstract shape/dtype
interpreter, and the graftproto conversation verifier) so CI and
developers share one entry point with the baseline ratchet:

    pydcop_tpu lint --baseline tools/graftlint_baseline.json pydcop_tpu/
    pydcop_tpu lint --explain proto-reply-gap
    pydcop_tpu lint --format sarif pydcop_tpu/ > graftlint.sarif

Warm reruns are served from the content-hash finding cache under
``$PYDCOP_TPU_STATE_DIR`` (``--no-cache`` bypasses it).  Exit codes are
unchanged across formats: 0 clean, 1 new findings, 2 usage error.
"""

from __future__ import annotations

__all__ = ["set_parser", "run_cmd"]


def set_parser(subparsers) -> None:
    from ..analysis.cli import build_parser

    parser = subparsers.add_parser(
        "lint",
        help="static analysis: locks, JAX tracing, message protocol, "
        "array shape/dtype flow, conversation verification "
        "(graftproto); cached, text/json/sarif output",
    )
    build_parser(parser)
    parser.set_defaults(func=run_cmd)


def run_cmd(args, timeout=None) -> int:
    from ..analysis.cli import run_lint

    return run_lint(args)
