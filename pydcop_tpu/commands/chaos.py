"""``pydcop_tpu chaos``: replay a fault schedule against a DCOP run.

New verb (no reference counterpart; docs/chaos.md): runs the full
thread-mode runtime — orchestrator, agents, replication, repair — under a
seeded :class:`~pydcop_tpu.chaos.FaultSchedule`, then reports the
deterministic fault event log next to the solve result.  The exit code
makes it CI-able: non-zero when the run does not finish, when
``--max-dead-letters`` is exceeded, or when ``--check-convergence`` finds
the faulted assignment differs from the fault-free one (``make
chaos-smoke`` is exactly that, with a kill-and-repair schedule).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict

from ..dcop.yamldcop import load_dcop_from_file
from ._utils import (
    add_runtime_arguments,
    add_telemetry_arguments,
    build_algo_def,
    chaos_report,
    finish_telemetry,
    start_telemetry,
    write_output,
)

logger = logging.getLogger("pydcop_tpu.cli.chaos")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "chaos",
        help="replay a fault schedule against a run, print the event log",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument(
        "-p", "--algo_params", action="append", default=None
    )
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument(
        "--fault-schedule", required=True, metavar="FILE",
        help="YAML fault schedule to replay (docs/chaos.md)",
    )
    parser.add_argument("-n", "--n_cycles", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "-k", "--ktarget", type=int, default=None,
        help="replicate computations k-fold before the faults hit",
    )
    parser.add_argument(
        "--replication-mode", choices=["distributed", "local"],
        default="distributed",
        help="replica placement: the graftucs negotiation protocol "
        "(distributed, default) or the centralized UCS oracle (local) — "
        "docs/resilience.md",
    )
    parser.add_argument(
        "--event-log", default=None, metavar="FILE",
        help="also write the fault event log JSON to FILE",
    )
    parser.add_argument(
        "--max-dead-letters", type=int, default=None, metavar="N",
        help="fail (exit 1) when more than N parked messages were "
        "dead-lettered during the run",
    )
    parser.add_argument(
        "--check-convergence", action="store_true",
        help="also run fault-free and fail (exit 1) unless the faulted "
        "run converges to the same assignment",
    )
    add_runtime_arguments(parser)
    add_telemetry_arguments(parser)


def run_cmd(args, timeout: float = None) -> int:
    bridge = start_telemetry(args)
    try:
        return _run_cmd(args, timeout)
    finally:
        finish_telemetry(args, bridge)


def _run_cmd(args, timeout: float = None) -> int:
    from ..chaos import ChaosController, load_fault_schedule
    from ..infrastructure.run import run_local_thread_dcop

    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(
        args.algo, args.algo_params, mode=dcop.objective
    )
    schedule = load_fault_schedule(args.fault_schedule)
    controller = ChaosController(schedule)

    baseline = None
    if args.check_convergence:
        from ..api import solve_result

        # the fault-free reference: the device solve is seeded, so the
        # faulted run must land on this exact assignment once repair has
        # done its job
        baseline = solve_result(
            dcop,
            algo_def,
            n_cycles=args.n_cycles,
            seed=args.seed,
            infinity=args.infinity,
        )["assignment"]

    extra = {}
    if args.uiport is not None:
        extra["ui_port"] = args.uiport
    if args.delay is not None:
        extra["delay"] = args.delay
    if args.metrics_port is not None:
        extra["metrics_port"] = args.metrics_port
    t0 = time.perf_counter()
    orchestrator = run_local_thread_dcop(
        algo_def,
        dcop,
        args.distribution,
        n_cycles=args.n_cycles,
        seed=args.seed,
        infinity=args.infinity,
        chaos=controller,
        replication_mode=args.replication_mode,
        **extra,
    )
    try:
        orchestrator.deploy_computations()
        if args.ktarget:
            orchestrator.start_replication(args.ktarget)
        orchestrator.run(timeout=timeout)
        result: Dict[str, Any] = orchestrator.end_metrics()
    finally:
        try:
            orchestrator.stop_agents()
        finally:
            orchestrator.stop()

    result["chaos"] = chaos_report(controller, orchestrator)
    result["chaos"]["wall_s"] = round(time.perf_counter() - t0, 3)
    if baseline is not None:
        result["chaos"]["converged"] = result["assignment"] == baseline
    if args.event_log:
        controller.dump(args.event_log)
    write_output(args, result)

    failures = []
    if result.get("status") not in ("FINISHED", "TIMEOUT"):
        failures.append(f"run status {result.get('status')}")
    dead = result["chaos"]["dead_letters"]
    if (
        args.max_dead_letters is not None
        and dead > args.max_dead_letters
    ):
        failures.append(
            f"{dead} dead letters (max {args.max_dead_letters})"
        )
    if baseline is not None and not result["chaos"]["converged"]:
        failures.append("assignment diverged from the fault-free run")
        # graftpulse: divergence is a postmortem-worthy outcome — leave
        # the faulted run's health tail behind for `pydcop_tpu postmortem`
        from ..telemetry.pulse import pulse

        dumped = pulse.recorder.maybe_dump("chaos-divergence")
        if dumped:
            logger.error("postmortem written to %s", dumped)
    for f in failures:
        logger.error("chaos run failed: %s", f)
    return 1 if failures else 0
