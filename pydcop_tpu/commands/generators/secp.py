"""SECP benchmark generator: smart-environment configuration problems.

Workload parity with /root/reference/pydcop/commands/generators/secp.py
(generate_secp:129): ``lights`` light variables (domain 0..4) each with a
linear efficiency cost; ``models`` model variables tied to a weighted sum of
lights by a hard threshold constraint; ``rules`` soft constraints setting
targets for lights/models; one agent per light with hosting costs preferring
its own variable+cost and a high default hosting cost.
"""

from __future__ import annotations

import random
from typing import Dict, List

from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import constraint_from_str

__all__ = ["generate_secp"]


def generate_secp(
    lights: int = 3,
    models: int = 2,
    rules: int = 2,
    capacity: int = 100,
    max_model_size: int = 3,
    max_rule_size: int = 2,
    seed: int = 0,
) -> DCOP:
    rng = random.Random(seed)
    light_domain = Domain("light", "light", list(range(5)))
    dcop = DCOP("secp", "min")

    light_vars: Dict[str, Variable] = {}
    light_costs: Dict[str, str] = {}
    for i in range(lights):
        v = Variable(f"l{i}", light_domain)
        light_vars[v.name] = v
        dcop.add_variable(v)
        efficiency = rng.randint(0, 90) / 100
        c = constraint_from_str(
            f"c_l{i}", f"{v.name} * {efficiency}", [v]
        )
        dcop.add_constraint(c)
        light_costs[v.name] = c.name

    model_vars: Dict[str, Variable] = {}
    for j in range(models):
        mv = Variable(f"m{j}", light_domain)
        model_vars[mv.name] = mv
        dcop.add_variable(mv)
        size = rng.randint(2, max(2, max_model_size))
        chosen = rng.sample(sorted(light_vars), min(size, lights))
        expr = " + ".join(
            f"{name} * {rng.randint(1, 7) / 10}" for name in chosen
        )
        con = constraint_from_str(
            f"c_m{j}",
            f"0 if 10 * abs({mv.name} - ({expr})) < 5 else 10000",
            [light_vars[n] for n in chosen] + [mv],
        )
        dcop.add_constraint(con)

    all_vars = {**light_vars, **model_vars}
    for k in range(rules):
        max_size = min(max_rule_size, len(all_vars))
        rule_size = rng.randint(1, max_size)
        chosen = rng.sample(sorted(all_vars), rule_size)
        expr = " + ".join(
            f"abs({name} - {rng.randint(0, 4)})" for name in chosen
        )
        con = constraint_from_str(
            f"r_{k}", f"10 * ({expr})", [all_vars[n] for n in chosen]
        )
        dcop.add_constraint(con)

    agents: List[AgentDef] = []
    for name, cost_name in light_costs.items():
        agents.append(
            AgentDef(
                f"a{name}",
                capacity=capacity,
                hosting_costs={name: 0, cost_name: 0},
                default_hosting_cost=100,
            )
        )
    dcop.add_agents(agents)
    return dcop
