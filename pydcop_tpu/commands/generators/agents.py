"""Agent-definition generator.

Workload parity with /root/reference/pydcop/commands/generators/agents.py
(generate:186, generate_agents_names:263, generate_hosting_costs:294,
generate_routes_costs:305): agent lists named from a count or from a DCOP's
variables, with capacity, hosting-cost modes (``None`` | ``name_mapping`` —
zero cost for the matching variable — | ``var_startswith``) and random route
costs.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ...dcop.objects import AgentDef

__all__ = [
    "generate_agents_from_count",
    "generate_agents_from_variables",
    "generate_agent_defs",
]


def generate_agents_from_count(
    agent_count: int, agent_prefix: str = "a"
) -> List[str]:
    digits = len(str(agent_count - 1)) if agent_count > 1 else 1
    return [f"{agent_prefix}{i:0{digits}d}" for i in range(agent_count)]


def generate_agents_from_variables(
    variables: List[str], agent_prefix: str = "a"
) -> List[str]:
    """One agent per variable, named after it (reference :279: variable
    ``v12`` -> agent ``a12``; non-numeric names are prefixed whole)."""
    out = []
    for v in variables:
        suffix = v[1:] if v and not v[0].isdigit() else v
        out.append(f"{agent_prefix}{suffix}")
    return out


def generate_hosting_costs(
    mode: Optional[str], agent_names: List[str], computations: List[str]
) -> Dict[str, Dict[str, float]]:
    """hosting costs per agent (reference :294): ``name_mapping`` gives cost
    0 for the computation whose name matches the agent's suffix."""
    costs: Dict[str, Dict[str, float]] = {}
    if mode == "name_mapping":
        comp_by_suffix = {c[1:]: c for c in computations}
        for a in agent_names:
            suffix = a[1:]
            if suffix in comp_by_suffix:
                costs[a] = {comp_by_suffix[suffix]: 0.0}
    return costs


def generate_agent_defs(
    names: List[str],
    capacity: Optional[int] = None,
    hosting_mode: Optional[str] = None,
    computations: Optional[List[str]] = None,
    default_hosting_cost: float = 0,
    default_route: float = 1,
    routes_range: Optional[float] = None,
    seed: int = 0,
) -> List[AgentDef]:
    rng = random.Random(seed)
    hosting = generate_hosting_costs(
        hosting_mode, names, computations or []
    )
    routes: Dict[str, Dict[str, float]] = {}
    if routes_range:
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                routes.setdefault(a, {})[b] = round(
                    rng.uniform(0, routes_range), 2
                )
    out = []
    for a in names:
        kwargs = {}
        if capacity is not None:
            kwargs["capacity"] = capacity
        out.append(
            AgentDef(
                a,
                default_hosting_cost=default_hosting_cost,
                hosting_costs=hosting.get(a, {}),
                default_route=default_route,
                routes=routes.get(a, {}),
                **kwargs,
            )
        )
    return out
