"""Small-world benchmark generator.

Workload parity with /root/reference/pydcop/commands/generators/smallworld.py
(generate_small_world:50): a Watts-Strogatz small-world constraint graph with
random binary cost tables, one variable per node.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import NAryMatrixRelation

__all__ = ["watts_strogatz_edges", "generate_small_world"]


def watts_strogatz_edges(
    n: int, k: int, p: float, rng: np.random.Generator
) -> np.ndarray:
    """Watts-Strogatz ring-lattice rewiring: each node connects to its k//2
    nearest neighbors on a ring; each edge is rewired with probability p."""
    edges = set()
    for i in range(n):
        for offset in range(1, k // 2 + 1):
            j = (i + offset) % n
            if rng.random() < p:
                choices = [
                    m
                    for m in range(n)
                    if m != i
                    and (min(i, m), max(i, m)) not in edges
                ]
                if choices:
                    j = int(rng.choice(choices))
            if i != j:
                edges.add((min(i, j), max(i, j)))
    return np.asarray(sorted(edges), dtype=np.int32).reshape(-1, 2)


def generate_small_world(
    n: int = 20,
    k: int = 4,
    p: float = 0.1,
    domain_size: int = 5,
    cost_range: int = 10,
    seed: Optional[int] = None,
) -> DCOP:
    rng = np.random.default_rng(seed)
    edges = watts_strogatz_edges(n, k, p, rng)
    domain = Domain("d", "d", list(range(domain_size)))
    dcop = DCOP(f"smallworld_{n}_{k}_{p}", "min")
    variables = {}
    for i in range(n):
        v = Variable(f"v{i:03d}", domain)
        variables[i] = v
        dcop.add_variable(v)
    for i, j in edges:
        table = rng.integers(
            0, cost_range, (domain_size, domain_size)
        ).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation(
                [variables[int(i)], variables[int(j)]],
                table,
                name=f"c{int(i):03d}_{int(j):03d}",
            )
        )
    dcop.add_agents([AgentDef(f"a{i:03d}") for i in range(n)])
    return dcop
