"""Meeting-scheduling benchmark generator (PEAV model).

Workload parity with /root/reference/pydcop/commands/generators/
meetingscheduling.py (peav_model:317): resources with per-slot "value if kept
free", events requiring a subset of resources with per-resource values and a
length; in the PEAV encoding each resource is an agent controlling one
variable per event it may attend (domain = start slot, 0 = not scheduled,
:439-456).  Intra-agent constraints penalize overlapping schedules and carry
the scheduling utility (:503-585); inter-agent equality constraints penalize
resources disagreeing on an event's start time (:588-600).  Objective is
``max`` (:242).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import NAryMatrixRelation

__all__ = [
    "Resource",
    "Event",
    "generate_problem_definition",
    "generate_meeting_scheduling",
]


@dataclass
class Resource:
    id: int
    value_free: Dict[int, int]  # slot -> value if kept free


@dataclass
class Event:
    id: int
    resources: Dict[int, int]  # resource id -> value of attending
    length: int


def generate_problem_definition(
    slots_count: int,
    resources_count: int,
    max_resource_value: int,
    events_count: int,
    max_length_event: int,
    max_resources_event: int,
    rng: random.Random,
) -> Tuple[List[int], Dict[int, Event], Dict[int, Resource]]:
    """Random multi-event scheduling instance (reference :368-437)."""
    slots = list(range(1, slots_count + 1))
    resources = {
        i: Resource(
            i, {s: rng.randint(0, max_resource_value) for s in slots}
        )
        for i in range(resources_count)
    }
    events: Dict[int, Event] = {}
    for i in range(events_count):
        length = rng.randint(1, max_length_event)
        k = rng.randint(1, max_resources_event)
        chosen = rng.sample(sorted(resources), min(k, len(resources)))
        values = {r: rng.randint(1, max_resource_value) for r in chosen}
        events[i] = Event(i, values, length)
    return slots, events, resources


def _value_for_event(res: Resource, evt: Event, t: int) -> float:
    """Utility of scheduling ``res`` on ``evt`` at slot ``t`` — event value
    minus the forgone free-slot values (reference :603-630)."""
    if t == 0:
        return 0.0
    evt_value = evt.resources[res.id] * evt.length
    free_value = sum(res.value_free[t + j] for j in range(evt.length))
    return float(evt_value - free_value)


def generate_meeting_scheduling(
    slots_count: int = 5,
    resources_count: int = 3,
    max_resource_value: int = 10,
    events_count: int = 3,
    max_length_event: int = 2,
    max_resources_event: int = 2,
    penalty: int = 100,
    seed: int = 0,
) -> DCOP:
    """Full PEAV DCOP for a random instance."""
    rng = random.Random(seed)
    slots, events, resources = generate_problem_definition(
        slots_count,
        resources_count,
        max_resource_value,
        events_count,
        max_length_event,
        max_resources_event,
        rng,
    )
    dcop = DCOP(
        f"MeetingScheduling_{slots_count}_{resources_count}_{events_count}",
        "max",
    )

    variables: Dict[Tuple[int, int], Variable] = {}
    agents: List[AgentDef] = []
    for res in resources.values():
        res_vars: Dict[Tuple[int, int], Variable] = {}
        for evt in events.values():
            if res.id not in evt.resources:
                continue
            name = f"v_{res.id:02d}_{evt.id:02d}"
            # domain = start slot; 0 means "not scheduled"; an event of
            # length L can start no later than slots_count - L + 1
            dom = Domain(
                f"d_{name}",
                "time_slot",
                list(range(0, slots_count - evt.length + 2)),
            )
            v = Variable(name, dom)
            res_vars[(res.id, evt.id)] = v
            dcop.add_variable(v)
        variables.update(res_vars)
        agents.append(AgentDef(f"a_{res.id}"))

        # intra-agent constraints: conflicts + utilities (reference :503)
        keys = sorted(res_vars)
        n_evts = len(keys)
        for (r1, e1), (r2, e2) in itertools.combinations(keys, 2):
            v1, v2 = res_vars[(r1, e1)], res_vars[(r2, e2)]
            evt1, evt2 = events[e1], events[e2]
            table = np.zeros((len(v1.domain), len(v2.domain)))
            for i1, t1 in enumerate(v1.domain.values):
                for i2, t2 in enumerate(v2.domain.values):
                    overlap = (
                        t1 != 0
                        and t2 != 0
                        and (
                            t1 <= t2 <= t1 + evt1.length - 1
                            or t2 <= t1 <= t2 + evt2.length - 1
                        )
                    )
                    if overlap:
                        table[i1, i2] = -penalty
                    else:
                        table[i1, i2] = (
                            _value_for_event(res, evt1, t1)
                            + _value_for_event(res, evt2, t2)
                        ) / (n_evts - 1)
            dcop.add_constraint(
                NAryMatrixRelation(
                    [v1, v2], table, name=f"ci_{v1.name}_{v2.name}"
                )
            )
        if n_evts == 1:
            # single event: carry its utility as a unary constraint
            (rid, eid), v = next(iter(res_vars.items()))
            evt = events[eid]
            table = np.array(
                [
                    _value_for_event(res, evt, t)
                    for t in v.domain.values
                ]
            )
            dcop.add_constraint(
                NAryMatrixRelation([v], table, name=f"cu_{v.name}")
            )

    # inter-agent constraints: all resources of an event must agree on its
    # start slot (reference :588-600)
    for evt in events.values():
        for r1, r2 in itertools.combinations(sorted(evt.resources), 2):
            v1 = variables[(r1, evt.id)]
            v2 = variables[(r2, evt.id)]
            table = np.zeros((len(v1.domain), len(v2.domain)))
            for i1, t1 in enumerate(v1.domain.values):
                for i2, t2 in enumerate(v2.domain.values):
                    if t1 != t2:
                        table[i1, i2] = -penalty
            dcop.add_constraint(
                NAryMatrixRelation(
                    [v1, v2], table, name=f"ce_{v1.name}_{v2.name}"
                )
            )

    dcop.add_agents(agents)
    return dcop
