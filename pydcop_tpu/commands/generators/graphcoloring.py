"""Graph-coloring benchmark problem generator.

Workload parity with /root/reference/pydcop/commands/generators/
graphcoloring.py (generate:238, random/scalefree/grid graphs :310-353,
soft/hard constraints :355-405): same problem families, same knobs.

TPU-first addition: an *array-level* generator (``generate_coloring_arrays``)
that lowers straight to the compiled representation without building python
Constraint objects — required for the 100k-variable BASELINE configs where
object construction alone would dominate runtime.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ...compile.core import CompiledDCOP
from ...compile.direct import compile_from_edges
from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import NAryMatrixRelation

__all__ = [
    "random_edges",
    "scale_free_edges",
    "grid_edges",
    "generate_graph_coloring",
    "generate_coloring_arrays",
]


def random_edges(
    n: int, p_edge: float, rng: np.random.Generator
) -> np.ndarray:
    """Erdos-Renyi G(n, p) edge list [n_e, 2] (i < j)."""
    n_pairs = n * (n - 1) // 2
    if n <= 4096:
        i, j = np.triu_indices(n, k=1)
        keep = rng.random(i.shape[0]) < p_edge
        return np.stack([i[keep], j[keep]], axis=1).astype(np.int32)
    # large n: materializing all O(n^2) pairs is infeasible — draw the edge
    # count from Binomial(n_pairs, p) and sample that many distinct pairs
    n_edges = int(rng.binomial(n_pairs, p_edge))
    picked: set = set()
    while len(picked) < n_edges:
        need = n_edges - len(picked)
        a = rng.integers(0, n, 2 * need)
        b = rng.integers(0, n, 2 * need)
        lo, hi = np.minimum(a, b), np.maximum(a, b)
        for x, y in zip(lo[lo != hi], hi[lo != hi]):
            picked.add((int(x), int(y)))
            if len(picked) == n_edges:
                break
    return np.asarray(sorted(picked), dtype=np.int32).reshape(-1, 2)


def scale_free_edges(
    n: int, m: int, rng: np.random.Generator
) -> np.ndarray:
    """Barabasi-Albert preferential attachment: each new node attaches to
    ``m`` existing nodes with probability proportional to degree (the
    reference uses networkx.barabasi_albert_graph, graphcoloring.py:322)."""
    if n <= m:
        raise ValueError(f"scale-free graph needs n > m (got n={n}, m={m})")
    # repeated-nodes trick: sample attachment targets from a list where each
    # node appears once per unit of degree
    targets = list(range(m))
    repeated: List[int] = []
    edges = np.empty((m * (n - m), 2), dtype=np.int32)
    k = 0
    for src in range(m, n):
        for dst in targets:
            edges[k, 0] = dst
            edges[k, 1] = src
            k += 1
        repeated.extend(targets)
        repeated.extend([src] * m)
        # next targets: m distinct degree-weighted picks
        picks = set()
        while len(picks) < m:
            picks.add(repeated[int(rng.integers(len(repeated)))])
        targets = list(picks)
    return edges[:k]


def grid_edges(side: int) -> np.ndarray:
    """4-neighborhood grid lattice (side x side), as in the reference's
    grid graph (graphcoloring.py:341-353) and ising generator."""
    idx = np.arange(side * side).reshape(side, side)
    right = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    down = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    return np.concatenate([right, down]).astype(np.int32)


def _coloring_table(n_colors: int, hard: bool) -> np.ndarray:
    """Cost table for one edge: equal colors cost 1 (soft) or inf (hard),
    as in the reference (graphcoloring.py:355-405); random unary
    preferences are added by the caller in soft mode."""
    # np.where, not eye * inf: 0 * inf is NaN
    return np.where(
        np.eye(n_colors, dtype=bool), np.inf if hard else 1.0, 0.0
    )


def _build_edges(
    n: int,
    graph: str,
    p_edge: Optional[float],
    m_edge: Optional[int],
    rng: np.random.Generator,
) -> np.ndarray:
    if graph == "random":
        return random_edges(n, p_edge if p_edge is not None else 0.1, rng)
    if graph == "scalefree":
        return scale_free_edges(n, m_edge if m_edge is not None else 2, rng)
    if graph == "grid":
        side = int(round(n ** 0.5))
        if side * side != n:
            raise ValueError(
                f"grid graphs need a square variable count, got {n}"
            )
        return grid_edges(side)
    raise ValueError(f"unknown graph model {graph!r}")


def _connect_isolated(
    edges: np.ndarray, n: int, rng: np.random.Generator
) -> np.ndarray:
    """Attach every zero-degree variable to a random partner, like the
    reference's is_connected retry loop (graphcoloring.py:310)."""
    present = np.zeros(n, dtype=bool)
    present[edges.ravel()] = True
    missing = np.nonzero(~present)[0]
    if missing.size:
        partners = rng.integers(0, n - 1, missing.size)
        partners = partners + (partners >= missing)
        extra = np.stack(
            [missing.astype(np.int32), partners.astype(np.int32)], axis=1
        )
        edges = np.concatenate([edges, extra])
    return edges


def generate_graph_coloring(
    variables_count: int,
    colors_count: int,
    graph: str = "random",
    p_edge: Optional[float] = None,
    m_edge: Optional[int] = None,
    soft: bool = True,
    extensive: bool = False,
    noise_level: float = 0.02,
    seed: Optional[int] = None,
    allow_subgraph: bool = False,
    n_agents: Optional[int] = None,
) -> DCOP:
    """Object-level generator (YAML-able DCOP), reference generate:238.

    Soft problems add random unary preference costs scaled by
    ``noise_level``; hard problems make equal colors infeasible.
    """
    rng = np.random.default_rng(seed)
    edges = _build_edges(variables_count, graph, p_edge, m_edge, rng)
    if not allow_subgraph and variables_count > 1:
        edges = _connect_isolated(edges, variables_count, rng)

    dom = Domain("colors", "d", list(range(colors_count)))
    dcop = DCOP(f"graph_coloring_{variables_count}", objective="min")
    variables = []
    for i in range(variables_count):
        v = Variable(f"v{i:05d}", dom)
        variables.append(v)
        dcop.add_variable(v)

    table = _coloring_table(colors_count, hard=not soft)
    for k, (i, j) in enumerate(edges):
        c = NAryMatrixRelation(
            [variables[i], variables[j]],
            table,
            name=f"cost_{k}",
        )
        dcop.add_constraint(c)

    if soft and noise_level:
        for i, v in enumerate(variables):
            prefs = rng.random(colors_count) * noise_level
            c = NAryMatrixRelation([v], prefs, name=f"pref_{i}")
            dcop.add_constraint(c)

    if n_agents is None:
        n_agents = variables_count
    dcop.add_agents(
        [AgentDef(f"a{a:05d}", capacity=100) for a in range(n_agents)]
    )
    return dcop


def generate_coloring_arrays(
    variables_count: int,
    colors_count: int,
    graph: str = "scalefree",
    p_edge: Optional[float] = None,
    m_edge: Optional[int] = None,
    soft: bool = True,
    noise_level: float = 0.02,
    seed: Optional[int] = None,
) -> CompiledDCOP:
    """Array-level generator: straight to CompiledDCOP, no python objects.
    Same problem distribution as ``generate_graph_coloring``."""
    rng = np.random.default_rng(seed)
    edges = _build_edges(variables_count, graph, p_edge, m_edge, rng)
    if variables_count > 1:
        edges = _connect_isolated(edges, variables_count, rng)
    table = np.where(
        np.eye(colors_count, dtype=bool),
        np.float32(1.0 if soft else 1e9),
        np.float32(0.0),
    )
    unary = (
        rng.random((variables_count, colors_count)).astype(np.float32)
        * noise_level
        if soft and noise_level
        else None
    )
    return compile_from_edges(
        variables_count, colors_count, edges, table, unary=unary
    )
