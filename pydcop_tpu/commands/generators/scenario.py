"""Scenario generator: random agent-removal event streams.

Workload parity with /root/reference/pydcop/commands/generators/scenario.py
(generate_scenario:166): an initial delay, then ``evts_count`` removal events
(each removing ``actions_count`` distinct agents) separated by ``delay``
seconds, and a final delay.
"""

from __future__ import annotations

import random
from typing import List

from ...dcop.scenario import DcopEvent, EventAction, Scenario

__all__ = ["generate_scenario"]


def generate_scenario(
    evts_count: int,
    actions_count: int,
    delay: float,
    initial_delay: float,
    end_delay: float,
    agents: List[str],
    seed: int = 0,
) -> Scenario:
    rng = random.Random(seed)
    remaining = set(agents)
    events: List[DcopEvent] = [DcopEvent("init", delay=initial_delay)]
    for i in range(evts_count):
        if len(remaining) < actions_count:
            break
        removed = rng.sample(sorted(remaining), actions_count)
        remaining.difference_update(removed)
        events.append(
            DcopEvent(
                f"e{i}",
                actions=[
                    EventAction("remove_agent", agent=a) for a in removed
                ],
            )
        )
        if i != evts_count - 1:
            events.append(DcopEvent(f"d{i}", delay=delay))
    events.append(DcopEvent("end", delay=end_delay))
    return Scenario(events)
