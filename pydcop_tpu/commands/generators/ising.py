"""Ising benchmark problem generator.

Workload parity with /root/reference/pydcop/commands/generators/ising.py
(generate_ising:274): periodic 2-D grid of binary variables; each edge gets a
coupling cost drawn U(-bin_range, bin_range) — cost ``J`` when the two spins
agree, ``-J`` when they differ (:362-396); each variable gets a unary field
cost U(-un_range, un_range) — ``h`` for spin 0, ``-h`` for spin 1 (:412-430).
Extensive (cost-table) or intentional (expression) constraints, one agent per
grid cell, optional variable/factor distributions.

TPU-first addition: ``generate_ising_arrays`` lowers the grid straight to the
compiled representation (no python Constraint objects) for the 10k+ variable
BASELINE configs.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ...compile.core import CompiledDCOP
from ...compile.direct import compile_from_edges
from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import NAryMatrixRelation, constraint_from_str

__all__ = ["generate_ising", "generate_ising_arrays", "grid_edges_periodic"]


def grid_edges_periodic(rows: int, cols: int) -> np.ndarray:
    """Edge list of the periodic rows x cols grid: each cell connects to its
    right and down neighbor (wrap-around), like nx.grid_2d_graph(periodic)."""
    r, c = np.meshgrid(np.arange(rows), np.arange(cols), indexing="ij")
    idx = (r * cols + c).ravel()
    right = (r * cols + (c + 1) % cols).ravel()
    down = (((r + 1) % rows) * cols + c).ravel()
    edges = np.concatenate(
        [np.stack([idx, right], 1), np.stack([idx, down], 1)]
    )
    # drop self-loops (1-wide/1-tall grids) and duplicate edges (2x2 wrap)
    edges = edges[edges[:, 0] != edges[:, 1]]
    edges = np.unique(np.sort(edges, axis=1), axis=0)
    return edges.astype(np.int32)


def generate_ising(
    row_count: int,
    col_count: int,
    bin_range: float = 1.6,
    un_range: float = 0.05,
    extensive: bool = True,
    no_agents: bool = False,
    seed: Optional[int] = None,
) -> DCOP:
    """Object-level Ising DCOP (same structure/naming as the reference)."""
    rng = np.random.default_rng(seed)
    domain = Domain("var_domain", "binary", [0, 1])
    dcop = DCOP(
        f"Ising_{row_count}_{col_count}_{bin_range}_{un_range}", "min"
    )

    def vname(r: int, c: int) -> str:
        return f"v_{r}_{c}"

    variables: Dict[str, Variable] = {}
    for r in range(row_count):
        for c in range(col_count):
            v = Variable(vname(r, c), domain)
            variables[v.name] = v
            dcop.add_variable(v)

    # unary field costs (reference :412-430)
    for v in variables.values():
        h = float(rng.uniform(-un_range, un_range))
        if extensive:
            con = NAryMatrixRelation(
                [v], np.array([h, -h]), name=f"cu_{v.name}"
            )
        else:
            con = constraint_from_str(
                f"cu_{v.name}", f"-{h} if {v.name} == 1 else {h}", [v]
            )
        dcop.add_constraint(con)

    # binary couplings on the periodic grid (reference :343-396)
    for r in range(row_count):
        for c in range(col_count):
            for r2, c2 in (
                (r, (c + 1) % col_count),
                ((r + 1) % row_count, c),
            ):
                if (r2, c2) == (r, c):
                    continue
                (ra, ca), (rb, cb) = sorted([(r, c), (r2, c2)])
                name = f"cb_{vname(ra, ca)}_{vname(rb, cb)}"
                if name in dcop.constraints:
                    continue
                j = float(rng.uniform(-bin_range, bin_range))
                va, vb = variables[vname(ra, ca)], variables[vname(rb, cb)]
                if extensive:
                    con = NAryMatrixRelation(
                        [va, vb],
                        np.array([[j, -j], [-j, j]]),
                        name=name,
                    )
                else:
                    con = constraint_from_str(
                        name,
                        f"{j} if {va.name} == {vb.name} else -{j}",
                        [va, vb],
                    )
                dcop.add_constraint(con)

    if not no_agents:
        dcop.add_agents(
            [
                AgentDef(f"a_{r}_{c}")
                for r in range(row_count)
                for c in range(col_count)
            ]
        )
    return dcop


def generate_ising_arrays(
    rows: int,
    cols: int,
    bin_range: float = 1.6,
    un_range: float = 0.05,
    seed: int = 0,
) -> CompiledDCOP:
    """Array-level Ising instance: lowers straight to the compiled
    representation for large grids (10k+ variables)."""
    rng = np.random.default_rng(seed)
    n = rows * cols
    edges = grid_edges_periodic(rows, cols)
    j = rng.uniform(-bin_range, bin_range, edges.shape[0])
    tables = np.empty((edges.shape[0], 2, 2), dtype=np.float32)
    tables[:, 0, 0] = j
    tables[:, 1, 1] = j
    tables[:, 0, 1] = -j
    tables[:, 1, 0] = -j
    h = rng.uniform(-un_range, un_range, n)
    unary = np.stack([h, -h], axis=1).astype(np.float32)
    return compile_from_edges(
        n_vars=n, domain_size=2, edges=edges, table=tables, unary=unary
    )
