"""Mixed hard/soft constraint problem generator.

Workload parity with /root/reference/pydcop/commands/generate.py
(generate_mixed_problem:449): a random problem over one integer domain
``0..range-1`` mixing HARD constraints (infinite cost off a reachable
target) with SOFT ones (weighted distance to a random target), across
three arity regimes —

* arity 1 (:510): one unary constraint per variable,
* arity 2 (:560): constraints are the edges of a connected Erdos-Renyi
  graph; hard edges are disequalities, soft edges penalize the distance
  of the pair sum to a random target,
* arity >= 3 (:617): a random bipartite constraint/variable graph where
  every variable appears in at least one constraint, every constraint
  covers at least one variable and none exceeds ``arity``; constraints
  score a random-weighted sum of their scope against a target.

Deliberate deviations from the reference (documented, not accidental):
hard targets are drawn reachable over the FULL domain (the reference
samples ``range(n-1)``, silently excluding the top value,
generate.py:821); soft costs are ``abs(...)`` in every regime so costs
stay non-negative (the reference's arity-1 soft expression ``w*v - obj``
can go negative); and the hard-constraint count is
``round(proportion * constraint_count)`` in all regimes (the reference
mixes the proportion with a density-derived edge estimate,
generate.py:462, which for arity 1 can silently produce zero hard
constraints).  This is the natural workload for :mod:`..mixeddsa`, which
minimizes violations first and soft cost second.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import constraint_from_str
from .graphcoloring import _connect_isolated, random_edges

__all__ = ["generate_mixed_problem"]


def _weights(rng: np.random.Generator, k: int) -> List[float]:
    """k non-zero weights in (0, 1], rounded like the reference (:602);
    clamped away from 0 so rounding can never make a term a don't-care."""
    return [max(0.01, round(float(w), 2)) for w in 1.0 - rng.random(k)]


def _sum_expr(weights: List[float], names: List[str]) -> str:
    return " + ".join(
        f"{w}*{v}" if w != 1 else v for w, v in zip(weights, names)
    )


def _hard_expr(weights: List[float], names: List[str], values) -> str:
    """Infinite cost unless the weighted sum hits a reachable target."""
    target = round(sum(w * int(v) for w, v in zip(weights, values)), 2)
    return (
        f"0 if abs({_sum_expr(weights, names)} - {target}) < 1e-9 "
        "else float('inf')"
    )


def _soft_expr(
    weights: List[float], names: List[str], rng, domain_range: int
) -> str:
    target = round(float(rng.uniform(0, sum(weights) * (domain_range - 1))), 2)
    return f"abs({_sum_expr(weights, names)} - {target})"


def generate_mixed_problem(
    variable_count: int,
    constraint_count: int,
    hard_proportion: float,
    arity: int = 2,
    domain_range: int = 3,
    density: float = 0.3,
    agents: Optional[int] = None,
    capacity: int = 0,
    seed: Optional[int] = None,
) -> DCOP:
    if not 0 <= hard_proportion <= 1:
        raise ValueError(
            f"hard proportion must be in [0, 1], got {hard_proportion}"
        )
    if arity < 1:
        raise ValueError(f"arity must be at least 1, got {arity}")
    if arity > variable_count:
        raise ValueError(
            f"constraint arity ({arity}) cannot exceed the variable "
            f"count ({variable_count})"
        )
    if constraint_count <= 0:
        raise ValueError(
            f"constraint count must be positive, got {constraint_count}"
        )
    if arity == 1 and constraint_count != variable_count:
        # same rule as the reference (:511): unary constraints pair off
        # one-to-one with variables
        raise ValueError(
            "arity 1 needs exactly one constraint per variable "
            f"(got {constraint_count} constraints, {variable_count} "
            "variables)"
        )

    rng = np.random.default_rng(seed)
    domain = Domain("levels", "level", list(range(domain_range)))
    dcop = DCOP("mixed constraints problem", "min")
    variables: Dict[int, Variable] = {}
    for i in range(variable_count):
        v = Variable(f"v{i}", domain)
        variables[i] = v
        dcop.add_variable(v)

    if arity == 2:
        # constraints are the edges of a connected G(n, p=density) graph;
        # the requested constraint_count is advisory here, like the
        # reference (:562)
        edges = random_edges(variable_count, density, rng)
        edges = _connect_isolated(edges, variable_count, rng)
        scopes = [[int(i), int(j)] for i, j in edges]
        if len(scopes) != constraint_count:
            logging.getLogger("pydcop_tpu.generate").warning(
                "for arity 2 constraints are the edges of the random "
                "graph: the density (%s) produced %s constraints, not "
                "the requested %s",
                density, len(scopes), constraint_count,
            )
    elif arity == 1:
        scopes = [[i] for i in range(variable_count)]
    else:
        scopes = _bipartite_scopes(
            variable_count, constraint_count, arity, density, rng
        )

    n_constraints = len(scopes)
    hard_count = int(round(hard_proportion * n_constraints))
    hard_flags = np.zeros(n_constraints, dtype=bool)
    hard_flags[rng.permutation(n_constraints)[:hard_count]] = True

    for ci, (scope, is_hard) in enumerate(zip(scopes, hard_flags)):
        names = [f"v{i}" for i in scope]
        if arity == 2 and is_hard:
            # hard pair constraints are disequalities (reference :607) —
            # the graph-coloring flavor of "mixed"
            expr = f"0 if {names[0]} != {names[1]} else float('inf')"
        else:
            ws = _weights(rng, len(scope))
            if is_hard:
                reachable = rng.integers(0, domain_range, len(scope))
                expr = _hard_expr(ws, names, reachable)
            else:
                expr = _soft_expr(ws, names, rng, domain_range)
        dcop.add_constraint(
            constraint_from_str(
                f"c{ci}", expr, [variables[i] for i in scope]
            )
        )

    agents_count = variable_count if agents is None else agents
    if capacity:
        agent_defs = [
            AgentDef(f"a{i}", capacity=capacity) for i in range(agents_count)
        ]
    else:
        agent_defs = [AgentDef(f"a{i}") for i in range(agents_count)]
    dcop.add_agents(agent_defs)
    return dcop


def _bipartite_scopes(
    variable_count: int,
    constraint_count: int,
    arity: int,
    density: float,
    rng: np.random.Generator,
) -> List[List[int]]:
    """Random constraint scopes for arity >= 3 (reference :617-671): the
    density sets the total number of variable->constraint memberships;
    every variable joins at least one constraint, every constraint gets at
    least one variable, and no scope exceeds ``arity`` or repeats a
    variable."""
    max_memberships = constraint_count * arity
    target = int(constraint_count * min(arity, variable_count) * density)
    target = max(target, variable_count, constraint_count)
    if target > max_memberships:
        target = max_memberships
    if variable_count > max_memberships:
        raise ValueError(
            f"{constraint_count} constraints of arity <= {arity} cannot "
            f"cover {variable_count} variables"
        )

    scope_sets: List[set] = [set() for _ in range(constraint_count)]
    # open constraints tracked incrementally — rebuilding candidate lists
    # per placement would be O(constraints * variables) per membership
    open_cs = list(range(constraint_count))

    def _place(c_idx_in_open: int, v: int) -> None:
        c = open_cs[c_idx_in_open]
        scope_sets[c].add(v)
        if len(scope_sets[c]) == arity:  # full: swap-remove from open set
            open_cs[c_idx_in_open] = open_cs[-1]
            open_cs.pop()

    # every variable joins one constraint with room
    for v in rng.permutation(variable_count):
        _place(int(rng.integers(len(open_cs))), int(v))
    # every empty constraint gets one variable
    for c in range(constraint_count):
        if not scope_sets[c]:
            scope_sets[c].add(int(rng.integers(variable_count)))
    # rejection-sample (open constraint, new variable) memberships until the
    # density target is met; when nearly full the retry odds degrade, so cap
    # total attempts and accept coming up slightly short (the reference
    # likewise warns and stops when it runs out of edges, :660)
    placed = sum(len(s) for s in scope_sets)
    attempts = 0
    max_attempts = 50 * max(1, target - placed)
    while placed < target and open_cs and attempts < max_attempts:
        attempts += 1
        i = int(rng.integers(len(open_cs)))
        v = int(rng.integers(variable_count))
        if v in scope_sets[open_cs[i]]:
            continue
        _place(i, v)
        placed += 1
    return [sorted(s) for s in scope_sets]
