"""IoT benchmark generator: power-law constraint graphs with route and
hosting costs.

Workload parity with /root/reference/pydcop/commands/generators/iot.py
(generate_iot:74, generate_powerlaw_var_constraints:169): a Barabasi-Albert
constraint graph of ``num`` variables (random binary cost tables over
``range``), one agent per variable with capacity derived from the maxsum
footprint, hosting costs preferring the own variable and route costs derived
from the factor graph, plus an initial variable distribution.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ...dcop.dcop import DCOP
from ...dcop.objects import AgentDef, Domain, Variable
from ...dcop.relations import NAryMatrixRelation
from .graphcoloring import scale_free_edges

__all__ = ["generate_powerlaw_var_constraints", "generate_iot"]


def generate_powerlaw_var_constraints(
    num_var: int, domain_size: int, constraint_range: int, seed: int = 0
) -> Tuple[Dict[str, Variable], Dict[str, NAryMatrixRelation], Domain]:
    """Barabasi-Albert (m=2) graph; each edge a random cost table drawn
    uniformly in [0, constraint_range) (reference iot.py:169-224)."""
    rng = np.random.default_rng(seed)
    edges = scale_free_edges(num_var, 2, rng)
    domain = Domain("d", "d", list(range(domain_size)))
    variables = {
        f"v{i:03d}": Variable(f"v{i:03d}", domain) for i in range(num_var)
    }
    constraints: Dict[str, NAryMatrixRelation] = {}
    for i, j in edges:
        v1, v2 = variables[f"v{int(i):03d}"], variables[f"v{int(j):03d}"]
        table = rng.integers(
            0, constraint_range, (domain_size, domain_size)
        ).astype(float)
        c = NAryMatrixRelation(
            [v1, v2], table, name=f"c{int(i):03d}_{int(j):03d}"
        )
        constraints[c.name] = c
    return variables, constraints, domain


def generate_iot(
    num: int = 30,
    domain_size: int = 10,
    constraint_range: int = 100,
    seed: int = 0,
):
    """Full IoT instance: DCOP + agents with capacity/hosting/route costs +
    the initial variable-to-own-agent distribution (reference iot.py:74-163).

    Returns (dcop, distribution_mapping).
    """
    from ...algorithms import maxsum as maxsum_module
    from ...computations_graph import factor_graph

    variables, constraints, domain = generate_powerlaw_var_constraints(
        num, domain_size, constraint_range, seed
    )
    dcop = DCOP("iot", "min")
    for v in variables.values():
        dcop.add_variable(v)
    for c in constraints.values():
        dcop.add_constraint(c)

    cg = factor_graph.build_computation_graph(dcop)
    footprints = {
        n.name: maxsum_module.computation_memory(n) for n in cg.nodes
    }

    agents: List[AgentDef] = []
    mapping: Dict[str, List[str]] = {}
    var_nodes = [n for n in cg.nodes if n.type == "VariableComputation"]
    for node in var_nodes:
        a_name = f"a{node.name[1:]}"
        # prefer hosting the own variable (cost 0) and its factors (cost 1)
        hosting_costs = {node.name: 0.0}
        for neigh in node.neighbors:
            hosting_costs[neigh] = 1.0
        # route costs: cheap to agents of neighbor computations
        routes = {}
        for neigh in node.neighbors:
            for nn in cg.computation(neigh).neighbors:
                if nn != node.name:
                    routes[f"a{nn[1:]}"] = 0.5
        agents.append(
            AgentDef(
                a_name,
                capacity=footprints[node.name] * 100,
                default_hosting_cost=10,
                hosting_costs=hosting_costs,
                default_route=1,
                routes=routes,
            )
        )
        mapping[a_name] = [node.name]
    dcop.add_agents(agents)

    # distribute factor computations greedily on the agents, cheapest
    # (hosting + capacity-feasible) first — reference distribute_factors
    factor_nodes = [n for n in cg.nodes if n.type == "FactorComputation"]
    used = {a.name: footprints[mapping[a.name][0]] for a in agents}
    agent_by_name = {a.name: a for a in agents}
    for node in sorted(
        factor_nodes, key=lambda n: -footprints[n.name]
    ):
        best, best_cost = None, float("inf")
        for a in agents:
            if used[a.name] + footprints[node.name] > a.capacity:
                continue
            cost = agent_by_name[a.name].hosting_cost(node.name)
            if cost < best_cost:
                best, best_cost = a.name, cost
        if best is None:
            best = min(used, key=used.get)
        mapping[best].append(node.name)
        used[best] += footprints[node.name]
    return dcop, mapping
