"""``pydcop_tpu fleet``: the graftfleet federation plane.

No reference counterpart — the reference's orchestrator polls its own
agents' metrics (PAPER.md §5.4); this verb is the TPU-native fleet
version: a :class:`~pydcop_tpu.telemetry.federate.FleetCollector`
polling N worker endpoints (``/metrics.json`` + ``/status``) and
re-serving the merged, ``worker=``-labeled registry on its own
graftwatch surface:

- ``GET /metrics``       federated series, classic Prometheus text or
  OpenMetrics by the usual Accept negotiation (prom.py);
- ``GET /metrics.json``  the federated snapshot document;
- ``GET /status`` and ``GET /fleet/status``  the per-worker table
  (up/down, scrape age, queue depth + watermark, solves + solves/s,
  batch occupancy, pulse digest, burn rate) ``watch --fleet`` renders;
- ``GET /fleet/slo``     the fleet SLO report (with ``--slo``).

Targets come from positional ``URL`` / ``NAME=URL`` args, ``--fleet-file
YAML``, or ``--manifest`` pointing at graftdur ``fleet-manifest.json``
files (or a directory of per-worker state dirs).  ``--slo`` /
``--slo-file`` attach fleet-wide SLOs: the same objective grammar as
``serve --slo``, evaluated per worker AND fleet-aggregate over the
federated ``slo.events`` counters; fleet alerts name the worst worker.

Host-only: never touches a device backend — safe next to a TPU fleet.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Any, Dict

from ._utils import write_output

logger = logging.getLogger("pydcop_tpu.cli.fleet")


def set_parser(subparsers) -> None:
    parser = subparsers.add_parser(
        "fleet",
        help="federate worker metrics into one fleet surface (graftfleet)",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument(
        "targets", nargs="*", default=[], metavar="URL",
        help="worker endpoints: URL or NAME=URL (composes with "
        "--fleet-file / --manifest)",
    )
    parser.add_argument(
        "--fleet-file", default=None, metavar="FILE",
        help="YAML fleet file with a workers: section (name -> url)",
    )
    parser.add_argument(
        "--manifest", default=None, metavar="PATH",
        help="graftdur fleet-manifest.json (or a directory searched for "
        "them): workers federate from their recorded endpoints",
    )
    parser.add_argument(
        "--port", type=int, default=9020,
        help="HTTP port of the fleet surface (default 9020; 0 = "
        "ephemeral, printed on stdout)",
    )
    parser.add_argument(
        "--host", default="127.0.0.1", help="bind address"
    )
    parser.add_argument(
        "--interval", type=float, default=1.0,
        help="seconds between worker scrapes (default 1.0)",
    )
    parser.add_argument(
        "--stale-after", type=float, default=10.0,
        help="drop a dead worker's series after this many seconds "
        "without a successful scrape (default 10)",
    )
    parser.add_argument(
        "--slo", action="append", default=[], metavar="SPEC",
        help="fleet SLO objective (repeatable, serve --slo grammar): "
        "evaluated per worker and fleet-aggregate over federated "
        "slo.events; fleet alerts name the worst worker",
    )
    parser.add_argument(
        "--slo-file", default=None, metavar="FILE",
        help="YAML file of objectives (serve --slo-file format); "
        "composes with --slo",
    )
    parser.add_argument(
        "--duration", type=float, default=None,
        help="federate for this many seconds, then exit "
        "(default: until SIGINT/SIGTERM)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="poll every worker once, print the fleet status JSON, exit "
        "(non-zero when every worker is down)",
    )


def _collect_targets(args):
    from ..telemetry.federate import (
        targets_from_args,
        targets_from_fleet_file,
        targets_from_manifest,
    )

    targets = list(targets_from_args(args.targets))
    if args.fleet_file:
        targets += targets_from_fleet_file(args.fleet_file)
    if args.manifest:
        targets += targets_from_manifest(args.manifest)
    return targets


def run_cmd(args, timeout: float = None) -> int:
    import sys

    if timeout and not args.duration:
        args.duration = max(1.0, timeout - 5.0)
    from ..telemetry.federate import FleetCollector, FleetSlo

    try:
        targets = _collect_targets(args)
        if not targets:
            print(
                "error: no fleet targets — give worker URLs, "
                "--fleet-file or --manifest", file=sys.stderr,
            )
            return 2
        collector = FleetCollector(
            targets,
            interval_s=args.interval,
            stale_after_s=args.stale_after,
        )
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    fleet_slo = None
    if args.slo or args.slo_file:
        from ..telemetry.slo import load_slo_file, parse_objective

        objectives, options = (
            load_slo_file(args.slo_file) if args.slo_file else ([], {})
        )
        objectives += [parse_objective(s) for s in args.slo]
        options.pop("eval_interval_s", None)  # ticks ride the poll loop
        fleet_slo = FleetSlo(collector, objectives, **options)
        for o in objectives:
            logger.warning("fleet slo objective: %s = %s", o.name, o.describe())

    if args.once:
        collector.poll()
        if fleet_slo is not None:
            fleet_slo.evaluate()
        status = collector.status()
        if fleet_slo is not None:
            status["slo"] = fleet_slo.status_block()
        write_output(args, status)
        return 0 if status["workers_up"] > 0 else 1

    def _status() -> Dict[str, Any]:
        status = collector.status()
        if fleet_slo is not None:
            status["slo"] = fleet_slo.status_block()
        return status

    def _snapshot() -> Dict[str, Any]:
        snap = collector.snapshot()
        if fleet_slo is not None:
            snap["metrics"].update(fleet_slo.metrics_block())
        return snap

    def _http_fleet_status(path: str, body: bytes):
        return 200, _status()

    def _http_fleet_slo(path: str, body: bytes):
        if fleet_slo is None:
            return 404, {"error": "no fleet SLOs configured"}
        return 200, fleet_slo.status_block()

    from ..infrastructure.ui import MetricsHttpServer

    http = MetricsHttpServer(
        port=args.port,
        host=args.host,
        status_cb=_status,
        snapshot_cb=_snapshot,
        routes={
            ("GET", "/fleet/status"): _http_fleet_status,
            ("GET", "/fleet/slo"): _http_fleet_slo,
        },
    )
    # machine-parseable like serve's SERVE_PORT= (tools/fleet_smoke.py)
    print(f"FLEET_PORT={http.port}", flush=True)
    logger.warning(
        "fleet surface on http://%s:%s (%d worker(s), %.1fs interval)",
        args.host, http.port, len(targets), args.interval,
    )
    collector.start(
        on_tick=(fleet_slo.evaluate if fleet_slo is not None else None)
    )
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    deadline = (
        time.monotonic() + args.duration
        if args.duration is not None else None
    )
    while not stop.is_set():
        if deadline is not None and time.monotonic() >= deadline:
            break
        stop.wait(0.2)
    collector.stop()
    http.shutdown()
    status = _status()
    payload: Dict[str, Any] = {
        "workers_total": status["workers_total"],
        "workers_up": status["workers_up"],
        "fleet": status["fleet"],
        "workers": status["workers"],
    }
    if fleet_slo is not None:
        payload["slo"] = status["slo"]
    write_output(args, payload)
    return 0
